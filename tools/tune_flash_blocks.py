"""Tune the Pallas flash-attention block sizes on live hardware.

Runs the winning bench candidate once per block-shape point, each in a
killable subprocess (``bench._run_one_subproc``) with the
``DLROVER_TPU_FLASH_*`` env overrides set, and reports step times.  The
winner goes into ``ops/flash_attention.py``'s defaults (VERDICT r3 next
#1: "tune DEFAULT_BWD_BLOCK_* on the winner").

Run on the chip:  python tools/tune_flash_blocks.py [--model 300m_h128]
Writes FLASH_TUNE.json next to bench.py as points complete.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def candidate_spec(model: str) -> dict:
    from dlrover_tpu.models import llama

    if model == "300m_h128":
        cfg = dataclasses.replace(
            llama.LlamaConfig.small_300m(), n_head=8, n_kv_head=8
        )
        batch = 8
    elif model == "800m_h128":
        cfg = dataclasses.replace(
            llama.LlamaConfig.medium_800m(), n_head=12, n_kv_head=12,
        )
        batch = 8
    else:
        raise SystemExit(f"unknown --model {model}")
    return {
        "model": f"llama_{model}", "batch": batch, "seq": 2048,
        "remat": "none" if model == "300m_h128" else "block",
        "iters": 3, "opt": "adamw", "fp8": False,
        "cfg": {
            k: v for k, v in cfg.__dict__.items()
            if isinstance(v, (int, float, str, bool))
        },
    }


# (fwd_q, fwd_k, bwd_q, bwd_k, ce_chunk_rows) — first point is the
# current default.  The last entries hold flash blocks at default and
# sweep the fused lm-head CE chunking instead (the other hot kernel:
# ~20% of 300m FLOPs live in the lm-head GEMM inside a lax.scan).
GRID = [
    (512, 512, 256, 512, 1024),
    (512, 512, 512, 512, 1024),
    (512, 512, 256, 256, 1024),
    (512, 512, 128, 512, 1024),
    (512, 512, 512, 256, 1024),
    (1024, 512, 256, 512, 1024),
    (256, 512, 256, 512, 1024),
    (512, 256, 256, 512, 1024),
    (1024, 1024, 512, 512, 1024),
    (512, 512, 256, 512, 2048),
    (512, 512, 256, 512, 4096),
    (512, 512, 256, 512, 512),
]


def main() -> int:
    import bench

    model = "300m_h128"
    if "--model" in sys.argv:
        model = sys.argv[sys.argv.index("--model") + 1]
    spec = candidate_spec(model)
    out_path = os.path.join(REPO, "FLASH_TUNE.json")
    results = []
    for fq, fk, bq, bk, ce in GRID:
        os.environ["DLROVER_TPU_FLASH_BLOCK_Q"] = str(fq)
        os.environ["DLROVER_TPU_FLASH_BLOCK_K"] = str(fk)
        os.environ["DLROVER_TPU_FLASH_BWD_BLOCK_Q"] = str(bq)
        os.environ["DLROVER_TPU_FLASH_BWD_BLOCK_K"] = str(bk)
        os.environ["DLROVER_TPU_CE_CHUNK_ROWS"] = str(ce)
        label = f"fwd{fq}x{fk}_bwd{bq}x{bk}_ce{ce}"
        try:
            res = bench._run_one_subproc(spec, label, 900.0)
            entry = {
                "blocks": [fq, fk, bq, bk], "ce_chunk_rows": ce,
                "step_time_s": round(res["dt"], 4),
            }
        except Exception as e:  # noqa: BLE001
            entry = {
                "blocks": [fq, fk, bq, bk], "ce_chunk_rows": ce,
                "error": f"{type(e).__name__}: {str(e)[:160]}",
            }
        print(f"{label}: {entry}", file=sys.stderr)
        results.append(entry)
        with open(out_path, "w") as f:
            json.dump({"model": model, "points": results}, f, indent=1)
    ok = [r for r in results if "step_time_s" in r]
    if ok:
        best = min(ok, key=lambda r: r["step_time_s"])
        print(json.dumps({"best": best, "model": model}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
