"""Tune the Pallas flash-attention block sizes on live hardware.

Runs the winning bench candidate once per block-shape point, each in a
killable subprocess (``bench._run_one_subproc``) with the
``DLROVER_TPU_FLASH_*`` env overrides set, and reports step times.  The
winner goes into ``ops/flash_attention.py``'s defaults (VERDICT r3 next
#1: "tune DEFAULT_BWD_BLOCK_* on the winner").

Hardened after the r4 live session:
- RESUMES from an existing FLASH_TUNE.json (points already measured are
  skipped) — a wedged tunnel costs the remaining points, not the data.
- ABORTS after 2 consecutive timeouts (the backend is gone; burning
  900 s per remaining grid point blocks the rest of the session queue).
- bwd_q=128 is OUT of the grid: its execution wedged the device tunnel
  mid-session (and 128-wide blocks measured ~5% of peak in round 1
  anyway — it could never have won).

Run on the chip:  python tools/tune_flash_blocks.py [--model 300m_h128]
Writes FLASH_TUNE.json next to bench.py as points complete.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def candidate_spec(model: str) -> dict:
    from dlrover_tpu.models import llama

    if model == "300m_h128":
        cfg = dataclasses.replace(
            llama.LlamaConfig.small_300m(), n_head=8, n_kv_head=8
        )
        batch = 8
    elif model == "800m_h128":
        cfg = dataclasses.replace(
            llama.LlamaConfig.medium_800m(), n_head=12, n_kv_head=12,
        )
        batch = 8
    else:
        raise SystemExit(f"unknown --model {model}")
    return {
        "model": f"llama_{model}", "batch": batch, "seq": 2048,
        "remat": "none" if model == "300m_h128" else "block",
        "iters": 3, "opt": "adamw", "fp8": False,
        "cfg": {
            k: v for k, v in cfg.__dict__.items()
            if isinstance(v, (int, float, str, bool))
        },
    }


# (fwd_q, fwd_k, bwd_q, bwd_k, ce_chunk_rows) — first point is the
# current default.  The last entries hold flash blocks at default and
# sweep the fused lm-head CE chunking instead (the other hot kernel:
# ~20% of 300m FLOPs live in the lm-head GEMM inside a lax.scan).
GRID = [
    (512, 512, 256, 512, 1024),
    (1024, 512, 256, 512, 1024),
    (256, 512, 256, 512, 1024),
    (512, 256, 256, 512, 1024),
    (512, 512, 256, 512, 2048),
    (512, 512, 256, 512, 4096),
    (512, 512, 256, 512, 512),
    (512, 512, 512, 512, 1024),
    (512, 512, 256, 256, 1024),
    (512, 512, 512, 256, 1024),
    (1024, 1024, 512, 512, 1024),
]


def main() -> int:
    import bench

    model = "300m_h128"
    if "--model" in sys.argv:
        model = sys.argv[sys.argv.index("--model") + 1]
    spec = candidate_spec(model)
    out_path = os.path.join(REPO, "FLASH_TUNE.json")
    MAX_ATTEMPTS = 2
    results: list = []
    done: set = set()
    attempts: dict = {}
    try:
        with open(out_path) as f:
            prev = json.load(f)
        if prev.get("model") == model:
            for p in prev.get("points", []):
                # (.get: a pre-hardening artifact may lack ce_chunk_rows
                # — treat those as stale and re-measure)
                if "ce_chunk_rows" not in p:
                    continue
                key = (tuple(p["blocks"]), p["ce_chunk_rows"])
                if "step_time_s" in p:
                    # keep measured points
                    results.append(p)
                    done.add(key)
                elif p.get("attempts", 1) >= MAX_ATTEMPTS:
                    # A point that keeps erroring/timing out counts as
                    # permanently failed — it must not block the grid's
                    # "complete" flag forever (the watcher would re-burn
                    # 2x600s every cycle and never reach its terminal
                    # state).
                    results.append(p)
                    done.add(key)
                else:
                    # Pending retry: STAY in results so the attempt
                    # count survives an interruption before the retry
                    # lands (it is replaced in place when re-measured);
                    # dropping it would reset the counter every cycle
                    # and the permanent-failure cap could never fire.
                    results.append(p)
                    attempts[key] = p.get("attempts", 1)
    except (OSError, ValueError):
        pass
    if results:
        print(f"resuming: {len(results)} measured points kept",
              file=sys.stderr)
    consecutive_timeouts = 0
    for fq, fk, bq, bk, ce in GRID:
        if ((fq, fk, bq, bk), ce) in done:
            continue
        if consecutive_timeouts >= 2:
            print("2 consecutive timeouts — backend presumed wedged, "
                  "aborting sweep", file=sys.stderr)
            break
        os.environ["DLROVER_TPU_FLASH_BLOCK_Q"] = str(fq)
        os.environ["DLROVER_TPU_FLASH_BLOCK_K"] = str(fk)
        os.environ["DLROVER_TPU_FLASH_BWD_BLOCK_Q"] = str(bq)
        os.environ["DLROVER_TPU_FLASH_BWD_BLOCK_K"] = str(bk)
        os.environ["DLROVER_TPU_CE_CHUNK_ROWS"] = str(ce)
        label = f"fwd{fq}x{fk}_bwd{bq}x{bk}_ce{ce}"
        try:
            res = bench._run_one_subproc(spec, label, 600.0)
            entry = {
                "blocks": [fq, fk, bq, bk], "ce_chunk_rows": ce,
                "step_time_s": round(res["dt"], 4),
            }
            consecutive_timeouts = 0
        except TimeoutError as e:
            entry = {
                "blocks": [fq, fk, bq, bk], "ce_chunk_rows": ce,
                "error": f"TimeoutError: {str(e)[:160]}",
                "attempts": attempts.get(((fq, fk, bq, bk), ce), 0) + 1,
            }
            consecutive_timeouts += 1
        except Exception as e:  # noqa: BLE001
            entry = {
                "blocks": [fq, fk, bq, bk], "ce_chunk_rows": ce,
                "error": f"{type(e).__name__}: {str(e)[:160]}",
                "attempts": attempts.get(((fq, fk, bq, bk), ce), 0) + 1,
            }
            consecutive_timeouts = 0
        print(f"{label}: {entry}", file=sys.stderr)
        # Replace a carried pending-retry entry for this key in place;
        # append otherwise.
        for i, p in enumerate(results):
            if (tuple(p["blocks"]), p["ce_chunk_rows"]) == (
                (fq, fk, bq, bk), ce
            ):
                results[i] = entry
                break
        else:
            results.append(entry)
        with open(out_path, "w") as f:
            json.dump({"model": model, "points": results}, f, indent=1)
    # A point is settled when measured OR permanently failed (attempt
    # cap hit); only settled-everywhere marks the grid complete.
    settled = {
        (tuple(r["blocks"]), r["ce_chunk_rows"])
        for r in results
        if "step_time_s" in r or r.get("attempts", 0) >= MAX_ATTEMPTS
    }
    complete = all(((fq, fk, bq, bk), ce) in settled
                   for fq, fk, bq, bk, ce in GRID)
    with open(out_path, "w") as f:
        json.dump({"model": model, "points": results,
                   "complete": complete}, f, indent=1)
    ok = [r for r in results if "step_time_s" in r]
    if ok:
        best = min(ok, key=lambda r: r["step_time_s"])
        print(json.dumps({"best": best, "model": model}))
    # Non-zero on a wedge-abort so the watcher re-probes the tunnel
    # instead of marching into the next (doomed) stage.
    return 2 if consecutive_timeouts >= 2 else 0


if __name__ == "__main__":
    sys.exit(main())
