"""Calibrate the static HBM estimator against XLA's compiled truth.

For each (model, strategy) point this AOT-compiles the real train step
on the 8-device virtual CPU mesh (``accelerate.aot_analyze`` — no state
is materialized, so models far bigger than host RAM are fine) and
compares ``strategy_search.estimate_step_hbm_bytes`` with the peak
bytes XLA's buffer assignment reports (``compiled.memory_analysis()``).

This keeps the BO search's memory pruning honest before it faces real
HBM (VERDICT r3 next #8; the dryrun-scoring role of the reference's
``atorch/auto/engine/sg_algo/bayes_opt_sg.py``).  The resulting
calibration table lives in NOTES.md; ``tests/test_strategy_search.py``
asserts the error bound on a fast subset.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/calibrate_hbm.py [--fast]
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time


def points(fast: bool = False):
    """(label, cfg, batch, seq, strategy) calibration matrix."""
    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.accelerate import Strategy
    from dlrover_tpu.parallel.mesh import MeshSpec

    m300 = llama.LlamaConfig.small_300m()
    m300h = dataclasses.replace(m300, n_head=8, n_kv_head=8)
    m800 = llama.LlamaConfig.medium_800m()
    pts = [
        # llama_300m family: the bench sweep's shapes.
        ("300m dp8 none", m300, 8, 2048, Strategy(mesh=MeshSpec(dp=8))),
        ("300m dp8 block", dataclasses.replace(m300, remat_block=True),
         8, 2048, Strategy(mesh=MeshSpec(dp=8))),
        ("300m dp8 dots", m300, 8, 2048,
         Strategy(mesh=MeshSpec(dp=8), remat="dots")),
        ("300m dp8 full", m300, 8, 2048,
         Strategy(mesh=MeshSpec(dp=8), remat="full")),
        ("300m dp8 accum4", m300, 8, 2048,
         Strategy(mesh=MeshSpec(dp=8), grad_accum=4)),
        ("300m dp2xfsdp4 none", m300, 8, 2048,
         Strategy(mesh=MeshSpec(dp=2, fsdp=4))),
        ("300m fsdp8 block",
         dataclasses.replace(m300, remat_block=True), 8, 2048,
         Strategy(mesh=MeshSpec(fsdp=8))),
        ("300m_h128 dp8 none", m300h, 8, 2048,
         Strategy(mesh=MeshSpec(dp=8))),
        ("300m b16 dp8 block",
         dataclasses.replace(m300, remat_block=True), 16, 2048,
         Strategy(mesh=MeshSpec(dp=8))),
    ]
    if not fast:
        m800b = dataclasses.replace(m800, remat_block=True)
        pts += [
            ("800m dp8 block", m800b, 8, 2048,
             Strategy(mesh=MeshSpec(dp=8))),
            ("800m fsdp8 block", m800b, 8, 2048,
             Strategy(mesh=MeshSpec(fsdp=8))),
            ("800m fsdp8 b16 block", m800b, 16, 2048,
             Strategy(mesh=MeshSpec(fsdp=8))),
            ("800m dp2xfsdp2xtp2 block", m800b, 8, 2048,
             Strategy(mesh=MeshSpec(dp=2, fsdp=2, tp=2))),
            ("800m fsdp8 b16 accum4", m800b, 16, 2048,
             Strategy(mesh=MeshSpec(fsdp=8), grad_accum=4)),
        ]
    return pts


def measure_point(label, cfg, batch, seq, strategy):
    """Returns (predicted_bytes, actual_peak_bytes, compile_s)."""
    import numpy as np

    import jax
    import optax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.accelerate import aot_analyze
    from dlrover_tpu.parallel.strategy_search import (
        estimate_step_hbm_bytes,
    )

    sample = {
        "tokens": np.zeros((batch, seq + 1), np.int32)
    }
    t0 = time.perf_counter()
    job = aot_analyze(
        loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
        init_fn=lambda r: llama.init_params(r, cfg),
        optimizer=optax.adamw(3e-4),
        sample_batch=sample,
        strategy=strategy,
        devices=jax.devices()[:8],
    )
    dt = time.perf_counter() - t0
    if job.memory is None:
        raise RuntimeError(f"{label}: no memory_analysis on this backend")
    params_shape = jax.eval_shape(
        lambda r: llama.init_params(r, cfg), jax.random.PRNGKey(0)
    )
    # The estimator sees the same inputs the pruner gives it; the
    # model-level remat flag travels as strategy.remat="block" there.
    est_strategy = job.strategy
    if cfg.remat_block:
        est_strategy = dataclasses.replace(est_strategy, remat="block")
    predicted = estimate_step_hbm_bytes(
        params_shape, sample, est_strategy
    )
    return predicted, float(job.memory["peak_bytes"]), dt


def main() -> int:
    fast = "--fast" in sys.argv
    rows = []
    for label, cfg, batch, seq, strategy in points(fast):
        try:
            pred, actual, dt = measure_point(
                label, cfg, batch, seq, strategy
            )
        except Exception as e:  # noqa: BLE001
            print(f"{label:34s}  FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr)
            continue
        ratio = pred / actual if actual else float("inf")
        rows.append({
            "point": label,
            "predicted_gb": round(pred / 2**30, 3),
            "actual_gb": round(actual / 2**30, 3),
            "ratio": round(ratio, 3),
            "compile_s": round(dt, 1),
        })
        print(
            f"{label:34s}  pred {pred / 2**30:7.3f} GB   "
            f"actual {actual / 2**30:7.3f} GB   ratio {ratio:6.3f}   "
            f"({dt:.0f}s)",
            file=sys.stderr,
        )
        # Flush partials as points complete (a wedged run still leaves
        # data, same pattern as bench.py's BENCH_PARTIAL).
        import os as _os

        _out = _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            "CALIBRATE_HBM.json",
        )
        with open(_out, "w") as f:
            json.dump({"rows": rows}, f, indent=1)
    if not rows:
        print(json.dumps({"error": "no points measured"}))
        return 1
    ratios = [r["ratio"] for r in rows]
    import numpy as np

    summary = {
        "n_points": len(rows),
        "ratio_geomean": round(float(np.exp(np.mean(np.log(ratios)))), 3),
        "ratio_min": min(ratios),
        "ratio_max": max(ratios),
        "max_abs_rel_err": round(
            max(abs(r - 1.0) for r in ratios), 3
        ),
        "rows": rows,
    }
    import os

    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "CALIBRATE_HBM.json",
    )
    with open(out, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    import os

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from dlrover_tpu.common.jax_env import ensure_platform

    ensure_platform("cpu")
    sys.exit(main())
