"""Validate op-metrics classification against a REAL TPU profiler trace.

The HLO-name-prefix classifier (``utils.op_metrics.classify_op``) has
only ever seen synthetic CPU traces (VERDICT r3 weak #7): if real TPU
device-track names differ, the straggler operator silently sees 0%
matmul/collective fraction and never fires.  This runs a few llama
train steps on the live backend under an OpMetricsCollector capture and
prints the observed fractions plus the top op names by self time, so
wrong prefixes are immediately visible (and fixable).

Run on the chip:  python tools/validate_op_metrics.py
Writes OP_METRICS_TPU.json next to bench.py.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.accelerate import Strategy, accelerate
    from dlrover_tpu.parallel.mesh import MeshSpec
    from dlrover_tpu.utils.op_metrics import OpMetricsCollector

    backend = jax.default_backend()
    if "--require-tpu" in sys.argv and backend != "tpu":
        # Watcher mode: a shim fallback to CPU must NOT write the
        # artifact (the stage would wrongly count as done with
        # CPU-trace data — exactly the stale artifact r4 had to purge).
        print(f"FAIL: backend is {backend}, not tpu", file=sys.stderr)
        return 1
    if backend == "tpu":
        cfg = llama.LlamaConfig.small_300m()
        seq = 512
    else:  # CPU smoke of the tool itself: tiny shapes
        cfg = llama.LlamaConfig.tiny(n_layer=2)
        seq = 64
    batch_n = max(4, jax.local_device_count())
    rng = np.random.RandomState(0)
    sample = {
        "tokens": rng.randint(
            0, cfg.vocab_size, (batch_n, seq + 1)
        ).astype(np.int32)
    }
    job = accelerate(
        loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
        init_fn=lambda r: llama.init_params(r, cfg),
        optimizer=optax.adamw(3e-4),
        sample_batch=sample,
        strategy=Strategy(mesh=MeshSpec(dp=jax.local_device_count())),
    )
    state = job.create_state(jax.random.PRNGKey(0))
    batch = {"tokens": jnp.asarray(sample["tokens"])}

    col = OpMetricsCollector(capture_every=2)
    for step in range(4):
        col.step_begin(step)
        state, metrics = job.train_step(state, batch)
        _ = float(metrics["loss"])  # block
        col.step_end(step)
    diag = json.loads(col.diagnosis_data())
    m = diag["metrics"]
    captured = m.get("last_capture_step", -1.0) >= 0
    result = {
        "backend": backend,
        "matmul_frac": m.get("optime_matmul_frac"),
        "collective_frac": m.get("optime_collective_frac"),
        "other_frac": m.get("optime_other_frac"),
        "last_capture_step": m.get("last_capture_step"),
        "top_ops": diag.get("top_ops"),
    }
    out = os.path.join(REPO, "OP_METRICS_TPU.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result, indent=1))
    if not captured:
        print("FAIL: no capture completed", file=sys.stderr)
        return 1
    if backend == "tpu" and (m.get("optime_matmul_frac") or 0.0) <= 0.0:
        print(
            "FAIL: matmul fraction is zero on TPU — classify_op "
            "prefixes do not match real device-track names "
            "(see top_ops above for the actual names)",
            file=sys.stderr,
        )
        return 1
    print(
        f"OK: matmul={m.get('optime_matmul_frac', 0):.3f} "
        f"collective={m.get('optime_collective_frac', 0):.3f} "
        f"other={m.get('optime_other_frac', 0):.3f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
