"""The live-TPU session runbook: wait for the tunnel, then land every
hardware-gated artifact in priority order.

The tunneled backend has a history of answering for a while and then
wedging half-open (rounds 2-3 lost ALL hardware data to this; round 4's
sweep got 6 verified candidates before the tunnel died mid-session).
This script turns any future minutes of live tunnel into artifacts with
zero human latency:

1. kernel Mosaic smoke        -> KERNEL_SMOKE.json   (bench --kernel_smoke)
2. flash block-size tuning    -> FLASH_TUNE.json     (tools/tune_flash_blocks.py)
3. op-metrics classification  -> OP_METRICS_TPU.json (tools/validate_op_metrics.py)
4. goodput + restore seconds  -> GOODPUT_TPU.json    (bench.measure_goodput)
5. decode tokens/s            -> DECODE_TPU.json     (bench decode candidate)

Every stage is a killable subprocess with a hard timeout: a re-wedge
costs one stage, not the session.  Stages that already produced their
artifact are skipped, so the watcher is idempotent across restarts.

Run (backgrounded):  python tools/live_tpu_session.py --watch
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

def tunnel_alive(timeout_s: float = 90.0) -> bool:
    """One probe policy, shared with the bench guard
    (``bench.probe_live_backend``): ambient platform first, then
    auto-selection for the renamed-shim case.  When only auto answers,
    the choice is exported so every stage subprocess inherits it."""
    import bench

    outcome = bench.probe_live_backend(timeout_s)
    if outcome == "auto":
        os.environ["JAX_PLATFORMS"] = ""
    return outcome in ("ambient", "auto")


def run_stage(name: str, argv: list, timeout_s: float, log) -> str:
    """Returns "ok", "failed", or "timeout" (the caller treats a
    timeout differently: a SIGKILLed subprocess can't have written its
    own artifact)."""
    print(f"[live] stage {name}: starting", file=log, flush=True)
    t0 = time.time()
    try:
        proc = subprocess.run(
            argv, timeout=timeout_s, cwd=REPO,
            stdout=log, stderr=log, start_new_session=True,
        )
        ok = proc.returncode == 0
    except subprocess.TimeoutExpired:
        print(f"[live] stage {name}: TIMEOUT after {timeout_s:.0f}s",
              file=log, flush=True)
        return "timeout"
    print(
        f"[live] stage {name}: {'ok' if ok else 'FAILED'} "
        f"({time.time() - t0:.0f}s)",
        file=log, flush=True,
    )
    return "ok" if ok else "failed"


def goodput_stage_argv() -> list:
    # measure_goodput writes its dict; wrap to save an artifact.
    code = (
        "import json, sys; sys.path.insert(0, %r); "
        "import jax; assert jax.default_backend() == 'tpu', "
        "'shim fell back to %%s' %% jax.default_backend(); "
        "import bench; "
        "r = bench.measure_goodput(backend='tpu'); "
        "r['goodput_backend'] = 'tpu'; "
        "open(%r, 'w').write(json.dumps(r, indent=1)); print(r)"
        % (REPO, os.path.join(REPO, "GOODPUT_TPU.json"))
    )
    return [sys.executable, "-c", code]


def decode_stage_argv() -> list:
    # Dense and int8-kv generate() variants (decode is HBM-bandwidth-
    # bound, so the quant cache's half-sized reads should show directly
    # in tokens/s), plus the continuous-batching SERVER at 1 and 8
    # tokens per dispatch (the decode_chunk lever: each tunnel dispatch
    # costs real latency; K=8 measured ~6.5x tokens/s on the CPU
    # host-loop bound).  The artifact is written ONCE, only when ALL
    # variants measured: error-only or partial runs leave no artifact,
    # so _stage_done()'s existence check retries the stage next cycle
    # (a transient wedge must not permanently mask the measurements
    # this stage exists to collect).
    code = (
        "import json, sys; sys.path.insert(0, %r); import bench; "
        "from dlrover_tpu.models import llama; "
        "cfg = llama.LlamaConfig.small_300m()\n"
        "cfg_d = {k: v for k, v in cfg.__dict__.items()\n"
        "         if isinstance(v, (int, float, str, bool))}\n"
        "out = {}\n"
        "for name, q in (('dense', False), ('int8_kv', True)):\n"
        "    spec = {'kind': 'decode', 'batch': 8, 'prompt_len': 128,\n"
        "            'new_tokens': 128, 'quant_kv': q, 'cfg': cfg_d}\n"
        "    r = bench._run_one_subproc(spec, 'decode_' + name, 900.0)\n"
        "    out[name] = {'tokens_per_sec': round(r['tokens_per_sec'], 1)}\n"
        "    print(name, out[name])\n"
        "for name, k in (('server_k1', 1), ('server_k8', 8)):\n"
        "    spec = {'kind': 'server_decode', 'slots': 8,\n"
        "            'prompt_len': 128, 'new_tokens': 128,\n"
        "            'decode_chunk': k, 'cfg': cfg_d}\n"
        "    r = bench._run_one_subproc(spec, name, 900.0)\n"
        "    out[name] = {'tokens_per_sec': round(r['tokens_per_sec'], 1)}\n"
        "    print(name, out[name])\n"
        "open(%r, 'w').write(json.dumps(out, indent=1))\n"
        "print(out)"
        % (REPO, os.path.join(REPO, "DECODE_TPU.json"))
    )
    return [sys.executable, "-c", code]


def repro_800m_argv() -> list:
    # r4 sweep: llama_800m_h128 b8 block died with a swallowed
    # "no viable strategy found" while the plain 800m (identical sizes,
    # hd=96) passed.  Reproduce IN-PROCESS with stderr visible so
    # accelerate's per-candidate rejection log reaches LIVE_SESSION.log.
    # The EXPECTED outcome is a reproduced failure: the artifact must
    # be written either way (error + traceback on failure) or the
    # watcher would retry the 30-minute repro forever and never reach
    # the later stages.
    code = (
        "import dataclasses, json, sys, traceback; "
        "sys.path.insert(0, %r); "
        "import bench; from dlrover_tpu.models import llama; "
        "cfg = dataclasses.replace(llama.LlamaConfig.medium_800m(), "
        "n_head=12, n_kv_head=12)\n"
        "try:\n"
        "    dt, loss = bench._measure_candidate("
        "cfg, 8, 2048, 'block', 3, 'adamw', False)\n"
        "    out = {'dt': dt, 'loss': loss, 'mfu_pct': round(100.0 * "
        "bench.model_flops_per_step(cfg, 8, 2048) / dt / "
        "bench.detect_peak(), 2)}\n"
        "except Exception as e:\n"
        "    out = {'error': '%%s: %%s' %% (type(e).__name__, e), "
        "'traceback': traceback.format_exc()[-4000:]}\n"
        "open(%r, 'w').write(json.dumps(out, indent=1)); print(out)"
        % (REPO, os.path.join(REPO, "REPRO_800M_H128.json"))
    )
    return [sys.executable, "-c", code]


# Stages whose artifact is a RESUMABLE partial: existence alone does
# not mean done — the tool marks "complete" once every row/point is
# settled, and an incomplete artifact means "retry; measured rows are
# kept".
_RESUMABLE = {"flash_tune", "spec_decode"}


def _stage_done(name: str, artifact: str) -> bool:
    """A stage is done when its artifact exists — except resumable
    stages, which are only done once the tool has marked the whole
    table/grid measured."""
    apath = os.path.join(REPO, artifact)
    if not os.path.exists(apath):
        return False
    if name not in _RESUMABLE:
        return True
    try:
        with open(apath) as f:
            return bool(json.load(f).get("complete"))
    except (OSError, ValueError):
        return False


STAGES = [
    # (name, artifact-to-skip-if-present, argv builder, timeout_s)
    ("kernel_smoke", "KERNEL_SMOKE.json",
     lambda: [sys.executable, os.path.join(REPO, "bench.py"),
              "--kernel_smoke"], 2400.0),
    ("flash_tune", "FLASH_TUNE.json",
     lambda: [sys.executable,
              os.path.join(REPO, "tools", "tune_flash_blocks.py")],
     7200.0),
    ("repro_800m_h128", "REPRO_800M_H128.json", repro_800m_argv,
     1800.0),
    ("op_metrics", "OP_METRICS_TPU.json",
     lambda: [sys.executable,
              os.path.join(REPO, "tools", "validate_op_metrics.py"),
              "--require-tpu"],
     1800.0),
    ("goodput", "GOODPUT_TPU.json", goodput_stage_argv, 2400.0),
    # Outer timeout must exceed the stage's inner budgets (4 x 900s
    # variants) with startup headroom, or a SIGKILL lands between
    # variants — the all-or-nothing artifact then retries from scratch
    # next cycle.
    ("decode", "DECODE_TPU.json", decode_stage_argv, 4200.0),
    # Speculation's win condition on hardware: plain vs spec ceiling/
    # floor plus component-derived break-even (bench spec_bench_main
    # flushes rows as they complete and resumes measured rows; outer
    # timeout must exceed 4 rows x 900s inner budgets + headroom).
    ("spec_decode", "SPEC_DECODE_TPU.json",
     lambda: [sys.executable, os.path.join(REPO, "bench.py"),
              "--spec_bench"], 4200.0),
    # Remaining hardware unknowns (offload_opt x remat=offload on the
    # real partitioner, node-check payload timing, device-cache hit
    # path vs host pull) — each probe is its own killable subprocess.
    ("hw_probes", "HW_PROBES.json",
     lambda: [sys.executable,
              os.path.join(REPO, "tools", "probe_hw_unknowns.py")],
     3000.0),
    # Last: the full training sweep.  bench.py flushes TPU-measured
    # candidates to BENCH_TPU_VERIFIED.json as they complete (the
    # durable append-per-run artifact), so even a wedge mid-sweep
    # leaves verified numbers.  Goodput/decode probes are skipped —
    # their dedicated stages above already landed artifacts.
    ("bench_sweep", "BENCH_TPU_VERIFIED.json",
     lambda: ["/usr/bin/env", "DLROVER_TPU_BENCH_GOODPUT=0",
              "DLROVER_TPU_BENCH_DEADLINE=3300",
              sys.executable, os.path.join(REPO, "bench.py")], 3600.0),
]


def main() -> int:
    watch = "--watch" in sys.argv
    log_path = os.path.join(REPO, "LIVE_SESSION.log")
    with open(log_path, "a") as log:
        print(f"[live] watcher up pid={os.getpid()}", file=log,
              flush=True)
        while True:
            if not tunnel_alive():
                if not watch:
                    print("[live] tunnel down, exiting (no --watch)",
                          file=log, flush=True)
                    return 1
                time.sleep(120)
                continue
            print("[live] tunnel ALIVE — running stage queue",
                  file=log, flush=True)
            all_done = True
            for name, artifact, argv_fn, timeout_s in STAGES:
                if _stage_done(name, artifact):
                    continue
                outcome = run_stage(name, argv_fn(), timeout_s, log)
                if (
                    name == "repro_800m_h128"
                    and outcome == "timeout"
                    and not os.path.exists(os.path.join(REPO, artifact))
                ):
                    # The stage's in-process except can't fire on a
                    # SIGKILLed (hung) subprocess; persist the outcome
                    # anyway or every future cycle re-burns the
                    # 30-minute repro before reaching later stages.
                    # ONLY on timeout: a fast rc!=0 death (broken env,
                    # OOM-kill) should retry next cycle, not be masked
                    # by a fabricated "hung" record.
                    with open(os.path.join(REPO, artifact), "w") as f:
                        json.dump(
                            {"error": "hung until stage timeout "
                                      "(wedged backend?)"}, f,
                        )
                if outcome != "ok" and not tunnel_alive():
                    print("[live] tunnel re-wedged; back to waiting",
                          file=log, flush=True)
                    all_done = False
                    break
            if all_done and all(
                _stage_done(n, a) for n, a, _, _ in STAGES
            ):
                print("[live] all artifacts landed; exiting", file=log,
                      flush=True)
                return 0
            if not watch:
                return 0
            time.sleep(120)


if __name__ == "__main__":
    sys.exit(main())
