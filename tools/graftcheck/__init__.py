"""graftcheck — repo-native static analysis for JAX/TPU and
concurrency hazards.

The classes of bug that hurt this codebase most are exactly the ones
the test suite catches late or never: tracer leaks and silent
recompilation in the jit-heavy data plane, and lock-discipline races in
the threaded master/agent control plane.  graftcheck is an AST pass
that flags those shapes *before* they run.

Rule families
-------------
JAX (data plane):

- ``JX001`` — Python ``if``/``while`` branching on a traced value
  inside a jitted function (tracer leak / ConcretizationTypeError).
- ``JX002`` — host sync inside jit scope: ``float()``, ``.item()``,
  ``np.asarray``/``np.array``, ``.block_until_ready()``.
- ``JX003`` — ``jax.jit`` constructed inside a loop body (every
  iteration makes a fresh callable -> silent recompilation).
- ``JX004`` — PRNG key reuse: the same key fed to >=2 consuming
  ``jax.random`` calls (or re-consumed across loop iterations) without
  an intervening ``split``/rebind.
- ``JX005`` — non-hashable argument (list/dict/set display or
  comprehension) passed in a ``static_argnums`` position of a jitted
  function.

Concurrency (control plane):

- ``CC101`` — an instance attribute written both inside and outside
  ``with self.<lock>:`` (outside ``__init__``): torn-read hazard.
- ``CC102`` — ``time.sleep`` while holding a lock: every other thread
  on that lock sleeps too.
- ``CC103`` — a non-daemon ``threading.Thread`` that is never joined
  (and never flipped to daemon): hangs interpreter shutdown.
- ``CC104`` — ``except:`` / ``except Exception:`` whose body is only
  ``pass``/``continue``: swallows errors on RPC/retry paths.

Meta:

- ``GC000`` — a suppression comment without a justification.  An
  unjustified suppression does NOT suppress; the policy is enforced by
  the tool itself.

Suppression syntax
------------------
``# graftcheck: disable=JX003 -- memoized in self._cache, compiled once``

The ``-- justification`` text is REQUIRED.  Several ids may be given
comma-separated.  A suppression on its own line applies to the next
code line; trailing on a code line it applies to that line.
"""

from .engine import (  # noqa: F401
    Finding,
    RULES,
    check_source,
    check_file,
    run_paths,
    main,
)
