"""graftcheck — repo-native static analysis for JAX/TPU, concurrency,
and cross-module protocol hazards.

The classes of bug that hurt this codebase most are exactly the ones
the test suite catches late or never: tracer leaks and silent
recompilation in the jit-heavy data plane, lock-discipline races in
the threaded master/agent control plane, and — since the control plane
became a real distributed protocol — contracts that only exist BETWEEN
modules: which messages have handlers, which RPC retries are safe,
which mutations the HA journal covers, which chaos sites and counters
are real.  graftcheck flags those shapes *before* they run.

v3 is a three-pass engine: pass 1 builds a whole-program project model
(``project_model.py``); pass 2 runs the per-file AST families below on
each analyzed file plus the cross-module families (``proto_rules.py``)
over the model; pass 3 computes a transitive ambient-effect set for
every function/method (``effects.py``) and enforces the sim-readiness
contract on the pure-policy registry (``policy_registry.py``,
``effect_rules.py``) — ROADMAP item 7's wind tunnel can only drive
policy objects whose whole behavior flows through injected clocks and
caller-owned seeds.

Rule families
-------------
JAX (data plane):

- ``JX001`` — Python ``if``/``while`` branching on a traced value
  inside a jitted function (tracer leak / ConcretizationTypeError).
- ``JX002`` — host sync inside jit scope: ``float()``, ``.item()``,
  ``np.asarray``/``np.array``, ``.block_until_ready()``.
- ``JX003`` — ``jax.jit`` constructed inside a loop body (every
  iteration makes a fresh callable -> silent recompilation).
- ``JX004`` — PRNG key reuse: the same key fed to >=2 consuming
  ``jax.random`` calls (or re-consumed across loop iterations) without
  an intervening ``split``/rebind.
- ``JX005`` — non-hashable argument (list/dict/set display or
  comprehension) passed in a ``static_argnums`` position of a jitted
  function.

Concurrency (control plane):

- ``CC101`` — an instance attribute written both inside and outside
  ``with self.<lock>:`` (outside ``__init__``): torn-read hazard.
- ``CC102`` — ``time.sleep`` while holding a lock: every other thread
  on that lock sleeps too.
- ``CC103`` — a non-daemon ``threading.Thread`` that is never joined
  (and never flipped to daemon): hangs interpreter shutdown.
- ``CC104`` — ``except:`` / ``except Exception:`` whose body is only
  ``pass``/``continue``: swallows errors on RPC/retry paths.

Observability:

- ``OB301`` — a ``time.time()`` delta used as a duration/deadline
  (wall clocks step; use monotonic/perf_counter).

Protocol (cross-module, over the project model):

- ``PC401`` — a message sent at a ``.call(...)`` site that no dispatch
  table or ``isinstance`` handler accepts.
- ``PC402`` — a dispatch-table entry for a non-message type.
- ``PC403`` — ``idempotent=True`` retry of a handler that
  destructively consumes state without reading an idempotency token
  (the Heartbeat destructive-retry bug class).
- ``PC404`` — a mutating manager method reachable from a journaled
  servicer's handler that never reaches ``_jrec`` (acks before the
  control-state journal append on the HA path).
- ``PC405`` — a message class referenced nowhere outside its defining
  module (product or tests): dead protocol surface.

Lock discipline (cross-module):

- ``LK201`` — whole-program lock-order cycle / nested re-acquisition
  of a non-reentrant Lock (potential deadlock; RLock re-entry exempt).
- ``LK202`` — a ``_*_locked`` method called without the lock held.

Chaos coverage:

- ``CH501`` — a ``SITES`` entry never injected anywhere.
- ``CH502`` — an injected site string not declared in ``SITES``.
- ``CH503`` — a declared site no test references.

Metrics drift:

- ``MT601`` — a counter incremented but never exported by any gauge
  registration.
- ``MT602`` — one module registering the same gauge name twice.

Determinism / sim-readiness (effect inference over the model):

- ``DET701`` — an ambient clock read reachable from a registered pure
  policy, or a direct ambient read in a class with an injected clock
  seam in reach (own ``self._clock`` or a seamed collaborator).
- ``DET702`` — unseeded randomness (``random.*``, ``uuid4``,
  ``os.urandom``, ``np.random.*``) reachable from a registered policy.
- ``DET703`` — a sandbox escape reachable from a registered policy:
  thread/process spawn, blocking I/O, env read, global mutation.
- ``DET704`` — hash-order nondeterminism reachable from a registered
  policy: iterating / ``next(iter(...))`` / ``.pop()`` on a set
  without a ``sorted()`` total order.
- ``DET705`` — a wall-clock stamp recorded into decision/audit state
  (``self.x.append((time.time(), ...))``); the OB301 cousin for
  stored state replay compares.

Meta:

- ``GC000`` — a suppression comment without a justification.  An
  unjustified suppression does NOT suppress; the policy is enforced by
  the tool itself.
- ``GC001`` — a stale suppression whose rule no longer fires on the
  covered line (delete it).  Neither meta rule is suppressible.

Suppression syntax
------------------
``# graftcheck: disable=JX003 -- memoized in self._cache, compiled once``

The ``-- justification`` text is REQUIRED.  Several ids may be given
comma-separated.  A suppression on its own line applies to the next
code line; trailing on a code line it applies to that line.
"""

from .engine import (  # noqa: F401
    Finding,
    RULES,
    check_source,
    check_file,
    check_project,
    run_paths,
    run_project,
    render_chaos_table,
    main,
)
