"""The pure-policy registry: the objects the wind tunnel will drive.

ROADMAP item 7's discrete-event simulator replays a synthetic
10,000-node trace against the REAL policy objects — the same grant
scan, autoscale decision function, placement solver, and borrow
arbiter that run in production.  That only works if those objects are
pure state machines over an *injected* clock and *seeded* randomness:
any ambient ``time.time()``, ``random.random()``, thread spawn, or
hash-order pick makes the simulated run diverge from the replayed one
and the whole exercise meaningless.

This module is the contract's source of truth.  Registering an object
here turns the DET701–DET705 families on for it: graftcheck computes
its transitive ambient-effect set (``effects.py``) and fails the build
if the set is non-empty.  The ``--effects`` manifest
(``POLICY_EFFECTS.json``) is generated from the same registry, and a
tier-1 test pins it against drift.

How to register a new policy object
-----------------------------------
Add a ``PolicyObject`` entry below.  ``module`` is the repo-relative
path suffix (matched against the analyzed file's ``module_of`` label,
so fixtures under virtual paths with the same suffix also resolve);
``name`` is the class or module-level function name; ``kind`` is
``"class"`` (the whole method surface must be effect-free) or
``"function"`` (the function plus its same-module callees).  Then run
``python -m graftcheck --effects dlrover_tpu/`` and commit the
regenerated ``POLICY_EFFECTS.json``.

The entries deliberately name WHERE the code lives today — the
``_spec_k_request`` family sits in ``models/llama_infer.py`` (the
serving draft loop imports it from there), not a hypothetical
``serving/draft.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class PolicyObject:
    """One sim-bound object the determinism families protect."""

    module: str   # repo-relative path suffix, e.g. "serving/gateway.py"
    name: str     # class or function name inside that module
    kind: str     # "class" | "function"
    doc: str      # one line: what the simulator drives it for

    @property
    def label(self) -> str:
        return f"{self.module}::{self.name}"


REGISTRY: Tuple[PolicyObject, ...] = (
    PolicyObject(
        "dlrover_tpu/serving/gateway.py", "GatewayCore", "class",
        "admission/grant scan + queue policy over the injected clock",
    ),
    PolicyObject(
        "dlrover_tpu/serving/autoscale.py", "decide", "function",
        "single-pool scaling decision (pure snapshot -> Decision)",
    ),
    PolicyObject(
        "dlrover_tpu/serving/autoscale.py", "decide_pools", "function",
        "multi-pool scaling with the shared-budget tie-break",
    ),
    PolicyObject(
        "dlrover_tpu/common/hashring.py", "HashRing", "class",
        "consistent-hash ownership: same members -> same ring",
    ),
    PolicyObject(
        "dlrover_tpu/cells/federation.py", "merge_cell_snapshots",
        "function",
        "federation view merge (newest-wins, deterministic order)",
    ),
    PolicyObject(
        "dlrover_tpu/cells/federation.py", "place_roles", "function",
        "role placement across cells (sorted candidate order)",
    ),
    PolicyObject(
        "dlrover_tpu/cells/federation.py", "detect_splits", "function",
        "split-brain detection over the merged view",
    ),
    PolicyObject(
        "dlrover_tpu/cells/federation.py", "plan_moves", "function",
        "cross-cell move orders from a placement diff (sorted greedy)",
    ),
    PolicyObject(
        "dlrover_tpu/fleet/policy.py", "ChipBorrowArbiter", "class",
        "cross-job chip borrow/reclaim arbitration",
    ),
    PolicyObject(
        "dlrover_tpu/fleet/policy.py", "CrossCellMover", "class",
        "cross-cell chip-move actuation (drain-first, restart ladder)",
    ),
    PolicyObject(
        "dlrover_tpu/serving/spillover.py", "SpilloverPolicy", "class",
        "cross-cell spillover forward/stay decision (injected clock)",
    ),
    PolicyObject(
        "dlrover_tpu/reshard/plan.py", "build_plan", "function",
        "reshard transfer planning (same src/dst -> same plan)",
    ),
    PolicyObject(
        "dlrover_tpu/checkpoint/slicer.py", "plan_persist", "function",
        "per-process slice assignment for sliced checkpoints",
    ),
    PolicyObject(
        "dlrover_tpu/sim/events.py", "SimScheduler", "class",
        "the wind tunnel's event queue (seeded order, injected clock)",
    ),
    PolicyObject(
        "dlrover_tpu/sim/trace.py", "TraceGenerator", "class",
        "synthetic fleet traces (pure function of TraceConfig)",
    ),
    PolicyObject(
        "dlrover_tpu/models/llama_infer.py", "_spec_k_request",
        "function",
        "speculative-k controller (request-level EWMA policy)",
    ),
    PolicyObject(
        "dlrover_tpu/models/llama_infer.py", "_adapt_spec_k",
        "function",
        "speculative-k controller (per-step adaptation policy)",
    ),
    PolicyObject(
        "dlrover_tpu/offline/policy.py", "OfflinePolicy", "class",
        "virtual-capacity sizing for the preemptible offline tier",
    ),
    PolicyObject(
        "dlrover_tpu/sim/offline.py", "OfflineTierSim", "class",
        "the priority-class wind tunnel (baseline vs offline tier)",
    ),
)
