"""graftcheck pass 3 rules: determinism / sim-readiness (DET7xx).

These run over the effect index (:mod:`effects`) and the pure-policy
registry (:mod:`policy_registry`).  A registered policy object is one
the ROADMAP-item-7 wind tunnel will drive with a synthetic trace; the
contract is that its ENTIRE transitive behavior is a function of its
inputs, the injected clock, and the caller's seed:

DET701  an ambient clock read (``time.time``/``time.monotonic``/
        ``datetime.now`` …) reachable from a registered policy, or a
        direct ambient read inside a class that HAS an injected
        ``clock`` seam (a seam you bypass is worse than no seam — the
        object is half-simulable and the divergence is silent);
DET702  unseeded/ambient randomness (``random.*``, ``uuid4``,
        ``os.urandom``, ``np.random.*``) reachable from a registered
        policy — a replayed decision sequence can never match;
DET703  an effect that escapes the simulator's sandbox reachable from
        a registered policy: thread/process spawn, blocking I/O
        (sockets, files, sleeps), env reads, global mutation;
DET704  hash-order nondeterminism reachable from a registered policy:
        iterating a ``set`` (or ``next(iter(s))`` / ``s.pop()``) to
        pick victims/owners/grants without a ``sorted()`` total order
        — the pick flips with PYTHONHASHSEED and insertion history;
DET705  a wall-clock timestamp recorded into decision/audit state
        (``self.<attr>.append((time.time(), ...))`` and kin) — the
        OB301 cousin for STORED state: replay compares two runs'
        decision logs, and wall stamps make byte-identical sequences
        impossible.  Repo-wide, not registry-scoped: audit trails live
        on actuators, not on the pure policies themselves.

Like every graftcheck family the rules are conservative: an
unresolvable callee contributes nothing (that is what "behind a seam"
means — an injected callable is invisible to the closure), and a
deliberate ambient site carries a justified suppression at the
anchoring line.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding
from .effects import Effect, EffectIndex, WALL_CALLS
from .jax_rules import _dotted
from .policy_registry import REGISTRY, PolicyObject
from .project_model import ClassInfo, ProjectModel, module_of

_CLOCK_KINDS = {"wall_clock", "monotonic"}
_SANDBOX_KINDS = {"thread_spawn", "blocking_io", "env_read",
                  "global_mutation"}

#: self-attrs that ARE the clock seam.  Assigning a callable here is
#: the repo's injection idiom (``self._clock = clock``); ambient reads
#: elsewhere in the same class bypass it.
_SEAM_ATTRS = {"self._clock", "self.clock"}

#: the mutator-attr set itself lives in project_model (the pass-1
#: walk collects candidate call sites for us).
from .project_model import _AUDIT_MUTATOR_ATTRS as _AUDIT_MUTATORS  # noqa: E402,F401


# ---------------------------------------------------------------------------
# registry resolution
# ---------------------------------------------------------------------------


def resolve_policy(model: ProjectModel, policy: PolicyObject) \
        -> Optional[Tuple[str, object]]:
    """Locate a registry entry in the analyzed tree.

    Returns ``(path, ClassInfo)`` for classes and ``(path, name)`` for
    functions, or None when the entry's module is outside the analyzed
    set (fixtures resolve too: matching is by repo-relative module
    suffix, so a test file parsed under a virtual
    ``dlrover_tpu/serving/autoscale.py`` path carries the contract)."""
    if policy.kind == "class":
        for ci in model.classes_named(policy.name):
            if module_of(ci.path).endswith(policy.module):
                return ci.path, ci
        return None
    for path, funcs in model.module_funcs.items():
        if module_of(path).endswith(policy.module) and \
                policy.name in funcs:
            return path, policy.name
    return None


def policy_effects(model: ProjectModel, index: EffectIndex,
                   policy: PolicyObject) -> Optional[Set[Effect]]:
    """The transitive ambient-effect set of one registry entry, or
    None when it does not resolve in the analyzed tree."""
    got = resolve_policy(model, policy)
    if got is None:
        return None
    path, target = got
    if policy.kind == "class":
        return set(index.class_closure(policy.name, target))
    return set(index.func_closure(path, target))


# ---------------------------------------------------------------------------
# DET701–704: ambient effects reachable from registered policies
# ---------------------------------------------------------------------------


def _policy_findings(model: ProjectModel, index: EffectIndex) \
        -> List[Finding]:
    findings: List[Finding] = []
    for policy in REGISTRY:
        effs = policy_effects(model, index, policy)
        if not effs:
            continue
        for e in sorted(effs, key=lambda e: (e.path, e.line, e.kind)):
            if e.kind in _CLOCK_KINDS:
                findings.append(Finding(
                    "DET701", e.path, e.line,
                    f"ambient clock read ({e.detail}) reachable from "
                    f"registered policy {policy.label} — the wind "
                    "tunnel cannot advance an ambient clock; read the "
                    "injected `clock` seam instead",
                ))
            elif e.kind == "rng":
                findings.append(Finding(
                    "DET702", e.path, e.line,
                    f"unseeded randomness ({e.detail}) reachable from "
                    f"registered policy {policy.label} — replayed "
                    "decision sequences can never match; take a seed/"
                    "rng from the caller",
                ))
            elif e.kind in _SANDBOX_KINDS:
                findings.append(Finding(
                    "DET703", e.path, e.line,
                    f"{e.kind} ({e.detail}) reachable from registered "
                    f"policy {policy.label} — escapes the simulator's "
                    "sandbox; move it to the actuator/transport layer "
                    "behind a seam",
                ))
            elif e.kind == "hash_order":
                findings.append(Finding(
                    "DET704", e.path, e.line,
                    f"hash-order nondeterminism ({e.detail}) reachable "
                    f"from registered policy {policy.label} — the pick "
                    "flips with PYTHONHASHSEED; impose a sorted() "
                    "total order",
                ))
    return findings


# ---------------------------------------------------------------------------
# DET701 (seam-bypass form): ambient reads inside seam-bearing classes
# ---------------------------------------------------------------------------


def _has_clock_seam(cls: ast.ClassDef) -> bool:
    """Does the class assign a CALLABLE to ``self._clock``/``
    self.clock``?  ``self._clock = clock`` (param) and ``self._clock =
    time.monotonic`` (default) are seams; ``= time.monotonic()`` (a
    stored instant) is not.  Memoized on the node itself: seam-source
    resolution re-asks this for every (class, collaborator) pair, and
    re-walking a big class body each time dominated DET701."""
    cached = getattr(cls, "_graftcheck_has_seam", None)
    if cached is not None:
        return cached
    found = False
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, (ast.Name, ast.Attribute,
                                       ast.BoolOp)):
            continue
        for t in node.targets:
            if _dotted(t) in _SEAM_ATTRS:
                found = True
                break
        if found:
            break
    cls._graftcheck_has_seam = found
    return found


def _seam_source(model: ProjectModel, ci: ClassInfo) -> Optional[str]:
    """Where this class COULD read an injected clock: its own seam
    (``"self._clock"``), or a typed collaborator whose class carries
    one (``"self.core (GatewayCore)"``).  None = genuinely seamless —
    DET701's bypass form stays silent (registration is how an object
    opts into the contract from scratch)."""
    if isinstance(ci.node, ast.ClassDef) and _has_clock_seam(ci.node):
        return "self._clock"
    for attr in sorted(ci.attr_types):
        for cname in sorted(ci.attr_types[attr]):
            for collab in model.classes_named(cname):
                if isinstance(collab.node, ast.ClassDef) and \
                        _has_clock_seam(collab.node):
                    return f"self.{attr} ({cname})"
    return None


def _seam_bypass_findings(model: ProjectModel, index: EffectIndex) \
        -> List[Finding]:
    findings: List[Finding] = []
    for classes in model.classes.values():
        for ci in classes:
            if not isinstance(ci.node, ast.ClassDef):
                continue
            seam = _seam_source(model, ci)
            if seam is None:
                continue
            for mname in sorted(ci.methods):
                mi = ci.methods[mname]
                for e in index.direct_of(ci.path, mi, ci):
                    if e.kind in _CLOCK_KINDS:
                        findings.append(Finding(
                            "DET701", e.path, e.line,
                            f"ambient clock read ({e.detail}) in "
                            f"{ci.name}.{mname}, but an injected "
                            f"clock seam is in reach ({seam}) — "
                            "bypassing it makes the object half-"
                            "simulable; route the read through the "
                            "seam",
                        ))
    return findings


# ---------------------------------------------------------------------------
# DET705: wall stamps recorded into decision/audit state
# ---------------------------------------------------------------------------


def _contains_wall_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _dotted(sub.func) in WALL_CALLS:
            return True
    return False


def _audit_stamp_findings(model: ProjectModel) -> List[Finding]:
    # Candidates come from the model's single pass-1 walk
    # (``mutator_calls`` / ``subscript_assigns``) — re-walking every
    # tree here dominated the --changed latency budget.
    findings: List[Finding] = []
    for path, node in model.mutator_calls:
        container = _dotted(node.func.value)
        if container is None or not container.startswith("self."):
            continue
        if any(_contains_wall_call(a) for a in node.args) or \
                any(_contains_wall_call(kw.value)
                    for kw in node.keywords):
            findings.append(Finding(
                "DET705", path, node.lineno,
                f"wall-clock stamp recorded into {container} — "
                "replay compares stored decision/audit "
                "sequences, and wall stamps can never be "
                "byte-identical across runs; stamp via the "
                "injected clock",
            ))
    for path, node in model.subscript_assigns:
        if not _contains_wall_call(node.value):
            continue
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                container = _dotted(t.value)
                if container is not None and \
                        container.startswith("self."):
                    findings.append(Finding(
                        "DET705", path, node.lineno,
                        f"wall-clock stamp stored into "
                        f"{container}[...] — replayed state "
                        "can never match; stamp via the "
                        "injected clock",
                    ))
    return findings


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def check_project(model: ProjectModel,
                  index: Optional[EffectIndex] = None) \
        -> List[Finding]:
    index = index if index is not None else EffectIndex(model)
    findings: List[Finding] = []
    findings.extend(_policy_findings(model, index))
    findings.extend(_seam_bypass_findings(model, index))
    findings.extend(_audit_stamp_findings(model))
    uniq: Dict[Tuple[str, str, int], Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line), f)
    return list(uniq.values())


# ---------------------------------------------------------------------------
# the --effects manifest
# ---------------------------------------------------------------------------

MANIFEST_SCHEMA = "graftcheck.policy_effects.v1"


def effects_manifest(model: ProjectModel,
                     index: Optional[EffectIndex] = None) -> dict:
    """The per-policy effect manifest the future ``sim/`` harness (and
    the tier-1 drift gate) consumes.  Kinds only, no line numbers —
    line drift must not churn the committed ``POLICY_EFFECTS.json``."""
    from .effects import effects_summary
    index = index if index is not None else EffectIndex(model)
    policies = {}
    for policy in sorted(REGISTRY, key=lambda p: p.label):
        effs = policy_effects(model, index, policy)
        policies[policy.label] = {
            "kind": policy.kind,
            "doc": policy.doc,
            "resolved": effs is not None,
            "ambient_effects": effects_summary(effs or ()),
        }
    return {"schema": MANIFEST_SCHEMA, "policies": policies}
