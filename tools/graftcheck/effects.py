"""graftcheck pass 3: effect inference (the sim-readiness analysis).

ROADMAP item 7 (the 10,000-node wind tunnel) needs every fleet policy
to be a pure state machine over an INJECTED clock and seeded
randomness, so the real policy objects can run inside a discrete-event
simulator.  That property is structural, not behavioral — it can be
read off the AST.  This pass computes, for every function/method in
the analyzed tree, a **transitive ambient-effect set**:

- ``wall_clock``      — ``time.time()`` / ``datetime.now()`` reads;
- ``monotonic``       — ``time.monotonic()`` / ``perf_counter()``;
- ``rng``             — unseeded randomness: ``random.*`` module
                        calls, ``uuid4``, ``os.urandom``,
                        ``np.random.*`` (``jax.random`` is keyed —
                        JX004 owns key discipline, not this pass);
- ``thread_spawn``    — ``threading.Thread``/``Timer``,
                        ``multiprocessing.Process``, executors;
- ``blocking_io``     — ``time.sleep``, sockets, ``open``,
                        ``subprocess``, ``os.fsync``/``system``;
- ``env_read``        — ``os.environ`` / ``os.getenv``;
- ``global_mutation`` — a ``global`` declaration inside a function;
- ``hash_order``      — iterating / ``next(iter(...))`` / ``.pop()``
                        over a *set* without a ``sorted()`` total
                        order (victim/owner/grant picks must not
                        depend on PYTHONHASHSEED or insertion races).

Direct effects are lexical; the transitive part propagates them
through the PR-14 one-level call graph — ``self.<m>()`` calls
(including inherited methods), ``self.<attr>.<m>()`` calls through the
typed-collaborator index, and same-module function calls.  Calls that
do not resolve (imported functions, untyped locals) contribute
nothing: like every other graftcheck family, the analysis skips rather
than guesses — which is exactly the seam contract: an *injected*
callable (``self._clock()``, an ``observe_latency_ms`` hook, the obs
recorder) is invisible here, and that invisibility is what "behind the
seam" means.

A nested ``def`` is charged to its definer: the closure a method
builds (the gateway's gauge-snapshot reader) runs with the ambient
reads its body contains, and the definer is the object that must
route them through a seam.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, \
    Tuple

from .jax_rules import _dotted
from .project_model import ClassInfo, MethodInfo, ProjectModel

#: The closed effect vocabulary (the manifest schema pins this).
EFFECT_KINDS = (
    "wall_clock", "monotonic", "rng", "thread_spawn", "blocking_io",
    "env_read", "global_mutation", "hash_order",
)

#: Wall-clock reads: instants that step under NTP; a replayed decision
#: log stamped with these is incomparable across runs.
WALL_CALLS = {
    "time.time", "_time.time", "time.time_ns",
    "time.ctime", "time.localtime", "time.gmtime", "time.strftime",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "date.today", "datetime.date.today",
}

#: Monotonic reads: safe for durations, still AMBIENT — a simulator
#: cannot advance them; policies must read the injected clock.
MONO_CALLS = {
    "time.monotonic", "time.perf_counter",
    "time.monotonic_ns", "time.perf_counter_ns",
    "_time.monotonic", "_time.perf_counter",
}

#: Unseeded / process-global randomness.  ``jax.random`` is excluded
#: by construction (keyed; the caller owns the seed).
_RNG_EXACT = {"uuid.uuid4", "uuid4", "os.urandom", "getrandbits"}
_RNG_PREFIXES = ("random.", "_random.", "secrets.", "np.random.",
                 "numpy.random.")

_THREAD_CALLS = {
    "threading.Thread", "Thread", "threading.Timer", "Timer",
    "multiprocessing.Process", "Process",
    "ThreadPoolExecutor", "ProcessPoolExecutor",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
}

_IO_EXACT = {
    "time.sleep", "_time.sleep", "open", "os.fsync", "os.system",
    "os.popen", "select.select", "socket.create_connection",
}
_IO_PREFIXES = ("socket.", "subprocess.", "shutil.")


@dataclasses.dataclass(frozen=True)
class Effect:
    """One ambient-effect origin site."""

    kind: str
    path: str
    line: int
    detail: str


# ---------------------------------------------------------------------------
# set-typed name tracking (hash_order)
# ---------------------------------------------------------------------------


def _is_set_expr(node: ast.AST, settish: Set[str]) -> bool:
    """Does ``node`` evaluate to a set?  Set displays/comprehensions,
    ``set(...)``/``frozenset(...)`` calls, names assigned from one
    (``settish`` carries both locals and ``self.x`` spellings), and
    unions/intersections of sets."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fname = _dotted(node.func)
        return fname in ("set", "frozenset")
    name = _dotted(node)
    if name is not None and name in settish:
        return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
        return _is_set_expr(node.left, settish) or \
            _is_set_expr(node.right, settish)
    return False


def set_attrs_of_class(cls: ast.ClassDef) -> Set[str]:
    """``self.x`` attributes assigned a set anywhere in the class."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if value is None or not _is_set_expr(value, out):
            # Two sweeps would catch chains; one keeps it cheap and
            # conservative (misses only set-of-set aliasing).
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            name = _dotted(t)
            if name is not None and name.startswith("self."):
                out.add(name)
    return out


# ---------------------------------------------------------------------------
# direct effects of one function body
# ---------------------------------------------------------------------------


class _EffectWalk(ast.NodeVisitor):
    """One function's lexical ambient effects.  Walks nested defs too
    (a closure's effects belong to its definer — see module doc)."""

    def __init__(self, path: str, set_attrs: Set[str]):
        self.path = path
        self.settish: Set[str] = set(set_attrs)
        self.effects: List[Effect] = []

    def _add(self, kind: str, node: ast.AST, detail: str) -> None:
        self.effects.append(Effect(
            kind=kind, path=self.path,
            line=getattr(node, "lineno", 0), detail=detail,
        ))

    # -- names that become settish --------------------------------------
    def visit_Assign(self, node):
        if _is_set_expr(node.value, self.settish):
            for t in node.targets:
                name = _dotted(t)
                if name is not None:
                    self.settish.add(name)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None and \
                _is_set_expr(node.value, self.settish):
            name = _dotted(node.target)
            if name is not None:
                self.settish.add(name)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        # ``s |= {...}`` keeps/creates settishness.
        if isinstance(node.op, (ast.BitOr, ast.BitAnd)) and \
                _is_set_expr(node.value, self.settish):
            name = _dotted(node.target)
            if name is not None:
                self.settish.add(name)
        self.generic_visit(node)

    # -- iteration order -------------------------------------------------
    def _check_iter(self, it: ast.AST) -> None:
        if isinstance(it, ast.Call) and \
                _dotted(it.func) in ("sorted", "len", "sum", "min",
                                     "max", "any", "all"):
            return  # a total order (or an order-free reduction)
        if _is_set_expr(it, self.settish):
            self._add("hash_order", it,
                      "iterates a set in hash order")

    def visit_For(self, node):
        self._check_iter(node.iter)
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    def _visit_comp(self, node):
        for gen in node.generators:
            self._check_iter(gen.iter)
        self.generic_visit(node)

    visit_ListComp = _visit_comp
    visit_DictComp = _visit_comp
    visit_GeneratorExp = _visit_comp

    def visit_SetComp(self, node):
        # Building a set is order-free; only its ITERATION sources
        # matter.
        for gen in node.generators:
            self._check_iter(gen.iter)
        self.generic_visit(node)

    # -- ambient calls ---------------------------------------------------
    def visit_Call(self, node):
        fname = _dotted(node.func)
        if fname is not None:
            if fname in WALL_CALLS:
                self._add("wall_clock", node, f"{fname}()")
            elif fname in MONO_CALLS:
                self._add("monotonic", node, f"{fname}()")
            elif fname in _RNG_EXACT or \
                    fname.startswith(_RNG_PREFIXES):
                self._add("rng", node, f"{fname}()")
            elif fname in _THREAD_CALLS:
                self._add("thread_spawn", node, f"{fname}(...)")
            elif fname in _IO_EXACT or fname.startswith(_IO_PREFIXES):
                self._add("blocking_io", node, f"{fname}(...)")
            elif fname in ("os.getenv", "os.environ.get"):
                self._add("env_read", node, fname)
            elif fname == "next" and node.args and \
                    isinstance(node.args[0], ast.Call) and \
                    _dotted(node.args[0].func) == "iter" and \
                    node.args[0].args and _is_set_expr(
                        node.args[0].args[0], self.settish):
                self._add("hash_order", node,
                          "next(iter(<set>)) picks in hash order")
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "pop" and not node.args and \
                _is_set_expr(node.func.value, self.settish):
            self._add("hash_order", node,
                      "set.pop() picks in hash order")
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if _dotted(node) == "os.environ":
            self._add("env_read", node, "os.environ")
        self.generic_visit(node)

    def visit_Global(self, node):
        self._add("global_mutation", node,
                  "global " + ", ".join(node.names))
        self.generic_visit(node)


def direct_effects(path: str, func_node: ast.AST,
                   set_attrs: Optional[Set[str]] = None) \
        -> Tuple[Effect, ...]:
    """The lexical ambient effects of one function/method body."""
    walker = _EffectWalk(path, set_attrs or set())
    for stmt in getattr(func_node, "body", []):
        walker.visit(stmt)
    return tuple(walker.effects)


# ---------------------------------------------------------------------------
# transitive closure over the call graph
# ---------------------------------------------------------------------------


class EffectIndex:
    """Memoized direct + transitive effect sets over a project model.

    Propagation mirrors ``proto_rules._acquired_closure``: self calls
    (through lexical inheritance), typed-collaborator attr calls, and
    same-module function calls; bounded depth, cycle-safe."""

    def __init__(self, model: ProjectModel):
        self.model = model
        self._direct: Dict[int, Tuple[Effect, ...]] = {}
        self._set_attrs: Dict[int, Set[str]] = {}
        self._closure: Dict[Tuple[str, str, str], FrozenSet[Effect]] \
            = {}

    # -- direct ----------------------------------------------------------
    def _class_set_attrs(self, ci: ClassInfo) -> Set[str]:
        got = self._set_attrs.get(id(ci.node))
        if got is None:
            got = set_attrs_of_class(ci.node) \
                if isinstance(ci.node, ast.ClassDef) else set()
            self._set_attrs[id(ci.node)] = got
        return got

    def direct_of(self, path: str, mi: MethodInfo,
                  ci: Optional[ClassInfo] = None) -> Tuple[Effect, ...]:
        got = self._direct.get(id(mi.node))
        if got is None:
            attrs = self._class_set_attrs(ci) if ci is not None \
                else set()
            got = direct_effects(path, mi.node, attrs)
            self._direct[id(mi.node)] = got
        return got

    # -- transitive ------------------------------------------------------
    def method_closure(self, class_name: str, method: str,
                       _seen: Optional[Set[Tuple[str, str]]] = None,
                       _depth: int = 0) -> FrozenSet[Effect]:
        key = ("m", class_name, method)
        cached = self._closure.get(key)
        if cached is not None:
            return cached
        seen = _seen if _seen is not None else set()
        if (class_name, method) in seen or _depth > 6:
            return frozenset()
        seen.add((class_name, method))
        got = self.model.resolve_method(class_name, method)
        if got is None:
            return frozenset()
        ci, mi = got
        out: Set[Effect] = set(self.direct_of(ci.path, mi, ci))
        for callee in mi.self_calls:
            out |= self.method_closure(class_name, callee, seen,
                                       _depth + 1)
        for attr, meth in mi.attr_calls:
            for cname in ci.attr_types.get(attr, set()):
                out |= self.method_closure(cname, meth, seen,
                                           _depth + 1)
        for fname in mi.func_calls:
            out |= self.func_closure(ci.path, fname, seen, _depth + 1)
        if _seen is None:  # only memoize complete (non-reentrant) runs
            self._closure[key] = frozenset(out)
        return frozenset(out)

    def func_closure(self, path: str, func: str,
                     _seen: Optional[Set[Tuple[str, str]]] = None,
                     _depth: int = 0) -> FrozenSet[Effect]:
        key = ("f", path, func)
        cached = self._closure.get(key)
        if cached is not None:
            return cached
        seen = _seen if _seen is not None else set()
        skey = (f"<mod:{path}>", func)
        if skey in seen or _depth > 6:
            return frozenset()
        seen.add(skey)
        fmi = self.model.module_funcs.get(path, {}).get(func)
        if fmi is None:
            return frozenset()
        out: Set[Effect] = set(self.direct_of(path, fmi))
        for fname in fmi.func_calls:
            out |= self.func_closure(path, fname, seen, _depth + 1)
        if _seen is None:
            self._closure[key] = frozenset(out)
        return frozenset(out)

    def class_closure(self, class_name: str, ci: ClassInfo) \
            -> FrozenSet[Effect]:
        """Union over every method — a policy OBJECT is sim-ready only
        when its whole surface is (the simulator drives all of it)."""
        out: Set[Effect] = set()
        for mname in sorted(ci.methods):
            out |= self.method_closure(class_name, mname)
        return frozenset(out)


def effects_summary(effects: Iterable[Effect]) -> List[str]:
    """Sorted distinct kinds — the manifest form."""
    return sorted({e.kind for e in effects})
