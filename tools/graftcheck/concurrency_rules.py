"""Concurrency control-plane rules (CC101–CC104).

Aimed at the threaded master/agent: heartbeat loops, watchers, and
RPC retry paths where a torn read or a swallowed exception shows up as
a hung job hours later.  Lock regions are recognized lexically:
``with self.<attr>:`` where ``<attr>`` was assigned a
``threading.Lock/RLock/Condition`` in the class, or any ``with`` whose
context name contains "lock"/"cond".  ``acquire()``/``release()``
pairs are NOT tracked — the repo idiom is ``with``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding
from .jax_rules import _Ancestry, _ancestors, _dotted

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}


def _lock_attrs_of_class(cls: ast.ClassDef) -> Set[str]:
    """Attrs assigned from a threading lock factory anywhere in the
    class: ``self._lock = threading.Lock()``."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not (isinstance(v, ast.Call)):
            continue
        fname = None
        if isinstance(v.func, ast.Attribute):
            fname = v.func.attr
        elif isinstance(v.func, ast.Name):
            fname = v.func.id
        if fname not in _LOCK_FACTORIES:
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out.add(t.attr)
    return out


def _is_lock_expr(expr, lock_attrs: Set[str]) -> bool:
    name = _dotted(expr)
    if name is None and isinstance(expr, ast.Call):
        # with self._lock_for(x): / with lock() styles
        name = _dotted(expr.func)
    if name is None:
        return False
    last = name.split(".")[-1].lower()
    if isinstance(expr, ast.Attribute) and expr.attr in lock_attrs:
        return True
    return "lock" in last or "cond" in last


def _self_write_target(node) -> Optional[str]:
    """The self attr a statement mutates: ``self.X = ...``,
    ``self.X += ...``, ``self.X[k] = ...``."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    for t in targets:
        base = t
        while isinstance(base, ast.Subscript):
            base = base.value
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"):
            return base.attr
    return None


class _LockWalk(ast.NodeVisitor):
    """Walk one function recording (a) self-attr writes with their
    lock state and (b) time.sleep calls under a lock.  Nested defs
    reset the lock state: a closure defined under ``with lock`` does
    not RUN under it."""

    def __init__(self, lock_attrs: Set[str], path: str):
        self.lock_attrs = lock_attrs
        self.path = path
        self.locked = False
        self.writes: List[Tuple[str, int, bool]] = []  # attr, line, locked
        self.sleeps: List[Finding] = []

    def visit_With(self, node):
        entered = any(
            _is_lock_expr(item.context_expr, self.lock_attrs)
            for item in node.items
        )
        prev, self.locked = self.locked, self.locked or entered
        for child in node.body:
            self.visit(child)
        self.locked = prev

    visit_AsyncWith = visit_With

    def _visit_fn(self, node):
        prev, self.locked = self.locked, False
        self.generic_visit(node)
        self.locked = prev

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node):
        f = node.func
        is_sleep = (
            (isinstance(f, ast.Attribute) and f.attr == "sleep"
             and isinstance(f.value, ast.Name)
             and f.value.id == "time")
            or (isinstance(f, ast.Name) and f.id == "sleep")
        )
        if is_sleep and self.locked:
            self.sleeps.append(Finding(
                "CC102", self.path, node.lineno,
                "time.sleep while holding a lock stalls every thread "
                "contending for it — sleep outside, or use "
                "Condition.wait with a timeout",
            ))
        self.generic_visit(node)

    def generic_visit(self, node):
        attr = _self_write_target(node)
        if attr is not None:
            self.writes.append((attr, node.lineno, self.locked))
        super().generic_visit(node)


def _check_lock_discipline(tree, path, findings) -> None:
    """CC101 + CC102, per class."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        lock_attrs = _lock_attrs_of_class(cls)
        per_attr: Dict[str, Dict[str, List[Tuple[int, str]]]] = {}
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            w = _LockWalk(lock_attrs, path)
            for stmt in meth.body:
                w.visit(stmt)
            findings.extend(w.sleeps)
            if not lock_attrs:
                continue  # CC101 needs a lock to measure against
            for attr, line, locked in w.writes:
                if attr in lock_attrs:
                    continue
                slot = per_attr.setdefault(
                    attr, {"locked": [], "bare": []}
                )
                slot["locked" if locked else "bare"].append(
                    (line, meth.name)
                )
        for attr, slot in per_attr.items():
            if not slot["locked"]:
                continue
            bare = [(ln, m) for ln, m in slot["bare"]
                    if m != "__init__"]
            for line, meth_name in bare:
                lk_line, lk_meth = slot["locked"][0]
                findings.append(Finding(
                    "CC101", path, line,
                    f"self.{attr} written without the lock in "
                    f"{meth_name}() but written under it in "
                    f"{lk_meth}() (line {lk_line}) — take the lock or "
                    "document single-threaded ownership",
                ))
    # Module-level / function-level sleeps-under-lock outside classes.
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w = _LockWalk(set(), path)
            for stmt in node.body:
                w.visit(stmt)
            findings.extend(w.sleeps)


def _check_threads(tree, path, findings) -> None:
    """CC103: a non-daemon Thread never joined and never flipped to
    daemon — it pins interpreter shutdown."""
    joined_attrs: Set[str] = set()
    daemon_flipped: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("join",
                                                           "setDaemon"):
                name = _dotted(f.value)
                if name:
                    target = name.split(".")[-1]
                    (joined_attrs if f.attr == "join"
                     else daemon_flipped).add(target)
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon":
                    name = _dotted(t.value)
                    if name:
                        daemon_flipped.add(name.split(".")[-1])
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_thread = (
            (isinstance(f, ast.Attribute) and f.attr == "Thread")
            or (isinstance(f, ast.Name) and f.id == "Thread")
        )
        if not is_thread:
            continue
        daemon_kw = next(
            (kw for kw in node.keywords if kw.arg == "daemon"), None
        )
        if daemon_kw is not None:
            if (isinstance(daemon_kw.value, ast.Constant)
                    and daemon_kw.value.value is False):
                pass  # explicit daemon=False: still needs a join
            else:
                continue  # daemon=True or a runtime expression
        bound = None
        for anc in _ancestors(node):
            if isinstance(anc, ast.Assign):
                for t in anc.targets:
                    name = _dotted(t)
                    if name:
                        bound = name.split(".")[-1]
                break
            if isinstance(anc, (ast.stmt,)):
                break
        if bound is not None and (bound in joined_attrs
                                  or bound in daemon_flipped):
            continue
        where = (f"bound to {bound!r} but" if bound is not None
                 else "anonymous and")
        findings.append(Finding(
            "CC103", path, node.lineno,
            f"non-daemon Thread is {where} never joined (and never "
            "set daemon) — it blocks interpreter shutdown; pass "
            "daemon=True or join it on stop",
        ))


def _is_broad_type(t) -> bool:
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name):
        return t.id in ("Exception", "BaseException")
    if isinstance(t, ast.Tuple):
        return any(_is_broad_type(e) for e in t.elts)
    return False


def _check_swallowed(tree, path, findings) -> None:
    """CC104: broad except with a pass-only body."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad_type(node.type):
            continue
        body_is_noop = all(
            isinstance(s, (ast.Pass, ast.Continue))
            or (isinstance(s, ast.Expr)
                and isinstance(s.value, ast.Constant))
            for s in node.body
        )
        if body_is_noop:
            findings.append(Finding(
                "CC104", path, node.lineno,
                "broad except with a pass-only body swallows every "
                "error (RPC faults included) — log it, narrow the "
                "type, or re-raise",
            ))


def check(tree: ast.Module, path: str) -> Iterable[Finding]:
    _Ancestry().visit(tree)
    findings: List[Finding] = []
    _check_lock_discipline(tree, path, findings)
    _check_threads(tree, path, findings)
    _check_swallowed(tree, path, findings)
    uniq: Dict[Tuple[str, int], Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, f.line), f)
    return list(uniq.values())
