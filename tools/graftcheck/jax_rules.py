"""JAX data-plane rules (JX001–JX005).

Scope detection is deliberately lexical: a function is "jit scope" if
it is decorated with a jit-like decorator (``@jax.jit``, ``@jit``,
``@pjit``, ``@functools.partial(jax.jit, ...)``) or if its name is
passed to a jit-like call in the SAME lexical scope as its ``def``
(``self._step = jax.jit(step)`` with ``step`` defined in the same
method).  The same-scope restriction is what keeps a method and an
unrelated nested helper that happen to share a name from
contaminating each other; the cost is that a module-level function
jitted from inside some other scope is not treated as jit scope.
Lambdas passed to jit count too,
as do functions wrapped through one transform level
(``jax.jit(jax.grad(loss))``).  Nested ``def``s inside a jitted
function are traced with it, so their parameters are traced values as
well (the ``lax.scan`` body-carry idiom).

Everything here is a linter heuristic, not an interpreter: a finding
means "this shape is how the bug class looks", and a deliberate,
correct instance is suppressed WITH a justification at the site.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding

_JIT_NAMES = {"jit", "pjit"}
_JIT_ATTRS = {"jit", "pjit", "pmap"}
# jax.random callees that MINT or DERIVE keys rather than consume them.
_KEY_NONCONSUMING = {"split", "PRNGKey", "key", "fold_in", "clone",
                     "wrap_key_data", "key_data"}
_NUMPY_ALIASES = {"np", "onp", "numpy", "jnp"}
_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


def _is_jit_callee(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_ATTRS
    return False


def _is_jit_factory(call: ast.Call) -> bool:
    """``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    if _is_jit_callee(call.func):
        return True
    f = call.func
    is_partial = (
        (isinstance(f, ast.Attribute) and f.attr == "partial")
        or (isinstance(f, ast.Name) and f.id == "partial")
    )
    return (is_partial and bool(call.args)
            and _is_jit_callee(call.args[0]))


def _has_jit_decorator(fn) -> bool:
    for dec in fn.decorator_list:
        if _is_jit_callee(dec):
            return True
        if isinstance(dec, ast.Call) and _is_jit_factory(dec):
            return True
    return False


def _dotted(node: ast.AST) -> Optional[str]:
    """'self._rng' for Attribute chains, 'key' for Names, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _walk_scope(node: ast.AST, skip_nested=True):
    """Yield nodes of ``node``'s body without descending into nested
    function/class scopes (their bindings are separate)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if skip_nested and isinstance(
            child,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
             ast.ClassDef),
        ):
            continue
        stack.extend(ast.iter_child_nodes(child))


class _Ancestry(ast.NodeVisitor):
    """Annotate every node with a ``_gc_parent`` backlink."""

    def visit(self, node):
        for child in ast.iter_child_nodes(node):
            child._gc_parent = node
        self.generic_visit(node)


def _ancestors(node):
    node = getattr(node, "_gc_parent", None)
    while node is not None:
        yield node
        node = getattr(node, "_gc_parent", None)


def _scope_of(node):
    """Nearest enclosing scope node (requires _Ancestry annotation)."""
    for anc in _ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.ClassDef, ast.Module)):
            return anc
    return None


def _collect_jit_roots(tree: ast.Module):
    """Functions/lambdas that become jit-compiled callables.  A name
    passed to ``jax.jit(name)`` only marks defs in the SAME lexical
    scope as the jit call — a method and a nested helper sharing a
    name must not contaminate each other."""
    jitted_names: Dict[str, Set[int]] = {}
    roots: List[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_factory(node):
            args = list(node.args)
            if not _is_jit_callee(node.func) and args:
                args = args[1:]  # partial(jax.jit, ...) carries jit
            for arg in args[:1]:
                # one transform level deep: jax.jit(jax.grad(loss))
                if isinstance(arg, ast.Call):
                    arg = arg.args[0] if arg.args else arg
                if isinstance(arg, ast.Name):
                    jitted_names.setdefault(arg.id, set()).add(
                        id(_scope_of(node))
                    )
                elif isinstance(arg, ast.Lambda):
                    roots.append(arg)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            same_scope = id(_scope_of(node)) in jitted_names.get(
                node.name, set()
            )
            if _has_jit_decorator(node) or same_scope:
                roots.append(node)
    return roots


def _params_of(fn) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


class _TracedRoots(ast.NodeVisitor):
    """Root Names an expression's VALUE depends on, pruning subtrees
    that are static under trace: ``len(x)``, ``x.shape``/``ndim``/
    ``dtype``/``size``, ``isinstance``/``hasattr``/``getattr``/
    ``type`` calls (Python-level, resolved at trace time)."""

    STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "type",
                    "range"}
    STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

    def __init__(self):
        self.names: Set[str] = set()

    def visit_Call(self, node):
        if (isinstance(node.func, ast.Name)
                and node.func.id in self.STATIC_CALLS):
            return
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if node.attr in self.STATIC_ATTRS:
            return
        self.generic_visit(node)

    def visit_Name(self, node):
        self.names.add(node.id)


def _traced_roots(expr) -> Set[str]:
    v = _TracedRoots()
    v.visit(expr)
    return v.names


def _is_none_check(test) -> bool:
    """``x is None`` / ``x is not None`` — static under trace."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops))


def _check_jit_scope(root, path: str, findings: List[Finding]) -> None:
    """JX001 + JX002 inside one jit root (nested defs included)."""
    # Params of the root and of every nested def are all traced (the
    # lax.scan body-carry idiom nests defs inside the jitted fn).
    traced: Set[str] = set()
    for node in ast.walk(root):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            traced |= _params_of(node)
    for node in ast.walk(root):
        if isinstance(node, (ast.If, ast.While)):
            if _is_none_check(node.test):
                continue
            hit = _traced_roots(node.test) & traced
            if hit:
                kind = ("while" if isinstance(node, ast.While)
                        else "if")
                findings.append(Finding(
                    "JX001", path, node.lineno,
                    f"`{kind}` branches on traced value "
                    f"{sorted(hit)[0]!r} inside a jitted function — "
                    "use jnp.where/lax.cond or hoist the branch",
                ))
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Name) and f.id == "float"
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)):
                findings.append(Finding(
                    "JX002", path, node.lineno,
                    "float() on a traced value inside jit forces a "
                    "host sync (ConcretizationTypeError at trace)",
                ))
            elif isinstance(f, ast.Attribute) and f.attr in (
                "item", "block_until_ready",
            ):
                findings.append(Finding(
                    "JX002", path, node.lineno,
                    f".{f.attr}() inside jit scope is a host sync on "
                    "a tracer",
                ))
            elif (isinstance(f, ast.Attribute)
                  and f.attr in ("asarray", "array")
                  and isinstance(f.value, ast.Name)
                  and f.value.id in _NUMPY_ALIASES - {"jnp"}):
                findings.append(Finding(
                    "JX002", path, node.lineno,
                    f"{f.value.id}.{f.attr}() inside jit scope pulls "
                    "the value to host — use jnp",
                ))


def _check_jit_in_loop(tree, path, findings) -> None:
    """JX003: a jit factory call lexically under a for/while (before
    the nearest enclosing function boundary)."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jit_factory(node)):
            continue
        for anc in _ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
            if isinstance(anc, (ast.For, ast.While)):
                findings.append(Finding(
                    "JX003", path, node.lineno,
                    "jax.jit called inside a loop body builds a fresh "
                    "callable each iteration — jit caches by function "
                    "identity, so this recompiles every pass; hoist "
                    "or memoize it",
                ))
                break


def _bindings_in(scope_node) -> List[Tuple[str, int]]:
    """(dotted-name, line) for every binding in one function scope."""
    out: List[Tuple[str, int]] = []

    def targets_of(node):
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AugAssign, ast.AnnAssign,
                             ast.NamedExpr)):
            return [node.target]
        if isinstance(node, ast.For):
            return [node.target]
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # withitems carry no lineno of their own — bind them at
            # the With statement's line.
            return [item.optional_vars for item in node.items
                    if item.optional_vars is not None]
        return []

    def flatten(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from flatten(e)
        elif t is not None:
            name = _dotted(t)
            if name:
                yield name

    for node in _walk_scope(scope_node):
        for t in targets_of(node):
            for name in flatten(t):
                out.append((name, node.lineno))
    return out


def _check_key_reuse(tree, path, findings) -> None:
    """JX004: the same key name consumed twice with no rebinding in
    between, or consumed inside a loop that never rebinds it."""
    scopes = [tree] + [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for scope in scopes:
        uses: Dict[str, List[ast.Call]] = {}
        for node in _walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and isinstance(f.value, (ast.Name, ast.Attribute))
                    and _dotted(f.value) is not None
                    and _dotted(f.value).split(".")[-1] == "random"
                    and f.attr not in _KEY_NONCONSUMING):
                continue
            if not node.args:
                continue
            key = _dotted(node.args[0])
            if key:
                uses.setdefault(key, []).append(node)
        if not uses:
            continue
        binds = _bindings_in(scope)
        flagged: Set[Tuple[str, int]] = set()
        for key, calls in uses.items():
            calls.sort(key=lambda c: c.lineno)
            lines = sorted(ln for n, ln in binds if n == key)
            for prev, cur in zip(calls, calls[1:]):
                rebound = any(
                    prev.lineno < ln <= cur.lineno for ln in lines
                )
                if not rebound and (key, cur.lineno) not in flagged:
                    flagged.add((key, cur.lineno))
                    findings.append(Finding(
                        "JX004", path, cur.lineno,
                        f"PRNG key {key!r} already consumed at line "
                        f"{prev.lineno} — split it (reuse makes "
                        "\"random\" draws identical)",
                    ))
            # Loop form: consumed each iteration, never rebound inside.
            for call in calls:
                for anc in _ancestors(call):
                    if isinstance(anc, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.Lambda)):
                        break
                    if isinstance(anc, (ast.For, ast.While)):
                        span = (anc.lineno,
                                max(getattr(anc, "end_lineno",
                                            anc.lineno), anc.lineno))
                        rebound = any(
                            n == key and span[0] <= ln <= span[1]
                            for n, ln in binds
                        )
                        if (not rebound
                                and (key, call.lineno) not in flagged):
                            flagged.add((key, call.lineno))
                            findings.append(Finding(
                                "JX004", path, call.lineno,
                                f"PRNG key {key!r} consumed inside a "
                                "loop without a per-iteration split — "
                                "every iteration draws the same "
                                "randomness",
                            ))
                        break


def _static_positions(call: ast.Call) -> Optional[List[int]]:
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return [v.value]
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if (isinstance(e, ast.Constant)
                            and isinstance(e.value, int)):
                        out.append(e.value)
                return out
    return None


def _check_static_argnums(tree, path, findings) -> None:
    """JX005: list/dict/set (unhashable) passed in a static position —
    jit hashes static args to key its compile cache; this raises at
    call time."""
    static_fns: Dict[str, List[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            if _is_jit_factory(node.value):
                pos = _static_positions(node.value)
                if pos:
                    for t in node.targets:
                        name = _dotted(t)
                        if name:
                            static_fns[name] = pos
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_factory(dec):
                    pos = _static_positions(dec)
                    if pos:
                        static_fns[node.name] = pos

    def check_call(call: ast.Call, pos: List[int]):
        for i in pos:
            if i < len(call.args) and isinstance(call.args[i],
                                                 _UNHASHABLE):
                findings.append(Finding(
                    "JX005", path, call.args[i].lineno,
                    f"unhashable argument in static_argnums position "
                    f"{i} — jit keys its compile cache by hashing "
                    "static args; pass a tuple/frozen value",
                ))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name in static_fns:
            check_call(node, static_fns[name])
        elif (isinstance(node.func, ast.Call)
              and _is_jit_factory(node.func)):
            pos = _static_positions(node.func)
            if pos:
                check_call(node, pos)


def check(tree: ast.Module, path: str) -> Iterable[Finding]:
    _Ancestry().visit(tree)
    findings: List[Finding] = []
    seen: Set[int] = set()
    for root in _collect_jit_roots(tree):
        if id(root) in seen:
            continue
        seen.add(id(root))
        _check_jit_scope(root, path, findings)
    _check_jit_in_loop(tree, path, findings)
    _check_key_reuse(tree, path, findings)
    _check_static_argnums(tree, path, findings)
    # One finding per (rule, line): nested jit roots can overlap.
    uniq: Dict[Tuple[str, int], Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, f.line), f)
    return list(uniq.values())
