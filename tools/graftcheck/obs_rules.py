"""Observability rules (OB3xx).

OB301: a ``time.time()`` delta used as a duration/deadline.  Wall
clocks STEP — NTP slews and jumps bend any subtraction of two wall
instants (the PR-9 registry leases were bitten by exactly this; the
reader-side observation window was the fix).  Durations and local
deadlines must use ``time.monotonic()`` / ``time.perf_counter()``.
The legitimate exceptions — comparing wall TIMESTAMPS that crossed a
process boundary (heartbeats, diagnosis reports), where wall time is
the point — carry a justified suppression.

(OB301 covers wall deltas used as *durations*; its v3 cousin DET705 —
``effect_rules.py`` — covers wall stamps recorded into *stored*
decision/audit state that replay compares.)

Detection is lexical, matching the repo idiom: a ``Sub`` expression
where either operand is *wallish* — a direct ``time.time()`` /
``_time.time()`` call, a local name assigned from one in the same
function, or a ``self.<attr>`` assigned from one anywhere in the
enclosing class.  Sums (``time.time() + timeout``) are untouched:
building a wall deadline is only a hazard when it is later
subtracted, and that subtraction is what gets flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import Finding

_WALL_CALLS = {"time.time", "_time.time"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_wall_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and _dotted(node.func) in _WALL_CALLS
    )


def _assigned_names(node: ast.AST) -> List[str]:
    """Dotted targets of an assignment whose value is a wall call
    (``x = time.time()``, ``self._t0 = time.time()``, and the
    ``x = y or time.time()`` / ``timestamp or time.time()`` idiom)."""
    value = None
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        value, targets = node.value, list(node.targets)
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        value, targets = node.value, [node.target]
    if value is None:
        return []
    wall = _is_wall_call(value) or (
        isinstance(value, ast.BoolOp)
        and any(_is_wall_call(v) for v in value.values)
    )
    if not wall:
        return []
    out = []
    for t in targets:
        name = _dotted(t)
        if name:
            out.append(name)
    return out


def _wall_attrs_of_class(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        for name in _assigned_names(node):
            if name.startswith("self."):
                out.add(name)
    return out


class _SubWalk(ast.NodeVisitor):
    """One function's walk: track wallish local names, flag Subs."""

    def __init__(self, path: str, wall_attrs: Set[str]):
        self.path = path
        self.wall_attrs = wall_attrs
        self.local: Set[str] = set()
        self.findings: List[Finding] = []

    def _wallish(self, node: ast.AST) -> bool:
        if _is_wall_call(node):
            return True
        name = _dotted(node)
        if name is None:
            return False
        return name in self.local or name in self.wall_attrs

    def visit_Assign(self, node):
        for name in _assigned_names(node):
            self.local.add(name)
        self.generic_visit(node)

    visit_AnnAssign = visit_Assign
    visit_AugAssign = visit_Assign

    def visit_BinOp(self, node):
        if isinstance(node.op, ast.Sub) and (
            self._wallish(node.left) or self._wallish(node.right)
        ):
            self.findings.append(Finding(
                "OB301", self.path, node.lineno,
                "time.time() delta used as a duration/deadline — the "
                "wall clock steps under NTP (the PR-9 lease bug); use "
                "time.monotonic()/perf_counter(), or suppress where "
                "cross-process wall timestamps are the point",
            ))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # Nested defs get their own scope walk from check(); don't
        # leak this scope's names into them.
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def check(tree: ast.Module, path: str) -> Iterable[Finding]:
    findings: List[Finding] = []
    # Map every function to its enclosing class's wallish attrs.
    class_of = {}
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef):
            attrs = _wall_attrs_of_class(cls)
            for node in ast.walk(cls):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    class_of.setdefault(node, attrs)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w = _SubWalk(path, class_of.get(node, set()))
            for stmt in node.body:
                w.visit(stmt)
            findings.extend(w.findings)
    # Module-level statements (rare; scripts).
    w = _SubWalk(path, set())
    for stmt in tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            w.visit(stmt)
    findings.extend(w.findings)
    uniq = {}
    for f in findings:
        uniq.setdefault((f.rule, f.line), f)
    return list(uniq.values())
