import sys

from .engine import main

if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `graftcheck ... | head` closed the pipe: not an error.
        sys.exit(0)
