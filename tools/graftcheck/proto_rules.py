"""graftcheck pass 2: whole-program protocol rules (PC/LK/CH/MT).

These run over the :mod:`project_model`, not a single file — each rule
checks a contract that only exists BETWEEN modules:

PC4xx — RPC contracts
    PC401  a message type constructed at a ``.call(...)`` site that no
           dispatch table or ``isinstance`` handler anywhere accepts;
    PC402  a dispatch-table entry for a name that is not a registered
           message class;
    PC403  a call site retried with ``idempotent=True`` whose handler
           destructively consumes state without reading an idempotency
           token (``token``/``attempt_id``/``req_id``) — the PR-2
           Heartbeat destructive-retry bug, now a lint;
    PC404  a mutating manager method reachable from a journaled
           servicer's handler that never appends to the control-state
           journal (``_jrec``) — on the HA path the ack would precede
           (or never get) the ControlStateJournal append, so a warm
           standby adopts state missing that mutation;
    PC405  a message class in a messages module that nothing outside
           its defining file references (dead protocol surface).

LK2xx — lock discipline
    LK201  a cycle in the whole-program lock-order graph (edges from
           lexically nested ``with`` acquisitions plus the one-level
           call graph), or a nested re-acquisition of a plain
           non-reentrant ``Lock``;
    LK202  a ``self._*_locked(...)`` call made while no lock is held
           (and not from another ``*_locked`` method) — the documented
           caller-holds-the-lock contract, violated.

CH5xx — chaos coverage
    CH501  a site declared in ``SITES`` that no ``inject``/
           ``site_armed``/``has_site`` call (or site-string literal
           anywhere in product code) references;
    CH502  an injected site string that is not declared in ``SITES``
           (it can never fire — the plan parser rejects it);
    CH503  a declared site no test file mentions (an untested failure
           mode; only checked when the engine found a test tree).

MT6xx — metrics drift
    MT601  a counter name passed to ``.inc(...)`` that no gauge
           registration exports (invisible to operators — the inverse
           of the PR-12 registered-but-never-incremented warning);
    MT602  the same gauge name registered on two different lines of
           one module (one of the two callbacks is silently dark).

Every rule is lexical and conservative: unresolvable names make a rule
skip, not guess, and a deliberate instance is suppressed at the
anchoring line with a justified ``# graftcheck: disable=ID`` comment
like every other family.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding
from .project_model import ClassInfo, ProjectModel, _TOKEN_FIELDS

#: Manager methods PC404 never charges: replay/restore entry points run
#: journal-UNBOUND by design, and binding itself is not a mutation.
_PC404_EXEMPT = {"bind_journal", "load_state", "restore", "replay",
                 "apply", "rearm_clocks", "rearm_doing",
                 "rearm_deadline", "rearm_heartbeats"}


# ---------------------------------------------------------------------------
# shared handler analysis
# ---------------------------------------------------------------------------


def _servicer_mgr_types(model: ProjectModel,
                        servicer: ClassInfo) -> Dict[str, Set[str]]:
    """attr -> candidate manager class names for a dispatch-table
    servicer: the class's own ``self.x = Class()`` assignments plus
    constructor keywords resolved at every ``Servicer(kw=self.y)``
    call site (the masters wire managers in this way).  Memoized on
    the model — PC403 and PC404 both consult it per handler."""
    cache = getattr(model, "_mgr_types_cache", None)
    if cache is None:
        cache = model._mgr_types_cache = {}
    got = cache.get(id(servicer.node))
    if got is not None:
        return got
    out: Dict[str, Set[str]] = {
        k: set(v) for k, v in servicer.attr_types.items()
    }
    for path, node in model.ctor_calls.get(servicer.name, []):
        caller = _enclosing_classinfo(model, path, node)
        for kw in node.keywords:
            if kw.arg is None:
                continue
            classes = _expr_classes(model, caller, kw.value)
            if classes:
                out.setdefault(kw.arg, set()).update(classes)
    cache[id(servicer.node)] = out
    return out


def _enclosing_classinfo(model: ProjectModel, path: str,
                         node: ast.AST) -> Optional[ClassInfo]:
    from .jax_rules import _ancestors

    for anc in _ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return model.class_by_node.get(id(anc))
    return None


def _expr_classes(model: ProjectModel, caller: Optional[ClassInfo],
                  expr: ast.AST) -> Set[str]:
    """Candidate class names an expression evaluates to: a direct
    ``Class(...)`` construction, ``self.x`` resolved through the
    caller's typed attributes, or a dict of either."""
    out: Set[str] = set()
    if isinstance(expr, ast.Call):
        f = expr.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None
        )
        if name and name[0].isupper():
            out.add(name)
    elif isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id == "self" and caller is not None:
        out |= caller.attr_types.get(expr.attr, set())
    elif isinstance(expr, ast.Dict):
        for v in expr.values:
            out |= _expr_classes(model, caller, v)
    return out


def _local_mgr_types(servicer: Optional[ClassInfo], meth,
                     mgr_types: Dict[str, Set[str]]) \
        -> Dict[str, Set[str]]:
    """Handler-local variables typed to manager classes: ``mgr =
    self.rdzv_managers.get(...)`` or ``mgr = self._rdzv(name)`` where
    the helper's body touches a manager attribute.  ``meth`` is an AST
    node or a list of statements."""
    out: Dict[str, Set[str]] = {}
    if servicer is None:
        return out
    stmts = meth if isinstance(meth, list) else [meth]

    def classes_of_value(value: ast.AST,
                         depth: int = 0) -> Set[str]:
        found: Set[str] = set()
        for sub in ast.walk(value):
            if (isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"):
                if sub.attr in mgr_types:
                    found |= mgr_types[sub.attr]
                elif depth < 1:
                    helper = servicer.methods.get(sub.attr)
                    if helper is not None:
                        for stmt in ast.walk(helper.node):
                            if isinstance(stmt, (ast.Return,
                                                 ast.Assign)):
                                v = getattr(stmt, "value", None)
                                if v is not None:
                                    found |= classes_of_value(
                                        v, depth + 1
                                    )
        return found

    for stmt in stmts:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign):
                continue
            classes = classes_of_value(node.value)
            if not classes:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, set()).update(classes)
    return out


def _manager_calls(meth: ast.AST, servicer: ClassInfo,
                   mgr_types: Dict[str, Set[str]]) \
        -> List[Tuple[Set[str], str, int]]:
    """(candidate classes, method, line) for every manager-method call
    a handler makes — ``self.<mgr>.<m>(...)`` and typed-local
    ``var.<m>(...)`` forms."""
    local = _local_mgr_types(servicer, meth, mgr_types)
    out: List[Tuple[Set[str], str, int]] = []
    for node in ast.walk(meth):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        f = node.func
        if (isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"
                and f.value.attr in mgr_types):
            out.append((mgr_types[f.value.attr], f.attr, node.lineno))
        elif isinstance(f.value, ast.Name) and f.value.id in local:
            out.append((local[f.value.id], f.attr, node.lineno))
    return out


def _mentions_token_field(body: Iterable[ast.AST]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Attribute) and \
                    node.attr in _TOKEN_FIELDS:
                return True
    return False


def _handler_bodies(model: ProjectModel, msg: str) \
        -> List[Tuple[str, int, List[ast.AST], Optional[ClassInfo],
                      str]]:
    """(path, line, body statements, servicer class, label) for every
    handler of message type ``msg`` — dict-dispatch methods plus
    isinstance-guarded blocks."""
    out = []
    for e in model.dispatch:
        if e.msg != msg or e.cls is None:
            continue
        ci = model.class_by_node.get(id(e.cls))
        if ci is None:
            continue
        mi = ci.methods.get(e.handler)
        if mi is None:
            continue
        out.append((
            ci.path, mi.node.lineno, list(mi.node.body), ci,
            f"{ci.name}.{e.handler}",
        ))
    for h in model.iso_handlers:
        if h.msg != msg or h.func is None:
            continue
        ci = _enclosing_classinfo(model, h.path, h.func)
        # Positive ``if isinstance(msg, X):`` guards scope the handler
        # to the If body; the negated early-return idiom (``if not
        # isinstance: return``) means the whole function IS the
        # handler.
        body: List[ast.AST] = list(h.func.body)
        label = getattr(h.func, "name", "<handler>")
        for node in ast.walk(h.func):
            if isinstance(node, ast.If) and \
                    node.lineno <= h.line and any(
                        getattr(n, "lineno", -1) == h.line
                        and isinstance(n, ast.Call)
                        for n in ast.walk(node.test)
                    ):
                negated = isinstance(node.test, ast.UnaryOp) and \
                    isinstance(node.test.op, ast.Not)
                if not negated:
                    body = list(node.body)
                break
        if ci is not None:
            label = f"{ci.name}.{label}"
        out.append((h.path, h.line, body, ci, label))
    return out


def _body_destructive(model: ProjectModel, body: List[ast.AST],
                      owner: Optional[ClassInfo],
                      mgr_types: Dict[str, Set[str]]) -> bool:
    """Does a handler body destructively consume state — directly, via
    a self method, or via a (resolvable) manager/collaborator call
    (including handler-local ``mgr = self._rdzv(...)`` typed vars)?"""
    local = _local_mgr_types(owner, body, mgr_types)
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Delete):
                return True
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            f = node.func
            if f.attr.startswith("pop"):
                from .jax_rules import _ancestors

                parent = next(iter(_ancestors(node)), None)
                if not isinstance(parent, ast.Expr):
                    return True
            # self.<m>() on the owner class.
            if (isinstance(f.value, ast.Name)
                    and f.value.id == "self" and owner is not None):
                if model.method_destructive(owner.name, f.attr):
                    return True
                continue
            # manager / typed-attribute calls.
            classes: Set[str] = set()
            if (isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id == "self"):
                attr = f.value.attr
                classes = mgr_types.get(attr, set())
                if not classes and owner is not None:
                    classes = owner.attr_types.get(attr, set())
            elif isinstance(f.value, ast.Name):
                classes = local.get(f.value.id, set())
            for cname in classes:
                if model.method_destructive(cname, f.attr):
                    return True
    return False


# ---------------------------------------------------------------------------
# PC4xx — RPC contracts
# ---------------------------------------------------------------------------


def _check_pc401(model: ProjectModel, findings: List[Finding]) -> None:
    handled = model.handled_messages()
    seen: Set[str] = set()
    for cs in model.call_sites:
        if cs.msg not in model.messages or cs.msg in handled:
            continue
        if cs.msg in seen:
            continue
        seen.add(cs.msg)
        findings.append(Finding(
            "PC401", cs.path, cs.line,
            f"message {cs.msg} is sent here but no dispatch table or "
            "isinstance handler anywhere accepts it — every call gets "
            "the servicer's 'unhandled message type' error",
        ))


def _check_pc402(model: ProjectModel, findings: List[Finding]) -> None:
    if not model.messages:
        return
    for e in model.dispatch:
        if e.msg not in model.messages:
            findings.append(Finding(
                "PC402", e.path, e.line,
                f"dispatch-table entry for {e.msg} which is not a "
                "registered Message subclass — the key can never "
                "match a deserialized request",
            ))


def _check_pc403(model: ProjectModel, findings: List[Finding]) -> None:
    seen: Set[Tuple[str, int]] = set()
    for cs in model.call_sites:
        if not cs.idempotent or cs.msg not in model.messages:
            continue
        for path, line, body, owner, label in \
                _handler_bodies(model, cs.msg):
            mgr_types: Dict[str, Set[str]] = {}
            if owner is not None:
                mgr_types = _servicer_mgr_types(model, owner)
            if _mentions_token_field(body):
                continue  # participates in the token protocol
            if not _body_destructive(model, body, owner, mgr_types):
                continue
            site = (cs.path, cs.line)
            if site in seen:
                continue
            seen.add(site)
            findings.append(Finding(
                "PC403", cs.path, cs.line,
                f"{cs.msg} is retried with idempotent=True but its "
                f"handler {label} destructively consumes state "
                "without reading an idempotency token — a "
                "DEADLINE-retried duplicate re-consumes (the "
                "Heartbeat destructive-retry bug class); drop the "
                "flag or thread a token the handler dedupes on",
            ))


def _model_has_journal(model: ProjectModel) -> bool:
    return any(
        "_jrec" in ci.methods or mi.has_jrec
        for lst in model.classes.values() for ci in lst
        for mi in ci.methods.values()
    )


def _check_pc404(model: ProjectModel, findings: List[Finding]) -> None:
    if not _model_has_journal(model):
        return
    reported: Set[Tuple[str, str]] = set()
    for e in model.dispatch:
        if e.cls is None:
            continue
        servicer = model.class_by_node.get(id(e.cls))
        if servicer is None:
            continue
        mi = servicer.methods.get(e.handler)
        if mi is None:
            continue
        mgr_types = _servicer_mgr_types(model, servicer)
        # Only journaled control planes are held to journal-before-ack:
        # a servicer none of whose managers ever journals (a gateway, a
        # test fixture) has its own durability story.
        plane_journaled = any(
            model.method_reaches_jrec(cname, m.name)
            for classes in mgr_types.values() for cname in classes
            for ci in model.classes_named(cname)
            for m in ci.methods.values()
        )
        if not plane_journaled:
            continue
        for classes, meth, line in _manager_calls(
                mi.node, servicer, mgr_types):
            if meth in _PC404_EXEMPT or meth.startswith("get") or \
                    meth.startswith("dump"):
                continue
            for cname in sorted(classes):
                got = model.resolve_method(cname, meth)
                if got is None:
                    continue
                owner_ci, owner_mi = got
                if not model.method_mutates(cname, meth):
                    continue
                if model.method_reaches_jrec(cname, meth):
                    continue
                key = (owner_ci.name, meth)
                if key in reported:
                    continue
                reported.add(key)
                findings.append(Finding(
                    "PC404", owner_ci.path, owner_mi.node.lineno,
                    f"{owner_ci.name}.{meth} mutates master state and "
                    f"is reachable from servicer handler "
                    f"{servicer.name}.{e.handler} ({e.msg}) but never "
                    "journals (_jrec): on the HA path the RPC acks "
                    "before any ControlStateJournal append, so a warm "
                    "standby loses this mutation — journal it, or "
                    "suppress documenting why the state is ephemeral",
                ))


def _check_pc405(model: ProjectModel, findings: List[Finding]) -> None:
    import re as _re

    for name, (path, line) in sorted(model.messages.items()):
        if not path.replace("\\", "/").endswith("messages.py"):
            continue
        if model.mentioned_outside(name, path):
            continue
        if model.test_text and _re.search(
                r"\b%s\b" % _re.escape(name), model.test_text):
            continue  # tests are consumers too (probe messages)
        findings.append(Finding(
            "PC405", path, line,
            f"message class {name} is referenced nowhere outside its "
            "defining module — dead protocol surface (no sender, no "
            "handler); delete it or wire it up",
        ))


# ---------------------------------------------------------------------------
# LK2xx — lock discipline
# ---------------------------------------------------------------------------


def _acquired_closure(model: ProjectModel, class_name: str,
                      method: str,
                      _seen: Optional[Set[Tuple[str, str]]] = None,
                      _depth: int = 0) -> Set[str]:
    """Every lock id a call into ``class_name.method`` may acquire,
    through the one-level-resolved call graph (bounded depth)."""
    seen = _seen if _seen is not None else set()
    key = (class_name, method)
    if key in seen or _depth > 6:
        return set()
    seen.add(key)
    got = model.resolve_method(class_name, method)
    if got is None:
        return set()
    ci, mi = got
    out = {acq for (_held, acq, _ln) in mi.acquires}
    for callee in mi.self_calls:
        out |= _acquired_closure(model, class_name, callee, seen,
                                 _depth + 1)
    for attr, meth in mi.attr_calls:
        for cname in ci.attr_types.get(attr, set()):
            out |= _acquired_closure(model, cname, meth, seen,
                                     _depth + 1)
    for fname in mi.func_calls:
        fmi = model.module_funcs.get(ci.path, {}).get(fname)
        if fmi is not None:
            out |= {acq for (_h, acq, _ln) in fmi.acquires}
    return out


def _lock_factory_of(model: ProjectModel, lock_id: str) \
        -> Optional[str]:
    """'Lock'/'RLock'/... for a ``module::Class.attr`` lock id when the
    attr was assigned from a known factory, else None."""
    if "::" not in lock_id:
        return None
    _mod, rest = lock_id.split("::", 1)
    if "." not in rest:
        return None
    cls_name, attr = rest.rsplit(".", 1)
    for ci in model.classes_named(cls_name):
        fac = ci.lock_attrs.get(attr)
        if fac:
            return fac
    return None


def _check_lk201(model: ProjectModel, findings: List[Finding]) -> None:
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add_edge(a: str, b: str, path: str, line: int) -> None:
        if a == b:
            return
        edges.setdefault((a, b), (path, line))

    all_infos = [ci for lst in model.classes.values() for ci in lst]
    for ci in all_infos:
        for mi in ci.methods.values():
            for held, acq, line in mi.acquires:
                if held is None:
                    continue
                if held == acq:
                    fac = _lock_factory_of(model, held)
                    if fac == "Lock":
                        findings.append(Finding(
                            "LK201", ci.path, line,
                            f"nested re-acquisition of non-reentrant "
                            f"lock {held.split('::')[-1]} in "
                            f"{ci.name}.{mi.name} — self-deadlock "
                            "(use RLock or restructure onto the "
                            "lock-inside pattern)",
                        ))
                    continue
                add_edge(held, acq, ci.path, line)
            for held, ref, line in mi.calls_under:
                targets: Set[str] = set()
                if ref.kind == "self":
                    targets = _acquired_closure(
                        model, ci.name, ref.method
                    )
                elif ref.kind == "attr":
                    for cname in ci.attr_types.get(ref.attr, set()):
                        targets |= _acquired_closure(
                            model, cname, ref.method
                        )
                elif ref.kind == "func":
                    fmi = model.module_funcs.get(ci.path, {}) \
                        .get(ref.method)
                    if fmi is not None:
                        targets = {
                            acq for (_h, acq, _l) in fmi.acquires
                        }
                for tgt in targets:
                    if tgt == held:
                        fac = _lock_factory_of(model, held)
                        if fac == "Lock":
                            findings.append(Finding(
                                "LK201", ci.path, line,
                                f"call under non-reentrant lock "
                                f"{held.split('::')[-1]} re-acquires "
                                f"it via {ref.method}() — "
                                "self-deadlock",
                            ))
                        continue
                    add_edge(held, tgt, ci.path, line)
    # Cycle detection over the edge set (iterative DFS).
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    color: Dict[str, int] = {}
    stack_path: List[str] = []
    cycles: List[List[str]] = []

    def dfs(start: str) -> None:
        stack: List[Tuple[str, Iterable[str]]] = \
            [(start, iter(graph[start]))]
        color[start] = 1
        stack_path.append(start)
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if color.get(nxt, 0) == 0:
                    color[nxt] = 1
                    stack_path.append(nxt)
                    stack.append((nxt, iter(graph[nxt])))
                    advanced = True
                    break
                if color.get(nxt) == 1:
                    i = stack_path.index(nxt)
                    cyc = stack_path[i:] + [nxt]
                    if len(cyc) > 2:
                        cycles.append(cyc)
            if not advanced:
                color[node] = 2
                stack_path.pop()
                stack.pop()

    for node in sorted(graph):
        if color.get(node, 0) == 0:
            dfs(node)
    reported: Set[frozenset] = set()
    for cyc in cycles:
        key = frozenset(cyc)
        if key in reported:
            continue
        reported.add(key)
        # Anchor at the lexically-first edge of the cycle.
        sites = []
        for a, b in zip(cyc, cyc[1:]):
            site = edges.get((a, b))
            if site is not None:
                sites.append(site)
        if not sites:
            continue
        path, line = min(sites)
        chain = " -> ".join(n.split("::")[-1] for n in cyc)
        findings.append(Finding(
            "LK201", path, line,
            f"lock-order cycle {chain}: two threads taking these "
            "locks in opposite orders deadlock — pick one global "
            "order or narrow one of the critical sections",
        ))


def _check_lk202(model: ProjectModel, findings: List[Finding]) -> None:
    for lst in model.classes.values():
        for ci in lst:
            if ci.name == "<module>":
                continue
            for mi in ci.methods.values():
                if mi.name.endswith("_locked"):
                    continue  # contract: the caller holds the lock
                for meth, line in mi.self_calls_unlocked:
                    if not (meth.startswith("_")
                            and meth.endswith("_locked")):
                        continue
                    findings.append(Finding(
                        "LK202", ci.path, line,
                        f"{ci.name}.{mi.name} calls self.{meth}() "
                        "without holding a lock — the _locked suffix "
                        "documents that the caller must hold the "
                        "object's lock; wrap the call in the lock or "
                        "rename the method",
                    ))


# ---------------------------------------------------------------------------
# CH5xx — chaos coverage
# ---------------------------------------------------------------------------


def _check_chaos(model: ProjectModel, findings: List[Finding]) -> None:
    if not model.chaos_sites:
        return
    injected = {i.name for i in model.injects}
    declared = set(model.chaos_sites)
    # A site referenced by LITERAL anywhere outside its declaring file
    # counts as injected (the master main's has_site tuple idiom).
    for site, decl in model.chaos_sites.items():
        if site in injected:
            continue
        referenced = any(
            site in fi.source
            for p, fi in model.files.items() if p != decl.path
        )
        if not referenced:
            findings.append(Finding(
                "CH501", decl.path, decl.line,
                f"chaos site {site!r} is declared in SITES but no "
                "injection point references it — it can never fire; "
                "wire an inject() or delete the declaration",
            ))
    for i in model.injects:
        if i.name not in declared:
            findings.append(Finding(
                "CH502", i.path, i.line,
                f"inject site {i.name!r} is not declared in "
                "chaos.SITES — FaultSpec.parse rejects any plan "
                "naming it, so this injection point is dead; declare "
                "it or fix the string",
            ))
    if model.test_text:
        for site, decl in sorted(model.chaos_sites.items()):
            if site not in model.test_text:
                findings.append(Finding(
                    "CH503", decl.path, decl.line,
                    f"chaos site {site!r} is referenced by no test — "
                    "an untested failure mode is a claim, not a "
                    "property; add a unit/e2e that arms it",
                ))


# ---------------------------------------------------------------------------
# MT6xx — metrics drift
# ---------------------------------------------------------------------------


def _check_metrics(model: ProjectModel,
                   findings: List[Finding]) -> None:
    if model.gauge_regs:
        exported: Set[str] = set()
        for g in model.gauge_regs:
            exported.add(g.name)
            exported.update(g.values)
        # Anchor each unexported counter at its LAST inc site: the
        # first is typically the zero-priming loop, where a dozen
        # names share one line (one finding would shadow the rest).
        sites: Dict[str, Tuple[str, int]] = {}
        for inc in model.counter_incs:
            if inc.name not in exported:
                cur = sites.get(inc.name)
                if cur is None or (inc.path, inc.line) > cur:
                    sites[inc.name] = (inc.path, inc.line)
        for c in sorted(sites):
            path, line = sites[c]
            findings.append(Finding(
                "MT601", path, line,
                f"counter {c!r} is incremented but no gauge "
                "registration exports it — the signal never reaches "
                "/metrics (the inverse of the registered-but-never-"
                "incremented drift); add it to a register_gauges "
                "loop or suppress documenting the intended surface",
            ))
    # MT602: one module registering the same gauge name twice.
    per_file: Dict[Tuple[str, str], List[int]] = {}
    for g in model.gauge_regs:
        per_file.setdefault((g.path, g.name), []).append(g.line)
    for (path, name), lines in sorted(per_file.items()):
        distinct = sorted(set(lines))
        if len(distinct) < 2:
            continue
        findings.append(Finding(
            "MT602", path, distinct[-1],
            f"gauge {name!r} is registered here and on line "
            f"{distinct[0]} of the same module — the earlier "
            "callback is silently replaced (one of the two signals "
            "is dark)",
        ))


def check_project(model: ProjectModel) -> List[Finding]:
    findings: List[Finding] = []
    _check_pc401(model, findings)
    _check_pc402(model, findings)
    _check_pc403(model, findings)
    _check_pc404(model, findings)
    _check_pc405(model, findings)
    _check_lk201(model, findings)
    _check_lk202(model, findings)
    _check_chaos(model, findings)
    _check_metrics(model, findings)
    uniq: Dict[Tuple[str, str, int], Finding] = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line), f)
    return list(uniq.values())
