"""graftcheck engine: file walking, suppression parsing, reporters.

The engine owns everything rule-independent: turning source blobs into
ASTs plus suppression maps, the THREE-PASS drive (pass 1 builds the
whole-program :mod:`project_model`; pass 2 runs the per-file rule
modules on each analyzed file and the cross-module
:mod:`proto_rules` over the model; pass 3 computes transitive
ambient-effect sets (:mod:`effects`) and runs the DET determinism
families (:mod:`effect_rules`) over them), marking findings
suppressed, stale-suppression detection (GC001), and rendering
human/JSON/chaos-table/effects-manifest reports.  Per-file rules live
in ``jax_rules.py``, ``concurrency_rules.py`` and ``obs_rules.py``;
cross-module rules in ``proto_rules.py`` and ``effect_rules.py`` —
all are pure functions over ASTs/model.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import re
import subprocess
import sys
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

RULES: Dict[str, str] = {
    "GC000": "suppression comment without justification",
    "GC001": "stale suppression: the named rule no longer fires on "
             "the covered line",
    "JX001": "Python if/while branches on a traced value inside jit",
    "JX002": "host sync inside jit scope (float()/.item()/np.asarray/"
             "block_until_ready)",
    "JX003": "jax.jit constructed inside a loop body (recompilation "
             "hazard)",
    "JX004": "PRNG key reuse without split",
    "JX005": "non-hashable argument in a static_argnums position",
    "CC101": "instance attribute written both with and without the "
             "object's lock held",
    "CC102": "time.sleep while holding a lock",
    "CC103": "non-daemon thread never joined",
    "CC104": "except:/except Exception: with a pass-only body swallows "
             "errors",
    "OB301": "time.time() delta used as a duration/deadline "
             "(monotonic/perf_counter required; wall clocks step)",
    "PC401": "message sent via .call() that no handler accepts",
    "PC402": "dispatch-table entry for a non-message type",
    "PC403": "idempotent=True retry of a handler that destructively "
             "mutates without consuming an idempotency token",
    "PC404": "mutating servicer-reachable manager method that never "
             "appends to the control-state journal (acks before the "
             "journal write on the HA path)",
    "PC405": "message class referenced nowhere outside its defining "
             "module (dead protocol surface)",
    "LK201": "whole-program lock-order cycle / nested re-acquisition "
             "of a non-reentrant lock (potential deadlock)",
    "LK202": "_locked-suffix method called without the documented "
             "lock held",
    "CH501": "chaos site declared in SITES but never injected",
    "CH502": "injected chaos site not declared in SITES (plan parser "
             "rejects it — dead injection point)",
    "CH503": "chaos site referenced by no test",
    "MT601": "counter incremented but never exported by any gauge "
             "registration",
    "MT602": "gauge name registered twice in one module (first "
             "callback silently dark)",
    "DET701": "ambient clock read reachable from a registered pure "
              "policy (or bypassing a class's injected clock seam) — "
              "the wind tunnel cannot advance an ambient clock",
    "DET702": "unseeded/ambient randomness reachable from a "
              "registered pure policy (replayed decision sequences "
              "can never match)",
    "DET703": "sandbox escape reachable from a registered pure "
              "policy: thread/process spawn, blocking I/O, env read, "
              "or global mutation",
    "DET704": "hash-order nondeterminism reachable from a registered "
              "pure policy (set iteration / next(iter) / .pop() "
              "without a sorted() total order)",
    "DET705": "wall-clock timestamp recorded into decision/audit "
              "state that replay compares (stamp via the injected "
              "clock)",
}

#: Meta rules the suppression machinery itself emits; a suppression
#: cannot silence them (the fix is editing the suppression).
_UNSUPPRESSIBLE = {"GC000", "GC001"}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*disable="
    r"([A-Z]{2,3}\d{3}(?:\s*,\s*[A-Z]{2,3}\d{3})*)"
    r"\s*(?:--\s*(\S.*?))?\s*$"
)


def _comment_cols(source: str) -> Optional[Dict[int, int]]:
    """line -> start column of that line's comment token.  Tokenizing
    keeps suppression syntax QUOTED in docstrings/strings (the tool's
    own documentation!) from registering as live suppressions — a
    line-regex alone saw them, and GC001 then flagged the examples as
    stale.  None = source does not tokenize (caller falls back to the
    lexical scan; such files already fail parsing anyway)."""
    cols: Dict[int, int] = {}
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                cols[tok.start[0]] = tok.start[1]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None
    return cols


def _parse_suppressions(
    source: str, path: str
) -> Tuple[Dict[int, Dict[str, str]], List[Finding]]:
    """Return ({line: {rule_id: justification}}, [GC000 findings]).

    A suppression trailing a code line covers that line; one on a
    comment-only line covers the next CODE line (intervening comment /
    blank lines — e.g. a justification spanning several comment lines —
    are skipped).  A suppression with no ``-- justification`` text
    covers NOTHING and is itself a GC000 finding — the justification
    policy is enforced here, not by review.  Only REAL comment tokens
    count: the suppression syntax quoted inside a string/docstring is
    documentation, not a directive.
    """
    per_line: Dict[int, Dict[str, str]] = {}
    meta: List[Finding] = []
    pending: Dict[str, str] = {}
    pending_line = 0
    if "graftcheck:" not in source:
        # No directive can possibly match — skip the tokenize pass
        # (it dominates suppression parsing on a clean tree).
        return per_line, meta
    comments = _comment_cols(source)
    for lineno, text in enumerate(source.splitlines(), start=1):
        stripped = text.strip()
        m = _SUPPRESS_RE.search(text)
        if m is not None and comments is not None:
            col = comments.get(lineno)
            if col is None or m.start() < col:
                m = None  # inside a string literal, not a comment
        comment_only = stripped.startswith("#")
        if pending and stripped and not comment_only:
            # First code line after a standalone suppression — it gets
            # the pending cover even if it ALSO carries a trailing
            # suppression of its own.
            per_line.setdefault(lineno, {}).update(pending)
            pending = {}
        elif pending and comment_only and not m:
            # Justifications may wrap over several comment lines.
            extra = stripped.lstrip("#").strip()
            if extra:
                pending = {
                    rid: f"{j} {extra}" for rid, j in pending.items()
                }
        if not m:
            continue
        ids = [r.strip() for r in m.group(1).split(",")]
        justification = (m.group(2) or "").strip()
        if not justification:
            meta.append(Finding(
                "GC000", path, lineno,
                "suppression of "
                + ",".join(ids)
                + " has no justification (write "
                  "`# graftcheck: disable=ID -- why`); not honored",
            ))
        elif comment_only:
            for rid in ids:  # standalone: covers next code line
                pending[rid] = justification
            pending_line = lineno
        else:
            slot = per_line.setdefault(lineno, {})
            for rid in ids:
                slot[rid] = justification
    if pending:
        # A standalone suppression with no following code line covers
        # nothing — surface it instead of silently dropping it.
        meta.append(Finding(
            "GC000", path, pending_line,
            "suppression of " + ",".join(sorted(pending))
            + " is followed by no code line and covers nothing — "
              "remove it or move it above the intended statement",
        ))
    return per_line, meta


# ---------------------------------------------------------------------------
# two-pass analysis
# ---------------------------------------------------------------------------


def _analyze_sources(
    sources: Dict[str, str],
    targets: Optional[Set[str]] = None,
    test_text: Optional[str] = None,
):
    """The core drive: parse every file, build the project model over
    ALL of them, run per-file + cross-module rules, apply
    suppressions, detect stale ones.  ``targets`` restricts which
    files findings are REPORTED for (the ``--changed`` fast loop) —
    the model always spans every supplied source so cross-module
    rules stay sound.  Returns (findings, model)."""
    from . import (concurrency_rules, effect_rules, jax_rules,
                   obs_rules, proto_rules)
    from .project_model import FileInfo, build_model

    if targets is None:
        targets = set(sources)
    findings: List[Finding] = []
    infos: List[FileInfo] = []
    suppress: Dict[str, Dict[int, Dict[str, str]]] = {}
    for path, source in sources.items():
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            if path in targets:
                findings.append(Finding(
                    "GC000", path, e.lineno or 1,
                    f"file does not parse: {e.msg}",
                ))
            continue
        infos.append(FileInfo(path=path, source=source, tree=tree))
        if path in targets:
            # Suppressions only ever apply to REPORTED findings, and
            # reporting is target-filtered — parsing them for the
            # whole model would pay tokenize for nothing.
            sup, meta = _parse_suppressions(source, path)
            suppress[path] = sup
            findings.extend(meta)
            for rule_mod in (jax_rules, concurrency_rules, obs_rules):
                findings.extend(rule_mod.check(tree, path))
    model = build_model(infos, test_text=test_text)
    findings.extend(
        f for f in proto_rules.check_project(model)
        if f.path in targets
    )
    # Pass 3: effect inference + the DET determinism families.  Same
    # contract as pass 2 — the closure spans the whole model so a
    # --changed run still sees effects a policy reaches through
    # UNCHANGED collaborators, and reporting is target-filtered.
    findings.extend(
        f for f in effect_rules.check_project(model)
        if f.path in targets
    )
    used: Set[Tuple[str, int, str]] = set()
    for f in findings:
        just = suppress.get(f.path, {}).get(f.line, {}).get(f.rule)
        if just is not None and f.rule not in _UNSUPPRESSIBLE:
            f.suppressed = True
            f.justification = just
            used.add((f.path, f.line, f.rule))
    # GC001: a justified suppression whose rule no longer fires on the
    # covered line is dead weight that silently licenses FUTURE
    # instances of the hazard — surface it so it gets deleted.
    for path in sorted(set(suppress) & targets):
        for line in sorted(suppress[path]):
            for rid, _just in sorted(suppress[path][line].items()):
                if (path, line, rid) not in used:
                    findings.append(Finding(
                        "GC001", path, line,
                        f"stale suppression: {rid} does not fire on "
                        "this line any more — delete the comment "
                        "(keeping it would silently cover a future "
                        "regression)",
                    ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, model


def check_source(source: str, path: str = "<string>") -> List[Finding]:
    """Run every rule over one source blob (the blob is the whole
    program for the cross-module families); returns ALL findings,
    suppressed ones included (``suppressed=True`` + justification)."""
    findings, _model = _analyze_sources({path: source})
    return findings


def check_project(sources: Dict[str, str],
                  test_text: Optional[str] = None) -> List[Finding]:
    """Multi-file fixture entry point (tests): ``sources`` maps
    virtual paths to source blobs; the project model spans all of
    them."""
    findings, _model = _analyze_sources(sources, test_text=test_text)
    return findings


def _read_source(path: str) -> Tuple[Optional[str], Optional[Finding]]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return fh.read(), None
    except UnicodeDecodeError as e:
        # Same contract as a SyntaxError: one finding, not a crash —
        # the gate must stay readable on a stray latin-1 file.
        return None, Finding(
            "GC000", path, 1,
            f"file is not valid UTF-8 ({e.reason} at byte "
            f"{e.start}); not analyzed",
        )


def check_file(path: str) -> List[Finding]:
    source, err = _read_source(path)
    if err is not None:
        return [err]
    return check_source(source, path)


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        if not os.path.isdir(p):
            # A typo'd CI target must fail loudly, not pass as an
            # empty (and therefore "clean") tree.
            raise FileNotFoundError(
                f"graftcheck: no such file or directory: {p}"
            )
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def _detect_tests_dir(paths: Iterable[str]) -> Optional[str]:
    """The repo's test tree, for CH503/PC405: a ``tests`` directory
    beside an analyzed root, or under the cwd."""
    bases = [os.path.dirname(os.path.abspath(p)) for p in paths]
    bases.append(os.getcwd())
    for base in bases:
        cand = os.path.join(base, "tests")
        if os.path.isdir(cand):
            return cand
    return None


def _read_test_text(tests_dir: Optional[str]) -> Optional[str]:
    if not tests_dir or not os.path.isdir(tests_dir):
        return None
    chunks = []
    for path in iter_py_files([tests_dir]):
        try:
            with open(path, "r", encoding="utf-8",
                      errors="replace") as fh:
                chunks.append(fh.read())
        except OSError:
            continue
    return "\n".join(chunks)


def run_project(
    paths: Iterable[str],
    model_paths: Optional[Iterable[str]] = None,
    tests_dir: Optional[str] = None,
    targets: Optional[Iterable[str]] = None,
):
    """Analyze ``paths`` with a model spanning ``model_paths`` (default
    = ``paths``).  ``targets`` further restricts reporting to specific
    files (``--changed``).  Returns (findings, model)."""
    paths = list(paths)
    target_files = [
        os.path.normpath(p) for p in iter_py_files(paths)
    ]
    model_files = list(target_files)
    if model_paths is not None:
        # Dedupe on ABSOLUTE identity: the CLI passes cwd-relative
        # `paths` alongside an absolute model root, and a file parsed
        # under both spellings would enter the model twice — the
        # duplicate then dodges every `p != decl.path` exclusion
        # (e.g. the chaos table listed plan.py as its own injector).
        seen = {os.path.abspath(p) for p in model_files}
        for p in iter_py_files(model_paths):
            norm = os.path.normpath(p)
            if os.path.abspath(norm) not in seen:
                seen.add(os.path.abspath(norm))
                model_files.append(norm)
    if targets is not None:
        # Absolute-path matching: git names are repo-root-relative
        # while the analyzed paths may be absolute or cwd-relative —
        # a spelling mismatch must never silently report "clean".
        wanted = {os.path.abspath(t) for t in targets}
        target_set = {
            p for p in target_files if os.path.abspath(p) in wanted
        }
    else:
        target_set = set(target_files)
    sources: Dict[str, str] = {}
    pre: List[Finding] = []
    for path in model_files:
        source, err = _read_source(path)
        if err is not None:
            if path in target_set:
                pre.append(err)
            continue
        sources[path] = source
    if tests_dir is None:
        tests_dir = _detect_tests_dir(
            list(paths) + list(model_paths or [])
        )
    findings, model = _analyze_sources(
        sources, targets=target_set,
        test_text=_read_test_text(tests_dir),
    )
    findings = sorted(
        pre + findings, key=lambda f: (f.path, f.line, f.rule)
    )
    return findings, model


def run_paths(paths: Iterable[str]) -> List[Finding]:
    findings, _model = run_project(paths)
    return findings


def changed_files(ref: str = "HEAD",
                  cwd: Optional[str] = None) -> List[str]:
    """Changed AND untracked .py files as absolute paths — the
    ``--changed`` pre-commit loop's target set.  Untracked files are
    included (`git ls-files --others`): a brand-new module is exactly
    where findings are most likely.  Paths are resolved against the
    git toplevel so the caller's cwd and path spelling don't matter."""
    top = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"],
        capture_output=True, text=True, cwd=cwd, check=True,
    ).stdout.strip()
    # Both listings run FROM the toplevel: ls-files --others is
    # cwd-scoped and cwd-relative, so a subdirectory cwd would both
    # hide untracked files elsewhere and mis-resolve the names.
    out = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        capture_output=True, text=True, cwd=top, check=True,
    ).stdout
    out += subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        capture_output=True, text=True, cwd=top, check=True,
    ).stdout
    files = []
    for line in out.splitlines():
        name = line.strip()
        if not name.endswith(".py"):
            continue
        path = os.path.join(top, name)
        if os.path.isfile(path):
            files.append(path)
    return files


def find_model_root(paths: Iterable[str]) -> Optional[str]:
    """The ``dlrover_tpu`` package root governing ``paths``: walk up
    from each analyzed path (NOT the cwd — a subset run from another
    directory must still get the whole-program model or cross-module
    rules see orphans everywhere), then fall back to the cwd."""
    candidates = [os.path.abspath(p) for p in paths]
    candidates.append(os.getcwd())
    for start in candidates:
        cur = start if os.path.isdir(start) else os.path.dirname(start)
        while True:
            if os.path.basename(cur) == "dlrover_tpu" and \
                    os.path.isdir(cur):
                return cur
            cand = os.path.join(cur, "dlrover_tpu")
            if os.path.isdir(cand):
                return cand
            parent = os.path.dirname(cur)
            if parent == cur:
                break
            cur = parent
    return None


# ---------------------------------------------------------------------------
# reporters
# ---------------------------------------------------------------------------


def render_human(findings: List[Finding], show_suppressed=False) -> str:
    lines = []
    unsuppressed = 0
    for f in findings:
        if f.suppressed:
            if show_suppressed:
                lines.append(
                    f"{f.path}:{f.line}: {f.rule} [suppressed: "
                    f"{f.justification}] {f.message}"
                )
            continue
        unsuppressed += 1
        lines.append(f"{f.path}:{f.line}: {f.rule} {f.message}")
    n_sup = sum(1 for f in findings if f.suppressed)
    lines.append(
        f"graftcheck: {unsuppressed} finding(s), {n_sup} suppressed"
    )
    return "\n".join(lines)


def render_json(findings: List[Finding]) -> str:
    return json.dumps({
        "findings": [f.to_json() for f in findings],
        "unsuppressed": sum(1 for f in findings if not f.suppressed),
        "suppressed": sum(1 for f in findings if f.suppressed),
    }, indent=2)


def render_chaos_table(model) -> str:
    """The README chaos-site table, generated from the project model
    so docs cannot drift from ``chaos/plan.py``: site + kind (with
    exit code / default delay), the modules that inject it, and the
    declaration's ``doc`` text."""
    from .project_model import module_of

    lines = [
        "| Site | Kind | Injected in | Effect |",
        "|------|------|-------------|--------|",
    ]
    injects: Dict[str, Set[str]] = {}
    for i in model.injects:
        injects.setdefault(i.name, set()).add(module_of(i.path))
    for site in sorted(model.chaos_sites):
        decl = model.chaos_sites[site]
        kind = decl.kind
        if kind == "crash" and decl.exit_code:
            kind = f"crash (exit {decl.exit_code})"
        elif kind == "latency" and decl.delay:
            kind = f"latency ({decl.delay:g}s)"
        where = injects.get(site, set())
        if not where:
            # Sites armed through variables (the master main's
            # has_site tuple): any module whose source names the site.
            where = {
                module_of(p) for p, fi in model.files.items()
                if p != decl.path and site in fi.source
            }
        lines.append(
            f"| `{site}` | {kind} | "
            f"{', '.join(f'`{w}`' for w in sorted(where)) or '—'} | "
            f"{decl.doc or '—'} |"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="repo-native static analysis for JAX/TPU, "
                    "concurrency, and cross-module protocol hazards",
    )
    ap.add_argument("paths", nargs="*", default=["dlrover_tpu"],
                    help="files or directories (default: dlrover_tpu)")
    ap.add_argument("--format", choices=("human", "json"),
                    default="human")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in human output")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--changed", nargs="?", const="HEAD", default=None,
        metavar="REF",
        help="report only findings in files of `git diff --name-only "
             "REF` (default HEAD); the project model is still built "
             "over the full paths so cross-module rules stay sound",
    )
    ap.add_argument(
        "--chaos-table", action="store_true",
        help="print the chaos-site markdown table generated from the "
             "project model (the README embeds exactly this)",
    )
    ap.add_argument(
        "--effects", action="store_true",
        help="print the per-policy ambient-effect manifest as JSON "
             "(the committed POLICY_EFFECTS.json is exactly this; "
             "the sim/ harness consumes it as its gate)",
    )
    ap.add_argument(
        "--tests", default=None, metavar="DIR",
        help="test tree for CH503 coverage checks (default: a "
             "'tests' directory beside the analyzed root)",
    )
    args = ap.parse_args(argv)
    if args.list_rules:
        for rid, desc in sorted(RULES.items()):
            print(f"{rid}  {desc}")
        return 0
    paths = args.paths or ["dlrover_tpu"]
    targets = None
    if args.changed is not None:
        try:
            targets = changed_files(args.changed)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"graftcheck: --changed failed: {e}",
                  file=sys.stderr)
            return 2
        if not targets:
            print("graftcheck: 0 finding(s) (no changed .py files)")
            return 0
    # Partial invocations (a single file, a subdirectory) still get a
    # sound whole-program model: cross-module rules over a file subset
    # would see orphan messages, missing handlers, and — worse — emit
    # GC001 "stale suppression" for suppressions the FULL model needs.
    # The root is derived from the ANALYZED paths (cwd only as a
    # fallback), and run_project dedupes the union, so expanding is
    # free when the root was already given.
    root = find_model_root(paths)
    model_paths = [root] if root is not None else None
    if args.chaos_table:
        # The table is derived purely from the pass-1 model — skip
        # the rule pipeline entirely (targets=[]) so the README
        # regeneration loop stays fast.
        try:
            _findings, model = run_project(
                paths, model_paths=model_paths, tests_dir=args.tests,
                targets=[],
            )
        except FileNotFoundError as e:
            print(e, file=sys.stderr)
            return 2
        print(render_chaos_table(model))
        return 0
    if args.effects:
        # Like --chaos-table: pure pass-1+3 over the model, no rule
        # pipeline (targets=[]) — the manifest loop stays fast.
        from .effect_rules import effects_manifest
        try:
            _findings, model = run_project(
                paths, model_paths=model_paths, tests_dir=args.tests,
                targets=[],
            )
        except FileNotFoundError as e:
            print(e, file=sys.stderr)
            return 2
        print(json.dumps(effects_manifest(model), indent=2,
                         sort_keys=True))
        return 0
    try:
        findings, model = run_project(
            paths, model_paths=model_paths, tests_dir=args.tests,
            targets=targets,
        )
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if args.format == "json":
        print(render_json(findings))
    else:
        print(render_human(findings, args.show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0
