"""graftcheck engine: file walking, suppression parsing, reporters.

The engine owns everything rule-independent: turning a source blob into
an AST plus a suppression map, dispatching to the rule modules, marking
findings suppressed, and rendering human/JSON reports.  Rules live in
``jax_rules.py`` and ``concurrency_rules.py`` and are pure functions
``(tree, path) -> Iterable[Finding]``.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Tuple

RULES: Dict[str, str] = {
    "GC000": "suppression comment without justification",
    "JX001": "Python if/while branches on a traced value inside jit",
    "JX002": "host sync inside jit scope (float()/.item()/np.asarray/"
             "block_until_ready)",
    "JX003": "jax.jit constructed inside a loop body (recompilation "
             "hazard)",
    "JX004": "PRNG key reuse without split",
    "JX005": "non-hashable argument in a static_argnums position",
    "CC101": "instance attribute written both with and without the "
             "object's lock held",
    "CC102": "time.sleep while holding a lock",
    "CC103": "non-daemon thread never joined",
    "CC104": "except:/except Exception: with a pass-only body swallows "
             "errors",
    "OB301": "time.time() delta used as a duration/deadline "
             "(monotonic/perf_counter required; wall clocks step)",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*disable=([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
    r"\s*(?:--\s*(\S.*?))?\s*$"
)


def _parse_suppressions(
    source: str, path: str
) -> Tuple[Dict[int, Dict[str, str]], List[Finding]]:
    """Return ({line: {rule_id: justification}}, [GC000 findings]).

    A suppression trailing a code line covers that line; one on a
    comment-only line covers the next CODE line (intervening comment /
    blank lines — e.g. a justification spanning several comment lines —
    are skipped).  A suppression with no ``-- justification`` text
    covers NOTHING and is itself a GC000 finding — the justification
    policy is enforced here, not by review.
    """
    per_line: Dict[int, Dict[str, str]] = {}
    meta: List[Finding] = []
    pending: Dict[str, str] = {}
    pending_line = 0
    for lineno, text in enumerate(source.splitlines(), start=1):
        stripped = text.strip()
        m = _SUPPRESS_RE.search(text)
        comment_only = stripped.startswith("#")
        if pending and stripped and not comment_only:
            # First code line after a standalone suppression — it gets
            # the pending cover even if it ALSO carries a trailing
            # suppression of its own.
            per_line.setdefault(lineno, {}).update(pending)
            pending = {}
        elif pending and comment_only and not m:
            # Justifications may wrap over several comment lines.
            extra = stripped.lstrip("#").strip()
            if extra:
                pending = {
                    rid: f"{j} {extra}" for rid, j in pending.items()
                }
        if not m:
            continue
        ids = [r.strip() for r in m.group(1).split(",")]
        justification = (m.group(2) or "").strip()
        if not justification:
            meta.append(Finding(
                "GC000", path, lineno,
                "suppression of "
                + ",".join(ids)
                + " has no justification (write "
                  "`# graftcheck: disable=ID -- why`); not honored",
            ))
        elif comment_only:
            for rid in ids:  # standalone: covers next code line
                pending[rid] = justification
            pending_line = lineno
        else:
            slot = per_line.setdefault(lineno, {})
            for rid in ids:
                slot[rid] = justification
    if pending:
        # A standalone suppression with no following code line covers
        # nothing — surface it instead of silently dropping it.
        meta.append(Finding(
            "GC000", path, pending_line,
            "suppression of " + ",".join(sorted(pending))
            + " is followed by no code line and covers nothing — "
              "remove it or move it above the intended statement",
        ))
    return per_line, meta


def check_source(source: str, path: str = "<string>") -> List[Finding]:
    """Run every rule over one source blob; returns ALL findings,
    suppressed ones included (``suppressed=True`` + justification)."""
    from . import concurrency_rules, jax_rules, obs_rules

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(
            "GC000", path, e.lineno or 1,
            f"file does not parse: {e.msg}",
        )]
    suppress, findings = _parse_suppressions(source, path)
    for rule_mod in (jax_rules, concurrency_rules, obs_rules):
        findings.extend(rule_mod.check(tree, path))
    for f in findings:
        just = suppress.get(f.line, {}).get(f.rule)
        if just is not None and f.rule != "GC000":
            f.suppressed = True
            f.justification = just
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def check_file(path: str) -> List[Finding]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
    except UnicodeDecodeError as e:
        # Same contract as a SyntaxError: one finding, not a crash —
        # the gate must stay readable on a stray latin-1 file.
        return [Finding(
            "GC000", path, 1,
            f"file is not valid UTF-8 ({e.reason} at byte "
            f"{e.start}); not analyzed",
        )]
    return check_source(source, path)


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        if not os.path.isdir(p):
            # A typo'd CI target must fail loudly, not pass as an
            # empty (and therefore "clean") tree.
            raise FileNotFoundError(
                f"graftcheck: no such file or directory: {p}"
            )
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def run_paths(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        findings.extend(check_file(path))
    return findings


def render_human(findings: List[Finding], show_suppressed=False) -> str:
    lines = []
    unsuppressed = 0
    for f in findings:
        if f.suppressed:
            if show_suppressed:
                lines.append(
                    f"{f.path}:{f.line}: {f.rule} [suppressed: "
                    f"{f.justification}] {f.message}"
                )
            continue
        unsuppressed += 1
        lines.append(f"{f.path}:{f.line}: {f.rule} {f.message}")
    n_sup = sum(1 for f in findings if f.suppressed)
    lines.append(
        f"graftcheck: {unsuppressed} finding(s), {n_sup} suppressed"
    )
    return "\n".join(lines)


def render_json(findings: List[Finding]) -> str:
    return json.dumps({
        "findings": [f.to_json() for f in findings],
        "unsuppressed": sum(1 for f in findings if not f.suppressed),
        "suppressed": sum(1 for f in findings if f.suppressed),
    }, indent=2)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="repo-native static analysis for JAX/TPU and "
                    "concurrency hazards",
    )
    ap.add_argument("paths", nargs="*", default=["dlrover_tpu"],
                    help="files or directories (default: dlrover_tpu)")
    ap.add_argument("--format", choices=("human", "json"),
                    default="human")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in human output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)
    if args.list_rules:
        for rid, desc in sorted(RULES.items()):
            print(f"{rid}  {desc}")
        return 0
    try:
        findings = run_paths(args.paths or ["dlrover_tpu"])
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if args.format == "json":
        print(render_json(findings))
    else:
        print(render_human(findings, args.show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0
