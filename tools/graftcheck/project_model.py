"""graftcheck pass 1: the whole-program project model.

Per-file AST rules (JX/CC/OB) see one file at a time; the contracts
that actually hold this control plane together — which message types
have servicer handlers, which ``RpcClient.call`` sites may retry,
which mutations the HA journal covers, which chaos sites are real,
which counters reach an operator — span modules.  This pass walks
every analyzed file ONCE and builds the cross-module index the PC/LK/
CH/MT rule families (``proto_rules.py``) run over.

Everything here is lexical, matching the repo's idioms:

- message classes: ``class X(Message)`` dataclasses;
- dispatch tables: ``{m.X: self._on_x, ...}`` dict literals, and
  ``isinstance(msg, X)`` guards inside handler functions;
- RPC call sites: ``<client>.call(X(...), ..., idempotent=...)``;
- chaos: the ``SITES`` dict literal in ``chaos/plan.py`` vs the string
  literals fed to ``inject(...)`` / ``site_armed(...)`` /
  ``has_site(...)``;
- metrics: ``<counters>.inc("name")`` vs gauge registrations —
  including the repo's loop-over-literal-tuple registration idiom,
  whose f-string gauge names are expanded here;
- locks: ``with self.<lock>:`` acquisition nesting plus the
  one-level call graph (self methods, ``self.attr = Class(...)``
  typed attributes, same-module functions) that turns per-class lock
  use into a whole-program lock-order graph.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .jax_rules import _Ancestry, _ancestors, _dotted

#: Container-mutator method names: a ``self.<attr>.<verb>(...)`` call
#: with one of these verbs writes instance state.
_MUTATOR_VERBS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "push",
}

#: Verbs that are DESTRUCTIVE under retry: re-running them consumes /
#: drops something a lost first reply already consumed (the PR-2
#: Heartbeat bug: the handler pops pending DiagnosisActions).
_DESTRUCTIVE_VERBS = {"pop", "popleft", "popitem"}

#: Message fields that act as dedupe keys: a handler that reads one of
#: these participates in the idempotency-token protocol.
_TOKEN_FIELDS = {"token", "attempt_id", "req_id"}

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}

_INJECT_FUNCS = {"inject", "site_armed", "has_site"}

#: container-mutating method names whose call sites feed the DET705
#: audit-stamp scan (effect_rules imports this — single source).
_AUDIT_MUTATOR_ATTRS = {"append", "add", "insert", "setdefault",
                        "update"}


def module_of(path: str) -> str:
    """A stable, repo-relative module label for ``path`` (used in
    reports and the chaos table, where absolute tmp/CI prefixes would
    make output non-deterministic)."""
    norm = path.replace("\\", "/")
    for anchor in ("dlrover_tpu/", "tools/"):
        i = norm.rfind(anchor)
        if i >= 0:
            return norm[i:]
    return norm.rsplit("/", 1)[-1]


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FileInfo:
    path: str
    source: str
    tree: ast.Module


@dataclasses.dataclass
class CallSite:
    """One ``<client>.call(Msg(...), ...)`` site."""

    msg: str
    path: str
    line: int
    idempotent: bool


@dataclasses.dataclass
class DispatchEntry:
    """One ``{m.X: self._on_x}`` dispatch-table row."""

    msg: str
    handler: str  # method attr name ("" when not a self method)
    path: str
    line: int
    cls: Optional[ast.ClassDef]


@dataclasses.dataclass
class IsinstanceHandler:
    """One ``isinstance(<var>, X)`` guard over a known message type."""

    msg: str
    var: str
    path: str
    line: int
    func: Optional[ast.AST]  # enclosing function (handler body scope)


@dataclasses.dataclass
class ChaosSite:
    name: str
    kind: str
    path: str
    line: int
    exit_code: int = 0
    times: int = -1
    delay: float = 0.0
    doc: str = ""


@dataclasses.dataclass
class InjectSite:
    name: str
    path: str
    line: int


@dataclasses.dataclass
class CounterInc:
    name: str
    path: str
    line: int


@dataclasses.dataclass
class GaugeReg:
    """One registered gauge name (f-strings over literal loops are
    expanded; ``values`` are the placeholder strings that produced the
    name — the counter keys a registration loop exports)."""

    name: str
    path: str
    line: int
    values: Tuple[str, ...] = ()


@dataclasses.dataclass
class MethodInfo:
    name: str
    node: ast.AST
    writes_state: bool = False  # any self-state write
    destructive: bool = False  # retry-unsafe consumption
    has_jrec: bool = False  # calls self._jrec(...)
    self_calls: Set[str] = dataclasses.field(default_factory=set)
    # (held_lock_id or None, acquired_lock_id) nesting, plus calls made
    # while holding each lock — the LK201 edge inputs.
    acquires: List[Tuple[Optional[str], str, int]] = \
        dataclasses.field(default_factory=list)
    calls_under: List[Tuple[str, "_CallRef", int]] = \
        dataclasses.field(default_factory=list)
    #: every outgoing call regardless of lock state (transitive lock-
    #: acquisition closure) and ``self.<m>()`` calls made while NOT
    #: holding any lock (the LK202 `_locked`-contract check).
    attr_calls: List[Tuple[str, str]] = \
        dataclasses.field(default_factory=list)
    func_calls: Set[str] = dataclasses.field(default_factory=set)
    self_calls_unlocked: List[Tuple[str, int]] = \
        dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _CallRef:
    """A call made while holding a lock: ``self.m()``,
    ``self.attr.m()`` or a bare same-module ``fn()``."""

    kind: str  # "self" | "attr" | "func"
    attr: str  # manager/collaborator attribute ("" for self/func)
    method: str


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str
    node: ast.ClassDef
    bases: Tuple[str, ...]
    lock_attrs: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: ``self.<attr> = ClassName(...)`` typed collaborators.
    attr_types: Dict[str, Set[str]] = \
        dataclasses.field(default_factory=dict)
    methods: Dict[str, MethodInfo] = \
        dataclasses.field(default_factory=dict)

    def lock_id(self, attr: str) -> str:
        return f"{module_of(self.path)}::{self.name}.{attr}"


# ---------------------------------------------------------------------------
# literal-string resolution (the repo's loop/dict/ifexp idioms)
# ---------------------------------------------------------------------------


def _const_strs(node: ast.AST) -> Optional[List[str]]:
    """The string elements of a literal tuple/list (None when any
    element is not a plain string)."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for el in node.elts:
        if isinstance(el, ast.Constant) and isinstance(el.value, str):
            out.append(el.value)
        else:
            return None
    return out


class _Resolver:
    """Resolves an expression to the set of string literals it can
    evaluate to, using enclosing ``for`` loops over literal iterables
    and module-level string-tuple constants.  Returns None when any
    path is unresolvable — rules skip rather than guess."""

    def __init__(self, consts: Dict[str, List[str]]):
        self.consts = consts

    def resolve(self, node: ast.AST, depth: int = 0) \
            -> Optional[Set[str]]:
        if depth > 6:
            return None
        if isinstance(node, ast.Constant):
            return {node.value} if isinstance(node.value, str) else None
        if isinstance(node, ast.IfExp):
            a = self.resolve(node.body, depth + 1)
            b = self.resolve(node.orelse, depth + 1)
            return a | b if a is not None and b is not None else None
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Dict):
            # {"hit": "prefix_hits", ...}[route] — all values possible.
            vals: Set[str] = set()
            for v in node.value.values:
                got = self.resolve(v, depth + 1)
                if got is None:
                    return None
                vals |= got
            return vals
        if isinstance(node, ast.Name):
            return self._resolve_name(node, depth)
        return None

    def _iter_values(self, it: ast.AST, depth: int) \
            -> Optional[List[ast.AST]]:
        if isinstance(it, (ast.Tuple, ast.List)):
            return list(it.elts)
        name = _dotted(it)
        if name is not None:
            vals = self.consts.get(name.split(".")[-1])
            if vals is not None:
                return [ast.Constant(value=v) for v in vals]
        return None

    def _resolve_name(self, node: ast.Name, depth: int) \
            -> Optional[Set[str]]:
        # Walk enclosing For loops: ``for name in ("a", "b")`` and the
        # tuple-unpacking ``for src, dst in (("a","b"), ...)`` forms.
        for anc in _ancestors(node):
            if not isinstance(anc, ast.For):
                continue
            tgt = anc.target
            if isinstance(tgt, ast.Name) and tgt.id == node.id:
                elts = self._iter_values(anc.iter, depth)
                if elts is None:
                    return None
                out: Set[str] = set()
                for el in elts:
                    got = self.resolve(el, depth + 1)
                    if got is None:
                        return None
                    out |= got
                return out
            if isinstance(tgt, ast.Tuple):
                for idx, sub in enumerate(tgt.elts):
                    if isinstance(sub, ast.Name) and sub.id == node.id:
                        elts = self._iter_values(anc.iter, depth)
                        if elts is None:
                            return None
                        out = set()
                        for el in elts:
                            if not isinstance(el, (ast.Tuple, ast.List)) \
                                    or idx >= len(el.elts):
                                return None
                            got = self.resolve(el.elts[idx], depth + 1)
                            if got is None:
                                return None
                            out |= got
                        return out
        vals = self.consts.get(node.id)
        return set(vals) if vals is not None else None

    def expand_fstring(self, node: ast.JoinedStr) \
            -> Optional[List[Tuple[str, Tuple[str, ...]]]]:
        """Expand an f-string to [(name, placeholder values)] — the
        gauge-registration loop idiom.  None when unresolvable."""
        parts: List[List[Tuple[str, Tuple[str, ...]]]] = \
            [[("", ())]]
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                opts = [(str(piece.value), ())]
            elif isinstance(piece, ast.FormattedValue):
                got = self.resolve(piece.value)
                if got is None:
                    return None
                opts = [(v, (v,)) for v in sorted(got)]
            else:
                return None
            parts.append(opts)
        combos: List[Tuple[str, Tuple[str, ...]]] = [("", ())]
        for opts in parts:
            combos = [
                (pre + txt, vals + v)
                for pre, vals in combos
                for txt, v in opts
            ]
        return combos


# ---------------------------------------------------------------------------
# per-class analysis
# ---------------------------------------------------------------------------


def _self_attr_of(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x`` / ``self.x[...]`` chains."""
    base = node
    while isinstance(base, ast.Subscript):
        base = base.value
    if (isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"):
        return base.attr
    return None


def _is_lockish(expr: ast.AST, lock_attrs: Dict[str, str]) -> bool:
    name = _dotted(expr)
    if name is None and isinstance(expr, ast.Call):
        name = _dotted(expr.func)
    if name is None:
        return False
    last = name.split(".")[-1]
    if name.startswith("self.") and name.split(".", 1)[1] in lock_attrs:
        return True
    low = last.lower()
    return "lock" in low or low.endswith("_mu") or low == "cond" or \
        "cond" in low


def _lock_name_of(expr: ast.AST) -> str:
    name = _dotted(expr)
    if name is None and isinstance(expr, ast.Call):
        name = _dotted(expr.func) or "<lock>"
    return name or "<lock>"


class _MethodWalk(ast.NodeVisitor):
    """One method's walk: state writes, destructive ops, _jrec, lock
    acquisition nesting and calls-under-lock.  Nested defs reset lock
    state (a closure defined under a lock does not RUN under it)."""

    def __init__(self, cls: ClassInfo, info: MethodInfo):
        self.cls = cls
        self.info = info
        self.held: List[str] = []

    # -- locks ----------------------------------------------------------
    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        name = _lock_name_of(expr)
        if name.startswith("self."):
            return self.cls.lock_id(name.split(".", 1)[1])
        return f"{module_of(self.cls.path)}::{name}"

    def visit_With(self, node):
        entered: List[str] = []
        for item in node.items:
            if _is_lockish(item.context_expr, self.cls.lock_attrs):
                lid = self._lock_id(item.context_expr)
                if lid is not None:
                    held = self.held[-1] if self.held else None
                    self.info.acquires.append(
                        (held, lid, node.lineno)
                    )
                    self.held.append(lid)
                    entered.append(lid)
        for child in node.body:
            self.visit(child)
        for _ in entered:
            self.held.pop()

    visit_AsyncWith = visit_With

    def _visit_fn(self, node):
        prev, self.held = self.held, []
        for child in node.body:
            self.visit(child)
        self.held = prev

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- calls ----------------------------------------------------------
    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _DESTRUCTIVE_VERBS or \
                    f.attr.startswith("pop"):
                # A bare-statement pop is cleanup (the popped value is
                # discarded); consuming the VALUE is what makes a
                # retry destructive (the Heartbeat pop_actions shape).
                parent = next(iter(_ancestors(node)), None)
                if not isinstance(parent, ast.Expr):
                    self.info.destructive = True
            tgt = _self_attr_of(f.value) if not (
                isinstance(f.value, ast.Name) and f.value.id == "self"
            ) else None
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                if f.attr == "_jrec":
                    self.info.has_jrec = True
                self.info.self_calls.add(f.attr)
                if self.held:
                    self.info.calls_under.append((
                        self.held[-1],
                        _CallRef("self", "", f.attr),
                        node.lineno,
                    ))
                else:
                    self.info.self_calls_unlocked.append(
                        (f.attr, node.lineno)
                    )
            elif tgt is not None:
                if tgt == "_journal" and f.attr == "append":
                    # Direct journal writes (the speed monitor's
                    # throttled baseline) count the same as _jrec.
                    self.info.has_jrec = True
                if f.attr in _MUTATOR_VERBS:
                    self.info.writes_state = True
                self.info.attr_calls.append((tgt, f.attr))
                if self.held:
                    self.info.calls_under.append((
                        self.held[-1],
                        _CallRef("attr", tgt, f.attr),
                        node.lineno,
                    ))
        elif isinstance(f, ast.Name):
            self.info.func_calls.add(f.id)
            if self.held:
                self.info.calls_under.append((
                    self.held[-1], _CallRef("func", "", f.id),
                    node.lineno,
                ))
        self.generic_visit(node)

    # -- writes ---------------------------------------------------------
    def _note_write(self, target: ast.AST, aug: bool) -> None:
        attr = _self_attr_of(target)
        if attr is None:
            return
        self.info.writes_state = True
        if aug and isinstance(target, ast.Subscript):
            # read-modify-write on keyed state: retry-unsafe.
            self.info.destructive = True

    def visit_Assign(self, node):
        for t in node.targets:
            self._note_write(t, aug=False)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._note_write(node.target, aug=True)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._note_write(node.target, aug=False)
        self.generic_visit(node)

    def visit_Delete(self, node):
        for t in node.targets:
            if _self_attr_of(t) is not None:
                self.info.writes_state = True
                self.info.destructive = True
        self.generic_visit(node)


def _analyze_class(path: str, cls: ast.ClassDef) -> ClassInfo:
    bases = tuple(
        b for b in (_dotted(x) for x in cls.bases) if b is not None
    )
    info = ClassInfo(name=cls.name, path=path, node=cls, bases=bases)
    def _ctor_name(v: ast.AST) -> Optional[str]:
        if not isinstance(v, ast.Call):
            return None
        if isinstance(v.func, ast.Attribute):
            return v.func.attr
        if isinstance(v.func, ast.Name):
            return v.func.id
        return None

    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        ctors: List[str] = []
        fname = _ctor_name(v)
        if fname is not None:
            ctors = [fname]
        elif isinstance(v, ast.Dict):
            # self.rdzv_managers = {NAME: Manager(), ...}
            ctors = [
                c for c in (_ctor_name(dv) for dv in v.values)
                if c is not None
            ]
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                if fname in _LOCK_FACTORIES:
                    info.lock_attrs[t.attr] = fname or ""
                else:
                    for c in ctors:
                        if c and c[0].isupper():
                            info.attr_types.setdefault(
                                t.attr, set()
                            ).add(c)
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        mi = MethodInfo(name=meth.name, node=meth)
        walker = _MethodWalk(info, mi)
        for stmt in meth.body:
            walker.visit(stmt)
        info.methods[meth.name] = mi
    return info


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------


class ProjectModel:
    def __init__(self):
        self.files: Dict[str, FileInfo] = {}
        self.messages: Dict[str, Tuple[str, int]] = {}
        self.dispatch: List[DispatchEntry] = []
        self.iso_handlers: List[IsinstanceHandler] = []
        self.call_sites: List[CallSite] = []
        self.classes: Dict[str, List[ClassInfo]] = {}
        self.class_by_node: Dict[int, ClassInfo] = {}
        self.chaos_sites: Dict[str, ChaosSite] = {}
        self.injects: List[InjectSite] = []
        self.counter_incs: List[CounterInc] = []
        self.gauge_regs: List[GaugeReg] = []
        self.unresolved_gauge_regs: int = 0
        #: path -> every Name id / Attribute attr mentioned (cheap
        #: reference index for orphan detection).
        self.mentions: Dict[str, Set[str]] = {}
        #: module-level str-tuple constants, by bare name (global).
        self.consts: Dict[str, List[str]] = {}
        #: module-level int constants (the chaos EXIT_* codes).
        self.int_consts: Dict[str, int] = {}
        #: concatenated raw text of the test tree ("" = not supplied;
        #: CH503 only runs when it is).
        self.test_text: Optional[str] = None
        #: functions per module path (for same-module call edges).
        self.module_funcs: Dict[str, Dict[str, MethodInfo]] = {}
        #: constructor-ish call sites indexed by callee name (one pass
        #: over every tree — rules must never re-walk the program per
        #: dispatch entry; the ``--changed`` loop has a latency budget).
        self.ctor_calls: Dict[str, List[Tuple[str, "ast.Call"]]] = {}
        #: DET705 candidates, collected in the same single walk:
        #: ``self.<container>.append/add/...(...)`` calls and
        #: ``<target>[...] = <value>`` subscript assigns.  The audit-
        #: stamp rule filters these instead of re-walking every tree.
        self.mutator_calls: List[Tuple[str, "ast.Call"]] = []
        self.subscript_assigns: List[Tuple[str, "ast.Assign"]] = []

    # -- lookups used by the rules --------------------------------------
    def classes_named(self, name: str) -> List[ClassInfo]:
        return self.classes.get(name, [])

    def handled_messages(self) -> Set[str]:
        out = {e.msg for e in self.dispatch}
        out |= {h.msg for h in self.iso_handlers}
        return out

    def mentioned_outside(self, name: str, def_path: str) -> bool:
        return any(
            name in names for p, names in self.mentions.items()
            if p != def_path
        )

    def resolve_method(self, class_name: str, method: str,
                       _seen: Optional[Set[str]] = None) \
            -> Optional[Tuple["ClassInfo", "MethodInfo"]]:
        """Find ``method`` on ``class_name`` or (lexically) its bases
        — the owner class is what the mutation/journal analysis runs
        over, so a subclass inheriting a journaled base method is
        judged by the base's body."""
        seen = _seen or set()
        if class_name in seen:
            return None
        seen.add(class_name)
        for ci in self.classes_named(class_name):
            mi = ci.methods.get(method)
            if mi is not None:
                return ci, mi
            for base in ci.bases:
                got = self.resolve_method(
                    base.split(".")[-1], method, seen
                )
                if got is not None:
                    return got
        return None

    def _method_flag(self, class_name: str, method: str, flag: str,
                     follow_private_only: bool,
                     _seen: Optional[Set[Tuple[str, str]]] = None) \
            -> bool:
        seen = _seen if _seen is not None else set()
        key = (class_name, method)
        if key in seen:
            return False
        seen.add(key)
        got = self.resolve_method(class_name, method)
        if got is None:
            # Unresolvable body: destructiveness is judged by name —
            # the Heartbeat bug is literally a ``pop_*`` call.
            return flag == "destructive" and method.startswith("pop")
        _, mi = got
        if getattr(mi, flag):
            return True
        return any(
            self._method_flag(class_name, callee, flag,
                              follow_private_only, seen)
            for callee in mi.self_calls
            if not follow_private_only or callee.startswith("_")
        )

    def method_reaches_jrec(self, class_name: str,
                            method: str) -> bool:
        return self._method_flag(class_name, method, "has_jrec",
                                 follow_private_only=False)

    def method_mutates(self, class_name: str, method: str) -> bool:
        # Only PRIVATE callees propagate: a public callee owns its own
        # journal/idempotency contract and is judged separately.
        return self._method_flag(class_name, method, "writes_state",
                                 follow_private_only=True)

    def method_destructive(self, class_name: str,
                           method: str) -> bool:
        return self._method_flag(class_name, method, "destructive",
                                 follow_private_only=False)


def _msg_name_of(node: ast.AST) -> Optional[str]:
    """The message-class name a dispatch key / isinstance arg / call
    argument refers to (``m.X`` -> "X", bare ``X`` -> "X")."""
    name = _dotted(node)
    if name is None:
        return None
    return name.split(".")[-1]


def _node_classdef(model: ProjectModel, fi: FileInfo,
                   node: ast.ClassDef) -> None:
    for base in node.bases:
        name = _dotted(base)
        if name is not None and name.split(".")[-1] == "Message":
            model.messages[node.name] = (fi.path, node.lineno)
            break
    ci = _analyze_class(fi.path, node)
    model.classes.setdefault(node.name, []).append(ci)
    model.class_by_node[id(node)] = ci


def _node_dict(model: ProjectModel, fi: FileInfo,
               node: ast.Dict) -> None:
    rows = []
    for k, v in zip(node.keys, node.values):
        if k is None:
            continue
        msg = _msg_name_of(k)
        handler = ""
        if (isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"):
            handler = v.attr
        if msg is not None and handler:
            rows.append((msg, handler, k.lineno))
    # A dispatch table is a dict that is MOSTLY msg -> self-method
    # rows; one stray pair in an unrelated dict must not count.
    if len(rows) < 2:
        return
    cls = None
    for anc in _ancestors(node):
        if isinstance(anc, ast.ClassDef):
            cls = anc
            break
    for msg, handler, line in rows:
        model.dispatch.append(DispatchEntry(
            msg=msg, handler=handler, path=fi.path, line=line,
            cls=cls,
        ))


def _node_sites_assign(model: ProjectModel, fi: FileInfo,
                       node: ast.AST) -> None:
    targets = node.targets if isinstance(node, ast.Assign) \
        else [node.target]
    if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Subscript) for t in targets):
        model.subscript_assigns.append((fi.path, node))
    tnames = {t.id for t in targets if isinstance(t, ast.Name)}
    if "SITES" not in tnames or not isinstance(node.value, ast.Dict):
        return
    for k, v in zip(node.value.keys, node.value.values):
        if not (isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and isinstance(v, ast.Dict)):
            continue
        fields: Dict[str, object] = {}
        for fk, fv in zip(v.keys, v.values):
            if not isinstance(fk, ast.Constant):
                continue
            if isinstance(fv, ast.Constant):
                fields[fk.value] = fv.value
            elif isinstance(fv, ast.Name):
                # EXIT_* module constants resolve to their int.
                fields[fk.value] = model.int_consts.get(fv.id, 0)
        model.chaos_sites[k.value] = ChaosSite(
            name=k.value,
            kind=str(fields.get("kind", "flag")),
            path=fi.path, line=k.lineno,
            exit_code=fields.get("exit", 0)  # type: ignore
            if isinstance(fields.get("exit"), int) else 0,
            times=int(fields.get("times", -1))  # type: ignore
            if isinstance(fields.get("times"), int) else -1,
            delay=float(fields.get("delay", 0.0))  # type: ignore
            if isinstance(fields.get("delay"), (int, float))
            else 0.0,
            doc=str(fields.get("doc", "")),
        )


def _node_call(model: ProjectModel, fi: FileInfo, node: ast.Call,
               resolver: _Resolver) -> None:
    f = node.func
    fname = None
    if isinstance(f, ast.Name):
        fname = f.id
    elif isinstance(f, ast.Attribute):
        fname = f.attr
    if fname and fname[0].isupper():
        model.ctor_calls.setdefault(fname, []).append((fi.path, node))
    if isinstance(f, ast.Attribute) and f.attr in _AUDIT_MUTATOR_ATTRS \
            and (node.args or node.keywords):
        model.mutator_calls.append((fi.path, node))
    # isinstance(msg, X) handler guards.
    if (isinstance(f, ast.Name) and f.id == "isinstance"
            and len(node.args) == 2):
        var = _dotted(node.args[0]) or ""
        cand = node.args[1]
        classes = (
            [_msg_name_of(e) for e in cand.elts]
            if isinstance(cand, ast.Tuple) else [_msg_name_of(cand)]
        )
        func = None
        for anc in _ancestors(node):
            if isinstance(anc, (ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                func = anc
                break
        for cname in classes:
            if cname is not None:
                model.iso_handlers.append(IsinstanceHandler(
                    msg=cname, var=var.split(".")[-1], path=fi.path,
                    line=node.lineno, func=func,
                ))
        return
    if not node.args:
        return
    # <client>.call(Msg(...), ..., idempotent=...) sites.
    if isinstance(f, ast.Attribute) and f.attr == "call" and \
            isinstance(node.args[0], ast.Call):
        msg = _msg_name_of(node.args[0].func)
        if msg is not None:
            idem = False
            for kw in node.keywords:
                if kw.arg == "idempotent":
                    idem = bool(
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                    )
            model.call_sites.append(CallSite(
                msg=msg, path=fi.path, line=node.lineno,
                idempotent=idem,
            ))
    # inject("site") / site_armed("site") / has_site("site").
    if fname in _INJECT_FUNCS:
        got = resolver.resolve(node.args[0])
        if got is not None:
            for site in sorted(got):
                model.injects.append(InjectSite(
                    name=site, path=fi.path, line=node.lineno,
                ))
    # metrics: counter incs + gauge registrations.
    if not isinstance(f, ast.Attribute):
        return
    if f.attr == "inc":
        got = resolver.resolve(node.args[0])
        if got is not None:
            for name in sorted(got):
                model.counter_incs.append(CounterInc(
                    name=name, path=fi.path, line=node.lineno,
                ))
    elif f.attr == "gauge":
        arg0 = node.args[0]
        if isinstance(arg0, ast.JoinedStr):
            combos = resolver.expand_fstring(arg0)
            if combos is None:
                model.unresolved_gauge_regs += 1
                return
            for name, values in combos:
                model.gauge_regs.append(GaugeReg(
                    name=name, path=fi.path, line=node.lineno,
                    values=values,
                ))
        else:
            got = resolver.resolve(arg0)
            if got is None:
                model.unresolved_gauge_regs += 1
                return
            for name in sorted(got):
                model.gauge_regs.append(GaugeReg(
                    name=name, path=fi.path, line=node.lineno,
                    values=(name,),
                ))
    elif f.attr == "register_gauges" and len(node.args) >= 2:
        # Histogram.register_gauges(registry, "prefix") expands to
        # the metrics.py suffix set.
        got = resolver.resolve(node.args[1])
        if got is not None:
            for prefix in sorted(got):
                for suffix in ("_count", "_p50_ms", "_p95_ms",
                               "_p99_ms"):
                    model.gauge_regs.append(GaugeReg(
                        name=prefix + suffix, path=fi.path,
                        line=node.lineno,
                    ))


def _collect_consts(model: ProjectModel, fi: FileInfo) -> None:
    for node in fi.tree.body:
        if isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, int):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        model.int_consts[t.id] = node.value.value
                continue
            vals = _const_strs(node.value)
            if vals is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    model.consts[t.id] = vals


def _collect_module_funcs(model: ProjectModel, fi: FileInfo) -> None:
    funcs: Dict[str, MethodInfo] = {}
    shell = ClassInfo(name="<module>", path=fi.path,
                      node=ast.ClassDef(
                          name="<module>", bases=[], keywords=[],
                          body=[], decorator_list=[]),
                      bases=())
    for node in fi.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mi = MethodInfo(name=node.name, node=node)
            walker = _MethodWalk(shell, mi)
            for stmt in node.body:
                walker.visit(stmt)
            funcs[node.name] = mi
    model.module_funcs[fi.path] = funcs


def build_model(files: Iterable[FileInfo],
                test_text: Optional[str] = None) -> ProjectModel:
    model = ProjectModel()
    infos = list(files)
    for fi in infos:
        _Ancestry().visit(fi.tree)
        model.files[fi.path] = fi
        _collect_consts(model, fi)
    resolver = _Resolver(model.consts)
    # ONE walk per file: every collector below is a per-node dispatch
    # (the naive one-pass-per-collector layout dominated the
    # ``--changed`` latency budget).
    for fi in infos:
        _collect_module_funcs(model, fi)
        mentions: Set[str] = set()
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Name):
                mentions.add(node.id)
            elif isinstance(node, ast.Attribute):
                # Reference index for orphan detection (PC405): bare
                # names count; attribute references only off the
                # messages-module aliases — ``queue.Empty`` must not
                # keep a dead ``Empty`` message alive.
                base = _dotted(node.value)
                if base in ("m", "messages", "msg", "msgs"):
                    mentions.add(node.attr)
            elif isinstance(node, ast.Call):
                _node_call(model, fi, node, resolver)
            elif isinstance(node, ast.ClassDef):
                _node_classdef(model, fi, node)
            elif isinstance(node, ast.Dict):
                _node_dict(model, fi, node)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                _node_sites_assign(model, fi, node)
        model.mentions[fi.path] = mentions
    model.test_text = test_text
    return model
