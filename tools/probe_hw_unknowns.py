"""Resolve the remaining hardware-gated unknowns on a live TPU session.

Three probes, each a killable subprocess writing into HW_PROBES.json as
it completes (the tunnel wedges without warning; partial data must
survive):

1. ``offload_combo`` — does ``Strategy(remat="offload",
   offload_opt=True)`` compile and step on the real partitioner?
   (NOTES r3: jax-0.9 may reject the combination on TPU; the BO sweep
   self-rejects if so — but nobody has ever watched it happen.)
2. ``node_check_payload`` — wall time of the agent's pre-flight health
   payload (8 x 4096^3 matmuls) on a real chip vs its 300 s timeout
   budget (``agent/node_check.py``; a mis-sized payload would DoS the
   job it protects).
3. ``device_cache`` — per-batch cost of the device-resident embedding
   cache hit path (plan/apply + jitted gather) vs the host pull/push
   path it replaces (``embedding/device_cache.py``; the claimed
   PCIe-dominated advantage was never measured on TPU).

Run on the chip:  python tools/probe_hw_unknowns.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "HW_PROBES.json")


OFFLOAD_COMBO = r"""
import json, sys, time, traceback
sys.path.insert(0, %(repo)r)
import numpy as np
import jax, jax.numpy as jnp, optax
from dlrover_tpu.models import llama
from dlrover_tpu.parallel.accelerate import Strategy, accelerate
from dlrover_tpu.parallel.mesh import MeshSpec

cfg = llama.LlamaConfig.small_300m()
batch, seq = 4, 1024
rng = np.random.RandomState(0)
tokens = rng.randint(0, cfg.vocab_size, size=(batch, seq + 1)).astype("int32")
try:
    job = accelerate(
        loss_fn=lambda p, b: llama.loss_fn(p, b, cfg),
        init_fn=lambda r: llama.init_params(r, cfg),
        optimizer=optax.adamw(3e-4),
        sample_batch={"tokens": tokens},
        strategy=Strategy(
            mesh=MeshSpec(dp=jax.local_device_count()),
            remat="offload", offload_opt=True,
        ),
    )
    state = job.create_state(jax.random.PRNGKey(0))
    state, m = job.train_step(state, {"tokens": jnp.asarray(tokens)})
    _ = float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(3):
        state, m = job.train_step(state, {"tokens": jnp.asarray(tokens)})
    jax.block_until_ready(state)
    out = {"ok": True, "step_time_s": round((time.perf_counter() - t0) / 3, 4),
           "loss": float(m["loss"]), "backend": jax.default_backend()}
except Exception as e:
    out = {"ok": False, "error": "%%s: %%s" %% (type(e).__name__, str(e)[:400]),
           "traceback": traceback.format_exc()[-2000:]}
print("PROBE_RESULT " + json.dumps(out))
"""


NODE_CHECK = r"""
import json, sys, time
sys.path.insert(0, %(repo)r)
from dlrover_tpu.agent.node_check import _run_check_payload
t0 = time.perf_counter()
elapsed = _run_check_payload("", 1, 0)
wall = time.perf_counter() - t0
out = {"ok": elapsed is not None,
       "payload_timed_region_s": elapsed,
       "payload_wall_s": round(wall, 1),
       "timeout_budget_s": 300.0}
print("PROBE_RESULT " + json.dumps(out))
"""


DEVICE_CACHE = r"""
import json, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
import jax, jax.numpy as jnp
from dlrover_tpu.embedding.store import EmbeddingStore
from dlrover_tpu.embedding.device_cache import DeviceEmbeddingCache

dim, cache_rows, batch = 64, 1 << 16, 4096
store = EmbeddingStore(dim=dim)
cache = DeviceEmbeddingCache(store, capacity=cache_rows)
rng = np.random.RandomState(0)
# hot working set that fits the cache -> steady-state hit path
hot = rng.randint(0, cache_rows // 2, size=(64, batch)).astype(np.int64)

gather = jax.jit(lambda t, s: t[s])
# warm the WHOLE working set + compile: the timed loop must measure the
# steady-state hit path, not first-touch admissions
for i in range(64):
    slots = cache.map_batch(hot[i])
_ = gather(cache.table, jnp.asarray(slots)).block_until_ready()

t0 = time.perf_counter()
for i in range(32):
    slots = cache.map_batch(hot[i %% 64])
    out = gather(cache.table, jnp.asarray(slots))
out.block_until_ready()
hit_ms = (time.perf_counter() - t0) / 32 * 1e3

# host pull/push path: fetch rows from the store and device_put each batch
t0 = time.perf_counter()
for i in range(32):
    rows = store.lookup(hot[i %% 64])
    dev = jax.device_put(rows)
dev.block_until_ready()
pull_ms = (time.perf_counter() - t0) / 32 * 1e3
out = {"ok": True, "backend": jax.default_backend(),
       "cache_hit_ms_per_batch": round(hit_ms, 2),
       "host_pull_ms_per_batch": round(pull_ms, 2),
       "speedup": round(pull_ms / max(hit_ms, 1e-9), 2)}
print("PROBE_RESULT " + json.dumps(out))
"""


def run_probe(name: str, code: str, timeout_s: float) -> dict:
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code % {"repo": REPO}],
            capture_output=True, timeout=timeout_s, text=True,
            cwd=REPO, start_new_session=True,
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"timeout after {timeout_s:.0f}s"}
    for line in proc.stdout.splitlines():
        if line.startswith("PROBE_RESULT "):
            return json.loads(line[len("PROBE_RESULT "):])
    return {
        "ok": False,
        "error": f"no result (rc={proc.returncode})",
        "stderr": proc.stderr[-1500:],
    }


def main() -> int:
    results: dict = {}
    for name, code, timeout_s in [
        ("offload_combo", OFFLOAD_COMBO, 1200.0),
        ("node_check_payload", NODE_CHECK, 600.0),
        ("device_cache", DEVICE_CACHE, 900.0),
    ]:
        t0 = time.perf_counter()
        res = run_probe(name, code, timeout_s)
        res["total_s"] = round(time.perf_counter() - t0, 1)
        results[name] = res
        print(f"{name}: {res}", file=sys.stderr)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
    print(json.dumps({k: v.get("ok") for k, v in results.items()}))
    return 0


if __name__ == "__main__":
    main()
