// Native core for the shared-memory checkpoint arena.
//
// TPU-native analogue of the reference's pure-Python shm path
// (dlrover/python/elastic_agent/torch/ckpt_saver.py:148 _create_shared_memory
// + SharedMemoryHandler memcpy) — the copy path is the latency-critical part
// of flash checkpointing (device -> host DRAM -> shm), so it lives in C++:
// POSIX shm_open/mmap lifecycle, multi-threaded memcpy, and crc32c-style
// checksums for shard integrity on restore.
//
// Exposed as a plain C ABI consumed from Python via ctypes (no pybind11 in
// this image).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {

// Create (or open existing) a POSIX shm segment of `size` bytes.
// Returns fd >= 0 on success, -errno on failure.
int shm_arena_create(const char* name, uint64_t size) {
  int fd = shm_open(name, O_CREAT | O_RDWR, 0600);
  if (fd < 0) return -errno;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  if ((uint64_t)st.st_size < size) {
    if (ftruncate(fd, (off_t)size) != 0) {
      int e = errno;
      close(fd);
      return -e;
    }
  }
  return fd;
}

int shm_arena_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -errno;
  return fd;
}

int64_t shm_arena_size(int fd) {
  struct stat st;
  if (fstat(fd, &st) != 0) return -(int64_t)errno;
  return (int64_t)st.st_size;
}

void* shm_arena_map(int fd, uint64_t size) {
  void* p = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (p == MAP_FAILED) return nullptr;
  return p;
}

int shm_arena_unmap(void* addr, uint64_t size) {
  return munmap(addr, size) == 0 ? 0 : -errno;
}

int shm_arena_unlink(const char* name) {
  return shm_unlink(name) == 0 ? 0 : -errno;
}

int shm_arena_close(int fd) { return close(fd) == 0 ? 0 : -errno; }

// Multi-threaded memcpy: the host DRAM -> shm staging copy.  With pinned
// host buffers this saturates memory bandwidth well before thread count
// matters; nthreads<=0 picks hardware_concurrency.
void shm_parallel_memcpy(void* dst, const void* src, uint64_t n,
                         int nthreads) {
  if (nthreads <= 0) {
    nthreads = (int)std::thread::hardware_concurrency();
    if (nthreads <= 0) nthreads = 1;
  }
  if (n < (uint64_t)(1 << 22) || nthreads == 1) {  // <4MB: single memcpy
    memcpy(dst, src, n);
    return;
  }
  std::vector<std::thread> ts;
  uint64_t chunk = (n + nthreads - 1) / nthreads;
  for (int i = 0; i < nthreads; ++i) {
    uint64_t off = (uint64_t)i * chunk;
    if (off >= n) break;
    uint64_t len = (off + chunk > n) ? (n - off) : chunk;
    ts.emplace_back([=] {
      memcpy((char*)dst + off, (const char*)src + off, len);
    });
  }
  for (auto& t : ts) t.join();
}

// CRC-32 (zlib polynomial, table-driven) for shard integrity checks.
static uint32_t kCrcTable[256];
static bool kCrcInit = [] {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    kCrcTable[i] = c;
  }
  return true;
}();

uint32_t shm_crc32(const void* data, uint64_t n, uint32_t seed) {
  (void)kCrcInit;
  uint32_t c = seed ^ 0xFFFFFFFFu;
  const uint8_t* p = (const uint8_t*)data;
  for (uint64_t i = 0; i < n; ++i) c = kCrcTable[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

}  // extern "C"
