// Host-side hash-table embedding store — the TPU build's equivalent of
// TFPlus KvVariable (reference tfplus/tfplus/kv_variable/kernels/
// kv_variable.h:1021 concurrent hashmap + embedding_value.h frequency/
// version metadata + training_ops.cc sparse optimizer apply kernels).
//
// Design: striped-lock open-addressing-free sharded unordered_maps keyed by
// int64 feature ids; each row stores the embedding vector, optimizer slot
// vectors (allocated lazily per optimizer family), and metadata (frequency,
// last-update version) used for under-threshold filtering and elastic
// export/import (reference kv_variable_ops.cc import/export ops).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).  All
// batch entry points parallelize across a small thread pool when the batch
// is large; per-shard mutexes make concurrent callers safe.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Row {
  std::vector<float> emb;
  std::vector<float> slot0;  // adagrad accum / adam m / ftrl z
  std::vector<float> slot1;  // adam v / ftrl n
  int64_t freq = 0;
  int64_t version = 0;
};

struct Shard {
  std::mutex mu;
  std::unordered_map<int64_t, Row> rows;
};

struct Store {
  int dim;
  int num_shards;
  float init_scale;      // uniform(-s, s) init for new rows
  uint64_t seed;
  std::vector<Shard> shards;
  std::atomic<int64_t> version{0};

  Store(int d, int ns, float scale, uint64_t sd)
      : dim(d), num_shards(ns), init_scale(scale), seed(sd), shards(ns) {}

  Shard& shard_for(int64_t key) {
    uint64_t h = (uint64_t)key * 0x9E3779B97F4A7C15ull;
    return shards[(h >> 33) % num_shards];
  }

  void init_row(Row& row, int64_t key) {
    row.emb.resize(dim);
    if (init_scale > 0.f) {
      // Deterministic per-key init: elastic relaunches and different
      // store servers agree on a row's initial value.
      std::mt19937_64 gen(seed ^ (uint64_t)key);
      std::uniform_real_distribution<float> dist(-init_scale, init_scale);
      for (int i = 0; i < dim; ++i) row.emb[i] = dist(gen);
    } else {
      std::fill(row.emb.begin(), row.emb.end(), 0.f);
    }
  }
};


// Serialize one row at p: key,freq,version (i64) + emb,slot0,slot1 (f32[dim]).
void write_row(uint8_t* p, int64_t key, const Row& row, int dim) {
  int64_t meta[3] = {key, row.freq, row.version};
  std::memcpy(p, meta, sizeof(meta));
  p += sizeof(meta);
  std::memcpy(p, row.emb.data(), sizeof(float) * dim);
  p += sizeof(float) * dim;
  if (!row.slot0.empty())
    std::memcpy(p, row.slot0.data(), sizeof(float) * dim);
  else
    std::memset(p, 0, sizeof(float) * dim);
  p += sizeof(float) * dim;
  if (!row.slot1.empty())
    std::memcpy(p, row.slot1.data(), sizeof(float) * dim);
  else
    std::memset(p, 0, sizeof(float) * dim);
}

const int kMaxStores = 1024;
std::mutex g_stores_mu;
std::vector<Store*> g_stores(kMaxStores, nullptr);

Store* get(int handle) {
  if (handle < 0 || handle >= kMaxStores) return nullptr;
  return g_stores[handle];
}

// Run fn(begin, end) over [0, n) on up to `threads` workers.
template <typename F>
void parallel_for(int64_t n, const F& fn, int threads = 8) {
  if (n < (1 << 12) || threads <= 1) {
    fn(0, n);
    return;
  }
  int nw = std::min<int64_t>(threads, (n + 4095) / 4096);
  std::vector<std::thread> pool;
  int64_t chunk = (n + nw - 1) / nw;
  for (int w = 0; w < nw; ++w) {
    int64_t b = w * chunk, e = std::min(n, b + chunk);
    if (b >= e) break;
    pool.emplace_back([&fn, b, e] { fn(b, e); });
  }
  for (auto& t : pool) t.join();
}

}  // namespace

extern "C" {

// Create a store; returns handle >= 0 or -1.
int kv_create(int dim, int num_shards, float init_scale, uint64_t seed) {
  if (dim <= 0 || num_shards <= 0) return -1;
  std::lock_guard<std::mutex> g(g_stores_mu);
  for (int h = 0; h < kMaxStores; ++h) {
    if (g_stores[h] == nullptr) {
      g_stores[h] = new Store(dim, num_shards, init_scale, seed);
      return h;
    }
  }
  return -1;
}

void kv_destroy(int handle) {
  std::lock_guard<std::mutex> g(g_stores_mu);
  if (handle >= 0 && handle < kMaxStores) {
    delete g_stores[handle];
    g_stores[handle] = nullptr;
  }
}

int64_t kv_size(int handle) {
  Store* s = get(handle);
  if (!s) return -1;
  int64_t n = 0;
  for (auto& sh : s->shards) {
    std::lock_guard<std::mutex> g(sh.mu);
    n += (int64_t)sh.rows.size();
  }
  return n;
}

// Gather rows for `keys` into out[n, dim].  train != 0: missing keys are
// initialized+inserted and frequency/version updated (reference KvVariable
// lookup-or-create); train == 0: missing keys read as zeros, no mutation.
int kv_lookup(int handle, const int64_t* keys, int64_t n, float* out,
              int train) {
  Store* s = get(handle);
  if (!s) return -1;
  int64_t ver = s->version.load(std::memory_order_relaxed);
  int dim = s->dim;
  parallel_for(n, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      int64_t key = keys[i];
      Shard& sh = s->shard_for(key);
      std::lock_guard<std::mutex> g(sh.mu);
      auto it = sh.rows.find(key);
      if (it == sh.rows.end()) {
        if (!train) {
          std::memset(out + i * dim, 0, sizeof(float) * dim);
          continue;
        }
        Row row;
        s->init_row(row, key);
        it = sh.rows.emplace(key, std::move(row)).first;
      }
      Row& row = it->second;
      if (train) {
        row.freq++;
        row.version = ver;
      }
      std::memcpy(out + i * dim, row.emb.data(), sizeof(float) * dim);
    }
  });
  return 0;
}

// --- sparse optimizer apply kernels (reference training_ops.cc) -----------

// SGD: emb -= lr * grad
int kv_apply_sgd(int handle, const int64_t* keys, int64_t n,
                 const float* grads, float lr) {
  Store* s = get(handle);
  if (!s) return -1;
  int dim = s->dim;
  int64_t ver = ++s->version;
  parallel_for(n, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      Shard& sh = s->shard_for(keys[i]);
      std::lock_guard<std::mutex> g(sh.mu);
      auto it = sh.rows.find(keys[i]);
      if (it == sh.rows.end()) continue;
      Row& row = it->second;
      const float* gr = grads + i * dim;
      for (int d = 0; d < dim; ++d) row.emb[d] -= lr * gr[d];
      row.version = ver;
    }
  });
  return 0;
}

// Adagrad: accum += g^2; emb -= lr * g / (sqrt(accum) + eps)
int kv_apply_adagrad(int handle, const int64_t* keys, int64_t n,
                     const float* grads, float lr, float eps) {
  Store* s = get(handle);
  if (!s) return -1;
  int dim = s->dim;
  int64_t ver = ++s->version;
  parallel_for(n, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      Shard& sh = s->shard_for(keys[i]);
      std::lock_guard<std::mutex> g(sh.mu);
      auto it = sh.rows.find(keys[i]);
      if (it == sh.rows.end()) continue;
      Row& row = it->second;
      if (row.slot0.empty()) row.slot0.assign(dim, 0.f);
      const float* gr = grads + i * dim;
      for (int d = 0; d < dim; ++d) {
        row.slot0[d] += gr[d] * gr[d];
        row.emb[d] -= lr * gr[d] / (std::sqrt(row.slot0[d]) + eps);
      }
      row.version = ver;
    }
  });
  return 0;
}

// Adam (per-row step count approximated by row.freq of updates):
// m = b1*m + (1-b1)*g; v = b2*v + (1-b2)*g^2; emb -= lr_t * m/(sqrt(v)+eps)
int kv_apply_adam(int handle, const int64_t* keys, int64_t n,
                  const float* grads, float lr, float beta1, float beta2,
                  float eps, int64_t step) {
  Store* s = get(handle);
  if (!s) return -1;
  int dim = s->dim;
  int64_t ver = ++s->version;
  float bc1 = 1.f - std::pow(beta1, (float)step);
  float bc2 = 1.f - std::pow(beta2, (float)step);
  float lr_t = lr * std::sqrt(bc2) / bc1;
  parallel_for(n, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      Shard& sh = s->shard_for(keys[i]);
      std::lock_guard<std::mutex> g(sh.mu);
      auto it = sh.rows.find(keys[i]);
      if (it == sh.rows.end()) continue;
      Row& row = it->second;
      if (row.slot0.empty()) row.slot0.assign(dim, 0.f);
      if (row.slot1.empty()) row.slot1.assign(dim, 0.f);
      const float* gr = grads + i * dim;
      for (int d = 0; d < dim; ++d) {
        row.slot0[d] = beta1 * row.slot0[d] + (1.f - beta1) * gr[d];
        row.slot1[d] = beta2 * row.slot1[d] + (1.f - beta2) * gr[d] * gr[d];
        row.emb[d] -= lr_t * row.slot0[d] / (std::sqrt(row.slot1[d]) + eps);
      }
      row.version = ver;
    }
  });
  return 0;
}

// Group-lasso FTRL (reference sparse_group_ftrl): accumulator-based FTRL
// with an L2,1 (whole-row) penalty that zeroes rarely-useful rows.
// z += g - (sqrt(n+g^2)-sqrt(n))/alpha * emb;  n += g^2
// row ||z|| <= lambda1*sqrt(dim) -> emb = 0 else closed-form update.
int kv_apply_group_ftrl(int handle, const int64_t* keys, int64_t n,
                        const float* grads, float alpha, float beta,
                        float lambda1, float lambda2) {
  Store* s = get(handle);
  if (!s) return -1;
  int dim = s->dim;
  int64_t ver = ++s->version;
  parallel_for(n, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      Shard& sh = s->shard_for(keys[i]);
      std::lock_guard<std::mutex> g(sh.mu);
      auto it = sh.rows.find(keys[i]);
      if (it == sh.rows.end()) continue;
      Row& row = it->second;
      if (row.slot0.empty()) row.slot0.assign(dim, 0.f);  // z
      if (row.slot1.empty()) row.slot1.assign(dim, 0.f);  // n
      const float* gr = grads + i * dim;
      for (int d = 0; d < dim; ++d) {
        float g2 = gr[d] * gr[d];
        float sigma =
            (std::sqrt(row.slot1[d] + g2) - std::sqrt(row.slot1[d])) / alpha;
        row.slot0[d] += gr[d] - sigma * row.emb[d];
        row.slot1[d] += g2;
      }
      // Group (row) shrinkage: L2 norm of z against lambda1*sqrt(dim).
      float znorm = 0.f;
      for (int d = 0; d < dim; ++d) znorm += row.slot0[d] * row.slot0[d];
      znorm = std::sqrt(znorm);
      float thresh = lambda1 * std::sqrt((float)dim);
      if (znorm <= thresh) {
        std::fill(row.emb.begin(), row.emb.end(), 0.f);
      } else {
        float scale = (znorm - thresh) / znorm;
        for (int d = 0; d < dim; ++d) {
          float eta = (beta + std::sqrt(row.slot1[d])) / alpha + lambda2;
          row.emb[d] = -scale * row.slot0[d] / eta;
        }
      }
      row.version = ver;
    }
  });
  return 0;
}

// GroupAdam (reference tfplus group_adam in training_ops.cc): Adam moments
// plus an L2,1 whole-row lasso applied to the updated row — rows whose
// post-step norm falls under lambda*sqrt(dim) are zeroed, others shrunk.
int kv_apply_group_adam(int handle, const int64_t* keys, int64_t n,
                        const float* grads, float lr, float beta1,
                        float beta2, float eps, int64_t step,
                        float lambda_) {
  Store* s = get(handle);
  if (!s) return -1;
  int dim = s->dim;
  int64_t ver = ++s->version;
  float bc1 = 1.f - std::pow(beta1, (float)step);
  float bc2 = 1.f - std::pow(beta2, (float)step);
  float lr_t = lr * std::sqrt(bc2) / bc1;
  parallel_for(n, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      Shard& sh = s->shard_for(keys[i]);
      std::lock_guard<std::mutex> g(sh.mu);
      auto it = sh.rows.find(keys[i]);
      if (it == sh.rows.end()) continue;
      Row& row = it->second;
      if (row.slot0.empty()) row.slot0.assign(dim, 0.f);
      if (row.slot1.empty()) row.slot1.assign(dim, 0.f);
      const float* gr = grads + i * dim;
      for (int d = 0; d < dim; ++d) {
        row.slot0[d] = beta1 * row.slot0[d] + (1.f - beta1) * gr[d];
        row.slot1[d] = beta2 * row.slot1[d] + (1.f - beta2) * gr[d] * gr[d];
        row.emb[d] -= lr_t * row.slot0[d] / (std::sqrt(row.slot1[d]) + eps);
      }
      if (lambda_ > 0.f) {
        float norm = 0.f;
        for (int d = 0; d < dim; ++d) norm += row.emb[d] * row.emb[d];
        norm = std::sqrt(norm);
        float thresh = lr_t * lambda_ * std::sqrt((float)dim);
        if (norm <= thresh) {
          std::fill(row.emb.begin(), row.emb.end(), 0.f);
        } else {
          float scale = (norm - thresh) / norm;
          for (int d = 0; d < dim; ++d) row.emb[d] *= scale;
        }
      }
      row.version = ver;
    }
  });
  return 0;
}

// Delete rows by key (elastic rebalance move semantics: the router imports
// a row to its new owner, then deletes it here on the old one).  Returns
// rows actually removed.
int64_t kv_delete(int handle, const int64_t* keys, int64_t n) {
  Store* s = get(handle);
  if (!s) return -1;
  int64_t removed = 0;
  for (int64_t i = 0; i < n; ++i) {
    Shard& sh = s->shard_for(keys[i]);
    std::lock_guard<std::mutex> g(sh.mu);
    removed += (int64_t)sh.rows.erase(keys[i]);
  }
  return removed;
}

// --- metadata / filtering (reference embedding_value.h + filters) ---------

// Copy per-key (freq, version) into out_freq/out_version (missing -> -1).
int kv_metadata(int handle, const int64_t* keys, int64_t n,
                int64_t* out_freq, int64_t* out_version) {
  Store* s = get(handle);
  if (!s) return -1;
  for (int64_t i = 0; i < n; ++i) {
    Shard& sh = s->shard_for(keys[i]);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.rows.find(keys[i]);
    if (it == sh.rows.end()) {
      out_freq[i] = -1;
      out_version[i] = -1;
    } else {
      out_freq[i] = it->second.freq;
      out_version[i] = it->second.version;
    }
  }
  return 0;
}

// Evict rows with freq < min_freq or version older than
// (current - max_version_age); returns number evicted.
int64_t kv_filter(int handle, int64_t min_freq, int64_t max_version_age) {
  Store* s = get(handle);
  if (!s) return -1;
  int64_t cur = s->version.load();
  int64_t evicted = 0;
  for (auto& sh : s->shards) {
    std::lock_guard<std::mutex> g(sh.mu);
    for (auto it = sh.rows.begin(); it != sh.rows.end();) {
      bool low_freq = min_freq > 0 && it->second.freq < min_freq;
      bool stale = max_version_age > 0 &&
                   cur - it->second.version > max_version_age;
      if (low_freq || stale) {
        it = sh.rows.erase(it);
        ++evicted;
      } else {
        ++it;
      }
    }
  }
  return evicted;
}

// --- export / import (checkpoint + elastic resharding) --------------------
// Export layout per row: key(i64), freq(i64), version(i64),
// emb[dim], slot0[dim], slot1[dim]  (slots zero-filled if unallocated).

int64_t kv_export_count(int handle) { return kv_size(handle); }

int64_t kv_row_bytes(int handle) {
  Store* s = get(handle);
  if (!s) return -1;
  return 3 * (int64_t)sizeof(int64_t) + 3ll * s->dim * sizeof(float);
}

// Export up to max_rows rows whose ROUTER partition matches: per-key
// ((key * 0x9E3779B97F4A7C15) >> 33) % world == rank_filter — the exact
// hash the Python router's _owner() uses, so the rank_filter/world export
// path matches router ownership for ANY world, not only worlds dividing
// num_shards.  world<=1 exports all.  Returns rows written.
int64_t kv_export(int handle, uint8_t* buf, int64_t max_rows,
                  int rank_filter, int world) {
  Store* s = get(handle);
  if (!s) return -1;
  int dim = s->dim;
  int64_t rb = kv_row_bytes(handle);
  int64_t written = 0;
  for (int si = 0; si < s->num_shards; ++si) {
    Shard& sh = s->shards[si];
    std::lock_guard<std::mutex> g(sh.mu);
    for (auto& kv : sh.rows) {
      if (world > 1) {
        uint64_t h = ((uint64_t)kv.first * 0x9E3779B97F4A7C15ull) >> 33;
        if ((int)(h % (uint64_t)world) != rank_filter) continue;
      }
      if (written >= max_rows) return written;
      write_row(buf + written * rb, kv.first, kv.second, dim);
      ++written;
    }
  }
  return written;
}

// Dump up to max_keys (key, freq, version) triples — the scan the hybrid
// mem+disk tier uses to pick cold rows for spilling (reference tfplus
// hybrid_embedding/table_manager.h eviction scan).  Returns count.
int64_t kv_dump_keys(int handle, int64_t* keys_out, int64_t* freq_out,
                     int64_t* ver_out, int64_t max_keys) {
  Store* s = get(handle);
  if (!s) return -1;
  int64_t n = 0;
  for (auto& sh : s->shards) {
    std::lock_guard<std::mutex> g(sh.mu);
    for (auto& kv : sh.rows) {
      if (n >= max_keys) return n;
      keys_out[n] = kv.first;
      freq_out[n] = kv.second.freq;
      ver_out[n] = kv.second.version;
      ++n;
    }
  }
  return n;
}

// Export exactly the given keys' rows (same layout as kv_export) into buf;
// missing keys are skipped.  Returns rows written.
int64_t kv_export_keys(int handle, const int64_t* keys, int64_t n,
                       uint8_t* buf) {
  Store* s = get(handle);
  if (!s) return -1;
  int dim = s->dim;
  int64_t rb = kv_row_bytes(handle);
  int64_t written = 0;
  for (int64_t i = 0; i < n; ++i) {
    Shard& sh = s->shard_for(keys[i]);
    std::lock_guard<std::mutex> g(sh.mu);
    auto it = sh.rows.find(keys[i]);
    if (it == sh.rows.end()) continue;
    write_row(buf + written * rb, keys[i], it->second, dim);
    ++written;
  }
  return written;
}

int64_t kv_import(int handle, const uint8_t* buf, int64_t rows) {
  Store* s = get(handle);
  if (!s) return -1;
  int dim = s->dim;
  int64_t rb = kv_row_bytes(handle);
  for (int64_t i = 0; i < rows; ++i) {
    const uint8_t* p = buf + i * rb;
    int64_t meta[3];
    std::memcpy(meta, p, sizeof(meta));
    p += sizeof(meta);
    Shard& sh = s->shard_for(meta[0]);
    std::lock_guard<std::mutex> g(sh.mu);
    Row& row = sh.rows[meta[0]];
    row.freq = meta[1];
    row.version = meta[2];
    row.emb.assign((const float*)p, (const float*)p + dim);
    p += sizeof(float) * dim;
    row.slot0.assign((const float*)p, (const float*)p + dim);
    p += sizeof(float) * dim;
    row.slot1.assign((const float*)p, (const float*)p + dim);
  }
  return rows;
}

}  // extern "C"
