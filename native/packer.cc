// First-fit sequence packer: the host-side hot loop of long-context
// data prep (dlrover_tpu/data/packing.py).  The Python first-fit is
// O(pieces x rows) of interpreter-speed work per batch; at production
// packing rates (millions of documents) it dominates the coworker CPU.
// Same semantics as the Python reference: rows are scanned in creation
// order and a piece lands in the FIRST row with room, so python and
// native backends produce byte-identical layouts.
//
// C ABI (ctypes):
//   pack_first_fit(lengths[n] i64, n, seq_len,
//                  out_row[n] i32, out_off[n] i32, out_seg[n] i32)
//     -> number of rows used (or -1 on bad input)
// out_seg is the piece's segment index WITHIN its row (0, 1, ...) in
// offset order — exactly the ids pack_sequences assigns.

#include <cstdint>
#include <vector>

extern "C" {

int64_t pack_first_fit(const int64_t* lengths, int64_t n, int64_t seq_len,
                       int32_t* out_row, int32_t* out_off,
                       int32_t* out_seg) {
  if (n < 0 || seq_len <= 0) return -1;
  std::vector<int64_t> used;    // used slots per row
  std::vector<int32_t> pieces;  // pieces placed per row (segment counter)
  used.reserve(64);
  pieces.reserve(64);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t len = lengths[i];
    if (len <= 0 || len > seq_len) return -1;  // caller splits first
    int64_t row = -1;
    for (int64_t r = 0; r < (int64_t)used.size(); ++r) {
      if (used[r] + len <= seq_len) {
        row = r;
        break;
      }
    }
    if (row < 0) {
      row = (int64_t)used.size();
      used.push_back(0);
      pieces.push_back(0);
    }
    out_row[i] = (int32_t)row;
    out_off[i] = (int32_t)used[row];
    out_seg[i] = pieces[row]++;
    used[row] += len;
  }
  return (int64_t)used.size();
}

}  // extern "C"
