"""Top-level alias so ``python -m graftcheck dlrover_tpu/`` works from
the repo root — the canonical entry point stays
``python -m tools.graftcheck`` (same engine, same flags)."""

import sys

from tools.graftcheck.engine import main

if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `graftcheck ... | head` closed the pipe: not an error.
        sys.exit(0)
