"""Flagship elastic Llama pretraining through the full product stack.

The Llama-2 analogue of the reference's headline example
(``atorch/examples/llama2``): model + ``accelerate()`` strategy (mesh x
remat x dtype, layout planner), fused lm-head loss, elastic sampler fed
by the master's task manager, and flash checkpointing — all launched
under the elastic agent::

    python -m dlrover_tpu.run --standalone --nproc_per_node=2 \
        examples/llama_train.py -- --steps 20

Scale knobs: ``--model {tiny,300m,800m}`` picks the config;
``--strategy auto`` searches mesh factorizations instead of pure DP.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys

import dlrover_tpu.trainer as trainer_sdk


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--model", default="tiny",
                   choices=["tiny", "300m", "800m"])
    p.add_argument("--batch_per_proc", type=int, default=4)
    p.add_argument("--seq_len", type=int, default=32)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--strategy", default="dp",
                   choices=["dp", "auto"])
    p.add_argument("--remat_block", action="store_true")
    p.add_argument("--fp8", action="store_true",
                   help="route attention/MLP linears through e4m3/e5m2 "
                        "fp8_dot with delayed scaling")
    p.add_argument("--quant_grads", action="store_true",
                   help="int8-compress the dp gradient reduction "
                        "(pure-dp mesh; the DCN-bandwidth lever)")
    p.add_argument("--lora_rank", type=int, default=0,
                   help=">0: LoRA fine-tuning — train rank-r (A,B) "
                        "factors on the targeted projections, base "
                        "model frozen (reference fsdp_llama2.py "
                        "--use_lora/peft path)")
    p.add_argument("--lora_alpha", type=float, default=16.0)
    p.add_argument("--lora_targets", default="wq,wk,wv,wo",
                   help="comma-separated projection names; mlp adds "
                        "w_gate,w_up,w_down")
    p.add_argument("--init_from", default="",
                   help="HuggingFace Llama checkpoint dir to import as "
                        "the (frozen, for LoRA) base model")
    p.add_argument("--dataset_size", type=int, default=4096)
    p.add_argument("--ckpt_dir", default="")
    p.add_argument("--ckpt_interval", type=int, default=5)
    return p.parse_args()


def build_config(args):
    from dlrover_tpu.models import llama

    if args.model == "300m":
        cfg = llama.LlamaConfig.small_300m()
    elif args.model == "800m":
        cfg = llama.LlamaConfig.medium_800m()
    else:
        cfg = llama.LlamaConfig.tiny(max_seq_len=args.seq_len)
    return dataclasses.replace(cfg, remat_block=args.remat_block)


def synth_tokens(indices, seq_len, vocab):
    import numpy as np

    base = np.random.RandomState(0).randint(0, vocab, size=(seq_len + 1,))
    return np.stack(
        [(base + i) % vocab for i in indices], axis=0
    ).astype("int32")


def main() -> int:
    args = parse_args()
    ctx = trainer_sdk.init()

    import jax
    import numpy as np
    import optax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.accelerate import Strategy, accelerate
    from dlrover_tpu.parallel.mesh import MeshSpec
    from dlrover_tpu.trainer.sampler import ElasticSampler

    cfg = build_config(args)
    local_dev = jax.local_device_count()
    if args.batch_per_proc % local_dev:
        args.batch_per_proc = -(-args.batch_per_proc // local_dev) * local_dev
    global_batch = args.batch_per_proc * ctx.num_processes

    sample = synth_tokens(
        range(global_batch), args.seq_len, cfg.vocab_size
    )
    strategy = (
        "auto" if args.strategy == "auto"
        else Strategy(
            mesh=MeshSpec(dp=len(jax.devices())), fp8=args.fp8,
            quant_grads=args.quant_grads,
        )
    )

    if args.init_from and args.lora_rank == 0:
        # Full fine-tune from an import: compile against shapes first;
        # the weights stream onto the params sharding after create_state
        # (never an unsharded full copy — same discipline as the LoRA
        # branch below).
        from dlrover_tpu.models import hf_convert

        cfg = hf_convert.config_from_hf_dir(args.init_from)
        cfg = dataclasses.replace(cfg, remat_block=args.remat_block)
    if args.lora_rank > 0:
        # LoRA: base model frozen (rides the state as 'frozen'), only
        # the (A, B) factors train — reference fsdp_llama2.py peft path.
        from dlrover_tpu.models import lora

        if args.init_from:
            # 7B-scale flow: accelerate() sees SHAPES only; the real
            # weights stream from the checkpoint straight onto the
            # frozen sharding after compile (never an unsharded copy).
            from dlrover_tpu.models import hf_convert

            cfg = hf_convert.config_from_hf_dir(args.init_from)
            cfg = dataclasses.replace(cfg, remat_block=args.remat_block)
            frozen = jax.eval_shape(
                lambda: llama.init_params(jax.random.PRNGKey(0), cfg)
            )
        else:
            frozen = llama.init_params(jax.random.PRNGKey(0), cfg)
        targets = tuple(
            t.strip() for t in args.lora_targets.split(",") if t.strip()
        )

        def loss_fn(factors, b, frozen, fp8_states=None):
            return llama.loss_fn(
                lora.merge(frozen, factors), b, cfg,
                fp8_states=fp8_states,
            )

        base_for_shapes = frozen

        init_fn = lambda r: lora.init_lora(  # noqa: E731
            r, base_for_shapes, rank=args.lora_rank,
            alpha=args.lora_alpha, targets=targets,
        )
        optimizer = optax.masked(
            optax.adamw(args.lr), lora.trainable_mask
        )
    else:
        # One signature for both modes (fp8_states defaults to None in
        # llama.loss_fn): under --strategy auto the sweep mixes fp8 and
        # non-fp8 candidates, and a required fp8_states would silently
        # reject every non-fp8 point.
        loss_fn = lambda p, b, fp8_states=None: llama.loss_fn(  # noqa: E731
            p, b, cfg, fp8_states=fp8_states
        )
        init_fn = lambda r: llama.init_params(r, cfg)  # noqa: E731
        optimizer = optax.adamw(args.lr)
        frozen = None

    job = accelerate(
        loss_fn=loss_fn,
        init_fn=init_fn,
        optimizer=optimizer,
        sample_batch={"tokens": sample},
        strategy=strategy,
        param_specs="planner",
        fp8_init=(lambda: llama.init_fp8_states(cfg))
        if args.fp8 else None,
        frozen=frozen,
    )
    if args.lora_rank > 0 and args.init_from:
        # Stream the checkpoint leaf-by-leaf onto the compiled frozen
        # sharding: peak host memory ~ one tensor, device memory only
        # ever holds the sharded copy.
        from dlrover_tpu.models import hf_convert

        sharded_base, _ = hf_convert.from_hf_llama_dir(
            args.init_from, cfg, dtype=cfg.dtype,
            shardings=job.state_sharding["frozen"],
        )
        state = job.create_state(
            jax.random.PRNGKey(0), frozen_values=sharded_base
        )
    else:
        state = job.create_state(jax.random.PRNGKey(0))
        if args.init_from:
            from dlrover_tpu.models import hf_convert

            sharded, _ = hf_convert.from_hf_llama_dir(
                args.init_from, cfg, dtype=cfg.dtype,
                shardings=job.state_sharding["params"],
            )
            state["params"] = sharded

    def split_ckpt(st):
        """Checkpoints exclude the frozen base under LoRA: a factor
        save costs KBs, the base is re-attached from the live copy."""
        return {k: v for k, v in st.items() if k != "frozen"}

    start_step = 0
    ckpt = None
    if args.ckpt_dir:
        from dlrover_tpu.checkpoint.checkpointer import FlashCheckpointer

        ckpt = FlashCheckpointer(args.ckpt_dir, job_name=ctx.job_name)
        restored = ckpt.load(target=split_ckpt(state))
        if restored is not None:
            got, meta = restored
            if "frozen" in state:
                got = dict(got, frozen=state["frozen"])
            state = got
            start_step = int(meta.get("step", 0))
            print(f"[worker {ctx.process_id}] restored step={start_step}",
                  flush=True)

    sampler = ElasticSampler(
        args.dataset_size,
        batch_size_per_process=args.batch_per_proc,
        num_processes=ctx.num_processes,
        process_id=ctx.process_id,
        seed=17,
    )
    sampler.completed_steps = start_step

    step, loss = start_step, float("nan")
    it = iter(sampler)
    while step < args.steps:
        try:
            indices = next(it)
        except StopIteration:
            it = iter(sampler)
            continue
        toks = synth_tokens(indices, args.seq_len, cfg.vocab_size)
        batch = {
            "tokens": jax.make_array_from_process_local_data(
                job.batch_sharding["tokens"], toks
            )
        }
        state, metrics = job.train_step(state, batch)
        loss = float(metrics["loss"])
        step += 1
        ctx.report_step(step)
        if ckpt is not None and step % args.ckpt_interval == 0:
            ckpt.save(split_ckpt(state), meta={"step": step})
        if step % 10 == 0 or step == args.steps:
            print(f"[worker {ctx.process_id}] step {step} loss "
                  f"{loss:.4f}", flush=True)
    if ckpt is not None:
        ckpt.save(split_ckpt(state), meta={"step": step}, storage=True)
        ckpt.wait()
    print(f"TRAIN_DONE step={step} loss={loss:.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
