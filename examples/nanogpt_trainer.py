"""Elastic nanoGPT pretraining through the full Trainer SDK.

The Trainer-SDK variant of ``nanogpt_train.py`` (reference
``AtorchTrainer`` usage): eval loop, warmup+cosine LR schedule, callbacks,
checkpoint cadence — all surviving worker kills via the flash-checkpoint
restore (the schedule resumes because it lives in the optimizer state).

Run standalone on one host::

    python -m dlrover_tpu.run --standalone --nproc_per_node=2 \
        examples/nanogpt_trainer.py -- --steps 40 --ckpt_dir /tmp/ck
"""

from __future__ import annotations

import argparse
import sys


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--global_batch", type=int, default=8)
    p.add_argument("--seq_len", type=int, default=32)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--warmup_steps", type=int, default=4)
    p.add_argument("--dataset_size", type=int, default=4096)
    p.add_argument("--eval_steps", type=int, default=10)
    p.add_argument("--ckpt_dir", default="")
    p.add_argument("--save_steps", type=int, default=5)
    return p.parse_args()


def main() -> int:
    args = parse_args()

    import dlrover_tpu.trainer as sdk

    ctx = sdk.init()

    import jax
    import numpy as np

    from dlrover_tpu.models import nanogpt
    from dlrover_tpu.trainer.trainer import Trainer, TrainingArgs

    cfg = nanogpt.GPTConfig.tiny()
    cfg = type(cfg)(**{**cfg.__dict__, "block_size": args.seq_len})

    def synth(indices):
        rngs = np.random.RandomState(0)
        base = rngs.randint(0, cfg.vocab_size, size=(args.seq_len + 1,))
        out = np.stack(
            [(base + int(i)) % cfg.vocab_size for i in indices], axis=0
        ).astype("int32")
        return {"tokens": out[:, :-1], "targets": out[:, 1:]}

    def loss_fn(params, batch):
        return nanogpt.loss_fn(
            params, batch["tokens"], batch["targets"], cfg
        )

    local_dev = jax.local_device_count()
    gb = args.global_batch
    total_dev = local_dev * ctx.num_processes
    if gb % total_dev:
        gb = -(-gb // total_dev) * total_dev

    targs = TrainingArgs(
        global_batch_size=gb,
        max_micro_batch_per_proc=max(1, gb // ctx.num_processes),
        max_steps=args.steps,
        learning_rate=args.lr,
        lr_schedule="cosine",
        warmup_steps=args.warmup_steps,
        logging_steps=5,
        eval_steps=args.eval_steps,
        save_steps=args.save_steps,
        ckpt_dir=args.ckpt_dir,
        job_name=ctx.job_name,
        seed=17,
    )
    trainer = Trainer(
        loss_fn=loss_fn,
        init_fn=lambda rng: nanogpt.init_params(rng, cfg),
        args=targs,
        fetch_batch=synth,
        dataset_size=args.dataset_size,
        eval_fetch=synth,
        eval_dataset_size=max(64, gb * 4),
        master_client=ctx.client,
        step_reporter=ctx.report_step,
        num_processes=ctx.num_processes,
        process_id=ctx.process_id,
    )
    state = trainer.train(resume=True)
    final = [h for h in state.log_history if "eval_loss" in h]
    eval_loss = final[-1]["eval_loss"] if final else float("nan")
    print(
        f"TRAIN_DONE step={state.step} eval_loss={eval_loss:.4f} "
        f"lr={trainer.current_lr():.6f}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
