"""Elastic ViT image-classification training through the Trainer SDK.

The vision counterpart of ``nanogpt_trainer.py`` (reference parity: the
``examples/pytorch/mnist`` CNN job) — same elastic stack, non-LLM model
family: synthetic labeled images, eval loop, cosine LR, flash ckpt::

    python -m dlrover_tpu.run --standalone --nproc_per_node=2 \
        examples/vit_train.py -- --steps 30 --ckpt_dir /tmp/ck
"""

from __future__ import annotations

import argparse
import sys


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--global_batch", type=int, default=8)
    p.add_argument("--image_size", type=int, default=16)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--warmup_steps", type=int, default=4)
    p.add_argument("--dataset_size", type=int, default=2048)
    p.add_argument("--eval_steps", type=int, default=10)
    p.add_argument("--ckpt_dir", default="")
    p.add_argument("--save_steps", type=int, default=5)
    return p.parse_args()


def main() -> int:
    args = parse_args()

    import dlrover_tpu.trainer as sdk

    ctx = sdk.init()

    import jax
    import numpy as np

    from dlrover_tpu.models import vit
    from dlrover_tpu.trainer.trainer import Trainer, TrainingArgs

    cfg = vit.ViTConfig.tiny(image_size=args.image_size)

    def synth(indices):
        """Record i is derived from i alone (elastic re-partition safe):
        class = i % num_classes, image = class-dependent pattern+noise."""
        images, labels = [], []
        for i in indices:
            i = int(i)
            label = i % cfg.num_classes
            rng = np.random.RandomState(i)
            img = (
                np.full(
                    (cfg.image_size, cfg.image_size, cfg.channels),
                    label / cfg.num_classes, dtype=np.float32,
                )
                + 0.1 * rng.randn(cfg.image_size, cfg.image_size,
                                  cfg.channels).astype(np.float32)
            )
            images.append(img)
            labels.append(label)
        return {
            "images": np.stack(images),
            "labels": np.asarray(labels, dtype=np.int32),
        }

    def loss_fn(params, batch):
        return vit.loss_fn(params, batch, cfg)

    local_dev = jax.local_device_count()
    gb = args.global_batch
    total_dev = local_dev * ctx.num_processes
    if gb % total_dev:
        gb = -(-gb // total_dev) * total_dev

    targs = TrainingArgs(
        global_batch_size=gb,
        max_micro_batch_per_proc=max(1, gb // ctx.num_processes),
        max_steps=args.steps,
        learning_rate=args.lr,
        lr_schedule="cosine",
        warmup_steps=args.warmup_steps,
        logging_steps=5,
        eval_steps=args.eval_steps,
        save_steps=args.save_steps,
        ckpt_dir=args.ckpt_dir,
        job_name=ctx.job_name,
        seed=17,
    )
    trainer = Trainer(
        loss_fn=loss_fn,
        init_fn=lambda rng: vit.init_params(rng, cfg),
        args=targs,
        fetch_batch=synth,
        dataset_size=args.dataset_size,
        eval_fetch=synth,
        eval_dataset_size=max(64, gb * 4),
        master_client=ctx.client,
        step_reporter=ctx.report_step,
        num_processes=ctx.num_processes,
        process_id=ctx.process_id,
    )
    state = trainer.train(resume=True)
    final = [h for h in state.log_history if "eval_loss" in h]
    eval_loss = final[-1]["eval_loss"] if final else float("nan")
    print(
        f"TRAIN_DONE step={state.step} eval_loss={eval_loss:.4f}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
