"""Serving example: continuous-batching decode over a Llama model.

The serving half the reference delegates to vllm
(``atorch/rl/model_engine/model_engine.py:35``), as a runnable surface:

    python examples/llama_serve.py --requests 6 --max_new_tokens 24
    python examples/llama_serve.py --quant_kv          # int8 kv cache
    python examples/llama_serve.py --speculative       # draft + verify
    python examples/llama_serve.py --tp 4              # TP over a mesh

With ``--hf_dir`` the model comes from a HuggingFace checkpoint via the
streaming importer (``models/hf_convert.py``); otherwise a small random
model demonstrates the machinery.
"""

from __future__ import annotations

import argparse
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf_dir", default="",
                    help="HF checkpoint dir (streaming import)")
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max_new_tokens", type=int, default=24)
    ap.add_argument("--quant_kv", action="store_true",
                    help="int8 kv cache (half the decode HBM traffic)")
    ap.add_argument("--speculative", action="store_true",
                    help="draft-model speculative decode (one batched "
                         "call over all requests)")
    ap.add_argument("--spec_server", action="store_true",
                    help="speculative rounds INSIDE the continuous-"
                         "batching server (slot admission + per-slot "
                         "acceptance)")
    ap.add_argument("--draft_layers", type=int, default=1)
    ap.add_argument("--adapt_k", action="store_true",
                    help="(--spec_server) shrink/regrow the draft "
                         "window from measured acceptance")
    ap.add_argument("--decode_chunk", type=int, default=1,
                    help="tokens per dispatch in plain serving (K x "
                         "fewer device round-trips; ~9x tokens/s at "
                         "K=16 on the CPU host-loop bound)")
    ap.add_argument("--stream", action="store_true",
                    help="print request 0's tokens as they decode "
                         "(the vllm-streaming role of serve's "
                         "on_token hook)")
    ap.add_argument("--prefix_len", type=int, default=0,
                    help="share a random system prefix of N tokens "
                         "across all requests via prefix caching "
                         "(prefills once; admissions copy kv rows — "
                         "vllm's automatic-prefix-caching role)")
    ap.add_argument("--tp", type=int, default=0,
                    help="shard params over an N-way 'tp' mesh")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from dlrover_tpu.common.jax_env import ensure_platform

    ensure_platform()
    import numpy as np

    import jax

    from dlrover_tpu.models import llama, llama_infer

    try:
        from examples import serve_common
    except ImportError:  # run as a script: examples/ is sys.path[0]
        import serve_common

    if args.hf_dir:
        from dlrover_tpu.models import hf_convert

        params, cfg = hf_convert.from_hf_llama_dir(args.hf_dir)
    else:
        params, cfg = serve_common.tiny_llama(seed=args.seed)

    if args.stream and args.speculative:
        raise SystemExit(
            "--stream requires a server mode (it rides "
            "DecodeServer.serve's on_token hook); the one-shot "
            "--speculative batched call has no streaming surface"
        )
    if args.tp > 0:
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < args.tp:
            raise SystemExit(
                f"--tp {args.tp} needs {args.tp} devices, "
                f"have {len(devs)}"
            )
        mesh = Mesh(np.array(devs[: args.tp]), ("tp",))
        params, _ = llama_infer.shard_params_for_decode(
            params, cfg, mesh
        )
    prompts, rng = serve_common.seeded_requests(
        cfg, args.requests, args.seed
    )

    t0 = time.perf_counter()
    if args.speculative:
        import jax.numpy as jnp

        dcfg = llama.LlamaConfig.tiny(n_layer=args.draft_layers)
        if args.hf_dir:
            # A real deployment would load a small checkpoint here; the
            # example drafts with a random model (acceptance suffers —
            # but the output law is still exactly the target model's
            # greedy/sampled decode; a bad draft only costs speed).
            dcfg = llama.LlamaConfig(**{
                **cfg.__dict__, "n_layer": args.draft_layers
            })
        draft = llama.init_params(jax.random.PRNGKey(7), dcfg)
        # ONE batched call decodes the whole ragged request set: every
        # row drafts k proposals, a single chunked ragged verify scores
        # them all, acceptance is per-row.
        lens = np.asarray([len(p) for p in prompts], np.int32)
        P = int(lens.max())
        padded = np.zeros((len(prompts), P), np.int32)
        for b, p in enumerate(prompts):
            padded[b, : len(p)] = p
        stats: dict = {}
        out, out_lens = llama_infer.generate_speculative_batched(
            params, cfg, draft, dcfg, jnp.asarray(padded),
            jnp.asarray(lens),
            max_new_tokens=args.max_new_tokens,
            quant_kv=args.quant_kv, stats=stats,
            temperature=args.temperature,
            rng=jax.random.PRNGKey(args.seed),
        )
        outs = [
            np.asarray(out[b, : int(out_lens[b])])
            for b in range(len(prompts))
        ]
        mode = (f"speculative(batched) k=4 tokens/round="
                f"{stats.get('tokens_per_round', 0):.2f}")
    else:
        draft_kw = {}
        mode = (f"continuous-batching slots={args.slots}"
                + (f" decode_chunk={args.decode_chunk}"
                   if args.decode_chunk > 1 else ""))
        if args.spec_server:
            dcfg = llama.LlamaConfig.tiny(n_layer=args.draft_layers)
            draft_kw = {
                "draft": (
                    llama.init_params(jax.random.PRNGKey(7), dcfg),
                    dcfg,
                ),
                "draft_k": 4,
                "adapt_k": args.adapt_k,
            }
            mode = (f"continuous-batching+speculative "
                    f"slots={args.slots} k=4"
                    + (" adapt_k" if args.adapt_k else ""))
        srv = llama_infer.DecodeServer(
            params, cfg, slots=args.slots,
            # + chunk headroom (serve()'s capacity check counts the up
            # to K-1 writes a mid-chunk finish leaves behind) + the
            # shared prefix every request's cache rows now hold.
            max_len=max(64, args.max_new_tokens + 24)
            + max(0, args.decode_chunk - 1) + args.prefix_len,
            temperature=args.temperature, seed=args.seed,
            quant_kv=args.quant_kv, decode_chunk=args.decode_chunk,
            **draft_kw,
        )
        on_token = None
        if args.stream:
            def on_token(rid, tok):
                if rid == 0:
                    print(f"STREAM r0 +{tok}", flush=True)
        shared_prefix = None
        if args.prefix_len > 0:
            shared_prefix = rng.randint(
                1, cfg.vocab_size, size=(args.prefix_len,)
            ).astype(np.int32)
            mode += f" prefix_cached={args.prefix_len}"
        outs = srv.serve(prompts, max_new_tokens=args.max_new_tokens,
                         on_token=on_token,
                         shared_prefix=shared_prefix)
        if srv.last_stats:
            # Every path reports tokens_per_round; k_final is
            # speculative-only (plain/chunk report path+emitted).
            st = srv.last_stats
            mode += f" tokens/round={st['tokens_per_round']:.2f}"
            if "k_final" in st:
                mode += f" k_final={st['k_final']}"
    dt = time.perf_counter() - t0
    total_new = sum(len(o) - len(p) for o, p in zip(outs, prompts))
    for i, o in enumerate(outs[:3]):
        print(f"request {i}: {len(o)} tokens -> {o[:12].tolist()}...")
    print(
        f"SERVE_DONE requests={len(outs)} mode='{mode}' "
        f"quant_kv={args.quant_kv} new_tokens={total_new} "
        f"tokens_per_sec={total_new / dt:.1f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
