"""DeepFM sparse-recommendation training example.

The framework's criteo-style system-test analogue (reference
``examples/tensorflow/criteo_deeprec`` + ``dlrover-system-test-criteo``):
synthetic CTR data, unbounded-vocabulary embeddings in the native KV store
(local, or PS-style over ``--num_servers`` store servers), dense half jitted.

    python examples/deepfm_train.py --steps 200
    python examples/deepfm_train.py --steps 200 --num_servers 2
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch_size", type=int, default=256)
    p.add_argument("--vocab", type=int, default=100000)
    p.add_argument("--num_fields", type=int, default=8)
    p.add_argument("--embed_dim", type=int, default=16)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--num_servers", type=int, default=0,
                   help="0 = in-process store; N = PS-style servers")
    p.add_argument("--device_cache", type=int, default=0,
                   help="hot-row cache capacity: keeps embeddings "
                        "device-resident and trains them INSIDE the "
                        "jitted step (SparseCore shape)")
    p.add_argument("--ckpt_dir", default="")
    return p.parse_args()


def main() -> int:
    args = parse_args()
    from dlrover_tpu.common.jax_env import ensure_platform

    ensure_platform()  # the tunnel shim can override JAX_PLATFORMS
    import jax
    import optax

    from dlrover_tpu.embedding.layer import EmbeddingLayer
    from dlrover_tpu.embedding.optim import SparseAdagrad
    from dlrover_tpu.models import deepfm

    cfg = deepfm.DeepFMConfig(
        num_fields=args.num_fields, embed_dim=args.embed_dim
    )
    params = deepfm.init_dense_params(jax.random.PRNGKey(0), cfg)
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    if args.device_cache > 0 and args.num_servers == 0:
        return run_device_cached(args, cfg, params, opt_state, tx)
    step = deepfm.make_train_step(cfg, tx)

    servers = []
    if args.num_servers > 0:
        from dlrover_tpu.embedding.service import (
            DistributedEmbedding,
            EmbeddingServer,
        )

        servers = [
            EmbeddingServer(r, dim_by_table={
                "feat": cfg.embed_dim, "feat1": 1,
            })
            for r in range(args.num_servers)
        ]
        addrs = [s.addr for s in servers]

        class RemoteLayer:
            def __init__(self, table, dim):
                self.de = DistributedEmbedding(
                    table, dim, addrs=addrs,
                    optimizer={"kind": "adagrad", "lr": 0.1},
                )
                self.dim = dim

            def pull(self, keys, train=True):
                keys = np.asarray(keys, np.int64)
                uniq, inv = np.unique(
                    keys.reshape(-1), return_inverse=True
                )
                rows = self.de.lookup(uniq, train=train)
                return rows, {
                    "uniq": uniq, "inv": inv.astype(np.int32),
                    "shape": keys.shape,
                }

            def push(self, ctx, grad_rows):
                self.de.apply_gradients(ctx["uniq"], grad_rows)

        emb = RemoteLayer("feat", cfg.embed_dim)
        emb1 = RemoteLayer("feat1", 1)
    else:
        emb = EmbeddingLayer(cfg.embed_dim, SparseAdagrad(lr=0.1), seed=1)
        emb1 = EmbeddingLayer(1, SparseAdagrad(lr=0.1), seed=2)

    rng = np.random.default_rng(0)
    loss = None
    for i in range(1, args.steps + 1):
        keys = rng.integers(
            0, args.vocab, size=(args.batch_size, cfg.num_fields)
        )
        labels = (
            (keys[:, 0] % 3 == 0) ^ (keys[:, 1] % 2 == 0)
        ).astype(np.float32)
        rows, ctx = emb.pull(keys)
        rows1, ctx1 = emb1.pull(keys)
        params, opt_state, loss, g_rows, g_rows1 = step(
            params, opt_state, rows, ctx["inv"], rows1, ctx1["inv"], labels
        )
        emb.push(ctx, np.asarray(g_rows))
        emb1.push(ctx1, np.asarray(g_rows1))
        if i % 20 == 0:
            print(f"step {i} loss {float(loss):.4f}", flush=True)

    if args.ckpt_dir and args.num_servers == 0:
        from dlrover_tpu.embedding.checkpoint import save_table

        save_table(emb.store, args.ckpt_dir, "feat")
        save_table(emb1.store, args.ckpt_dir, "feat1")
    for s in servers:
        s.stop()
    print(f"TRAIN_DONE step={args.steps} loss={float(loss):.4f}", flush=True)
    return 0


def run_device_cached(args, cfg, params, opt_state, tx) -> int:
    """Device-resident embedding path: gather + sparse adagrad inside
    the compiled step; host store synced on a cadence + at the end."""
    import jax

    from dlrover_tpu.embedding.device_cache import DeviceEmbeddingCache
    from dlrover_tpu.embedding.store import EmbeddingStore
    from dlrover_tpu.models import deepfm

    store = EmbeddingStore(cfg.embed_dim, seed=1)
    store1 = EmbeddingStore(1, seed=2)
    cache = DeviceEmbeddingCache(
        store, args.device_cache, flush_every=50
    )
    cache1 = DeviceEmbeddingCache(
        store1, args.device_cache, flush_every=50
    )
    step = deepfm.make_cached_train_step(cfg, tx, emb_lr=0.1)

    rng = np.random.default_rng(0)

    def make_batch():
        keys = rng.integers(
            0, args.vocab, size=(args.batch_size, cfg.num_fields)
        )
        labels = (
            (keys[:, 0] % 3 == 0) ^ (keys[:, 1] % 2 == 0)
        ).astype(np.float32)
        return keys, labels

    # Admission double-buffering: the NEXT batch's store pulls + id
    # mapping (the host half) run on a worker thread while the device
    # executes the CURRENT step; apply_plan after update() is one cheap
    # scatter.  One plan in flight per cache (plan_batch contract).
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=2)
    loss = None
    keys, labels = make_batch()
    plan, plan1 = cache.plan_batch(keys), cache1.plan_batch(keys)
    for i in range(1, args.steps + 1):
        slots = cache.apply_plan(plan)
        slots1 = cache1.apply_plan(plan1)
        if i < args.steps:
            nxt_keys, nxt_labels = make_batch()
            fut = pool.submit(cache.plan_batch, nxt_keys)
            fut1 = pool.submit(cache1.plan_batch, nxt_keys)
        (params, opt_state, table, accum, table1, accum1, loss) = step(
            params, opt_state, cache.table, cache.accum, slots,
            cache1.table, cache1.accum, slots1, labels,
        )
        cache.update(table, accum)
        cache1.update(table1, accum1)
        cache.maybe_flush()
        cache1.maybe_flush()
        if i % 20 == 0:
            print(f"step {i} loss {float(loss):.4f}", flush=True)
        if i < args.steps:
            plan, plan1 = fut.result(), fut1.result()
            keys, labels = nxt_keys, nxt_labels
    pool.shutdown()

    cache.flush()
    cache1.flush()
    if args.ckpt_dir:
        from dlrover_tpu.embedding.checkpoint import save_table

        save_table(store, args.ckpt_dir, "feat")
        save_table(store1, args.ckpt_dir, "feat1")
    print(
        f"TRAIN_DONE step={args.steps} loss={float(loss):.4f} "
        f"device_cache={args.device_cache}", flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
