"""Elastic serving fleet example: N drain-aware replicas behind one
gateway (ISSUE 5) — the multi-replica generalization of
``llama_serve_elastic.py``.

Single-process demo (gateway + replicas as threads, loopback driver)::

    python examples/llama_serve_fleet.py --replicas 2 --requests 12

Process-per-role (what the chaos e2e and ``bench.py --serve_bench``
compose; each role is also how a supervised deployment runs under the
elastic agent)::

    python examples/llama_serve_fleet.py --role gateway --port 8710
    python examples/llama_serve_fleet.py --role replica \
        --gateway 127.0.0.1:8710 --replica_id r0 --journal_dir /tmp/j
    python examples/llama_serve_fleet.py --role driver \
        --gateway 127.0.0.1:8710 --requests 12 --rps 20

Sharded tier (ISSUE 9): point every role at a shared registry instead
of one gateway — gateways announce themselves and own a hash range,
replicas poll every live gateway, the driver consistent-hashes request
ids to their owner and rides out gateway deaths by resubmitting::

    python examples/llama_serve_fleet.py --role gateway \
        --registry 127.0.0.1:8700 --gateway_id g0     # and g1, ...
    python examples/llama_serve_fleet.py --role replica \
        --registry 127.0.0.1:8700 --replica_id r0 --journal_dir /tmp/j
    python examples/llama_serve_fleet.py --role driver \
        --registry 127.0.0.1:8700 --requests 12 --rps 20

Every replica rebuilds the SAME seeded float32 tiny-llama
(``serve_common``), so greedy decode is byte-identical across replicas
— a re-dispatched request completes with exactly the tokens its first
assignment would have produced, and journal replay after a kill agrees
with a fresh decode.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--role", default="all",
                   choices=("all", "gateway", "replica", "driver",
                            "draft"))
    p.add_argument("--port", type=int, default=0,
                   help="(gateway) listen port; 0 = ephemeral")
    p.add_argument("--gateway", default="",
                   help="(replica/driver) gateway host:port "
                        "(single-gateway mode)")
    p.add_argument("--registry", default="",
                   help="shared registry host:port (a "
                        "serving.RegistryServer or a master's KV): "
                        "switches every role to the SHARDED TIER — "
                        "gateways announce themselves, replicas poll "
                        "every live gateway, drivers consistent-hash "
                        "requests to their owner (ISSUE 9)")
    p.add_argument("--job", default="fleet",
                   help="(tier) registry namespace")
    p.add_argument("--gateway_id", default="g0",
                   help="(tier gateway) this gateway's id on the ring")
    p.add_argument("--metrics_port", type=int, default=-1,
                   help="(tier gateway) /metrics port (-1 = off, "
                        "0 = ephemeral): own gauges + merged tier "
                        "view + trace/flight-recorder drop counters")
    p.add_argument("--kv_relay", action="store_true",
                   help="(gateway) force the prefill->decode KV "
                        "segment through the gateway (the PR-8 relay "
                        "plane) instead of peer-to-peer tickets")
    p.add_argument("--no_kv_p2p", action="store_true",
                   help="(replica) never publish KV segments "
                        "peer-to-peer (always relay the payload)")
    p.add_argument("--replica_id", default="r0")
    p.add_argument("--replica_role", default="unified",
                   choices=("unified", "prefill", "decode"),
                   help="(replica) disaggregated role: prefill scores "
                        "prompts and exports KV segments; decode "
                        "continues from imported segments")
    p.add_argument("--quant_kv", action="store_true",
                   help="(replica) int8 KV cache — halves the "
                        "prefill->decode segment transfer")
    p.add_argument("--paged", action="store_true",
                   help="(replica) paged KV (ISSUE 19): block-pool "
                        "arena + per-request block tables; admission "
                        "by blocks actually needed, the poll reports "
                        "real memory headroom")
    p.add_argument("--block_size", type=int, default=16,
                   help="(replica) tokens per KV block under --paged")
    p.add_argument("--pool_blocks", type=int, default=0,
                   help="(replica) KV pool size in blocks under "
                        "--paged (0 = slots * max_len / block_size)")
    p.add_argument("--prefix_cache_cap", type=int, default=4,
                   help="(replica) warm prefix templates retained")
    p.add_argument("--warm_prefix_len", type=int, default=0,
                   help="(replica) pre-compile the prefix-template "
                        "path for this prefix length (the bench warms "
                        "XLA before registration so TTFT measures "
                        "admission, not compiles)")
    p.add_argument("--spec", action="store_true",
                   help="(replica) speculative serving (ISSUE 11): "
                        "advertise spec capability, attach the "
                        "gateway-announced remote draft, run draft/"
                        "verify/accept rounds with per-request "
                        "adaptive k (below break-even a stream "
                        "decodes plain)")
    p.add_argument("--draft_k", type=int, default=4,
                   help="(replica/draft) speculation width ceiling")
    p.add_argument("--spec_break_even", type=float, default=0.0,
                   help="(replica) accepted-tokens/round below which "
                        "a stream rides plain (0 = 1 + 0.6*draft_k, "
                        "the SPEC_DECODE_CPU.json break-even shape)")
    p.add_argument("--spec_min_tokens", type=int, default=0,
                   help="(gateway) max_new_tokens at which the grant "
                        "scan prefers spec-capable replicas (0 = off)")
    p.add_argument("--draft_layers", type=int, default=1,
                   help="(draft) draft model depth")
    p.add_argument("--draft_seed", type=int, default=-1,
                   help="(draft) draft init seed; -1 = share the "
                        "target seed AND shape (the ceiling draft "
                        "standing in for a trained one)")
    p.add_argument("--draft_streams", type=int, default=32,
                   help="(draft) concurrent stream caches retained")
    p.add_argument("--draft_floor_ms", type=float, default=0.0,
                   help="(draft) per-roll latency floor — the draft "
                        "chip's device time in the bench's "
                        "device-bound model")
    p.add_argument("--replicas", type=int, default=2,
                   help="(all) replica threads to run")
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--max_len", type=int, default=96)
    p.add_argument("--n_layer", type=int, default=2)
    p.add_argument("--d_model", type=int, default=64)
    p.add_argument("--d_ff", type=int, default=128)
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--max_new_tokens", type=int, default=16)
    p.add_argument("--rps", type=float, default=50.0,
                   help="(driver) Poisson arrival rate")
    p.add_argument("--deadline_s", type=float, default=0.0)
    p.add_argument("--journal_dir", default="")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--poll_interval", type=float, default=0.02)
    p.add_argument("--round_floor_ms", type=float, default=0.0,
                   help="(replica) per-round latency floor — models "
                        "the device-bound regime on a shared-CPU host")
    p.add_argument("--queue_cap", type=int, default=256)
    p.add_argument("--lease_timeout", type=float, default=10.0,
                   help="(gateway) seconds without a poll before a "
                        "replica is presumed dead and its work "
                        "re-dispatched")
    p.add_argument("--timeout", type=float, default=120.0)
    return p.parse_args(argv)


def build_replica(args, transport, draft_connect=None):
    """One seeded replica: tiny float32 llama + DecodeServer +
    ReplicaRunner (all replicas identical by construction).
    ``draft_connect`` overrides the remote-draft handle factory
    (in-process fleets: the bench smoke wires a loopback draft)."""
    import os

    import jax.numpy as jnp

    from dlrover_tpu.models import llama_infer
    from dlrover_tpu.serving import ReplicaRunner

    try:
        from examples import serve_common
    except ImportError:  # run as a script
        import serve_common

    params, cfg = serve_common.tiny_llama(
        seed=args.seed, dtype=jnp.float32,
        n_layer=getattr(args, "n_layer", 2),
        d_model=getattr(args, "d_model", 64),
        d_ff=getattr(args, "d_ff", 128),
    )
    role = getattr(args, "replica_role", "unified")
    spec = bool(getattr(args, "spec", False))
    srv = llama_infer.DecodeServer(
        params, cfg, slots=args.slots, max_len=args.max_len,
        prompt_buckets=(16, 32), seed=args.seed,
        quant_kv=getattr(args, "quant_kv", False),
        prefix_cache_cap=getattr(args, "prefix_cache_cap", 4),
        # Speculative serving (ISSUE 11): remote-draft intent sizes the
        # cache headroom; per-request adaptive k guarantees a bad
        # draft can never make a stream slower than plain decode.
        spec_remote=spec,
        draft_k=getattr(args, "draft_k", 4),
        adapt_k_per_request=spec,
        spec_break_even=getattr(args, "spec_break_even", 0.0),
        # Paged KV (ISSUE 19): block-pool arena; pool_blocks 0 keeps
        # the matched-memory default (slots * max_len / block_size).
        paged=getattr(args, "paged", False),
        block_size=getattr(args, "block_size", 16),
        pool_blocks=(getattr(args, "pool_blocks", 0) or None),
    )
    import numpy as np

    # Warm the compile caches BEFORE registering with the gateway: the
    # fleet's TTFT percentiles must measure admission+decode latency,
    # not the first request's XLA compile (~1.5s for even the tiny
    # model on CPU).  Each role warms ITS admission path; with
    # --warm_prefix_len the prefix-template jits (keyed by prefix
    # length) are compiled too.  The dummy template is dropped so it
    # never occupies the LRU or reports warm.
    warm_p0 = getattr(args, "warm_prefix_len", 0)
    dummy = np.arange(1, 5, dtype=np.int32)
    if role != "prefill":
        srv.serve([dummy], max_new_tokens=2)
    if role in ("prefill", "decode"):
        srv.prefill_request("__warm", dummy, 2)
        payload, _ = srv.export_kv("__warm")
        if role == "decode":
            srv.import_kv("__warm", payload, dummy, 2)
            srv.serve_incremental(tick=lambda: bool(
                srv.pending_count() or srv.active_rids()
            ))
    if warm_p0 > 0 and role != "decode":
        # The template path only engages when the COMBINED prompt
        # exceeds the largest bucket — a short warm prefix with a
        # short dummy tail would silently warm nothing.
        n_warm = max(warm_p0, srv.buckets[-1]) + 9
        wp = np.arange(1, n_warm + 1, dtype=np.int32)
        if role == "prefill":
            srv.prefill_request("__warmp", wp, 2, prefix_len=warm_p0)
            srv.export_kv("__warmp")
        else:
            srv.submit("__warmp", wp, 2, prefix_len=warm_p0)
            srv.serve_incremental(tick=lambda: bool(
                srv.pending_count() or srv.active_rids()
            ))
        srv.clear_prefix_templates()
    if spec and role != "prefill":
        # Warm the speculative verify programs for the widths the
        # adaptive policy actually visits (full width + the k=1
        # probe); intermediate widths compile on demand.
        cache_w = llama_infer.init_cache(
            cfg, args.slots, args.max_len, ring=False
        )
        cache_w = dict(
            cache_w, offset=jnp.zeros((args.slots,), jnp.int32)
        )
        for kw_ in {1, getattr(args, "draft_k", 4)}:
            progs = llama_infer._spec_programs(cfg, cfg, kw_, 0.0, 0, 0)
            progs["target_verify"](
                params, cache_w,
                jnp.zeros((args.slots, kw_ + 1), jnp.int32),
            )
    journal = None
    if args.journal_dir:
        os.makedirs(args.journal_dir, exist_ok=True)
        journal = os.path.join(
            args.journal_dir, f"{args.replica_id}.jsonl"
        )
    return ReplicaRunner(
        srv, transport, args.replica_id, journal_path=journal,
        poll_interval=args.poll_interval,
        round_floor_s=args.round_floor_ms / 1000.0,
        role=role,
        kv_p2p=not getattr(args, "no_kv_p2p", False),
        draft_connect=draft_connect,
    )


def drive(args, transport, core=None, client=None):
    """Submit the seeded request stream at Poisson arrivals, poll every
    result, print the summary line the tests and bench key on.
    ``client`` overrides the transport-bound ServeClient (the tier
    driver passes a consistent-hash-routing TierClient)."""
    import numpy as np

    from dlrover_tpu.models import llama
    from dlrover_tpu.serving import ServeClient

    try:
        from examples import serve_common
    except ImportError:
        import serve_common

    cfg = llama.LlamaConfig.tiny(n_layer=2)
    prompts, _ = serve_common.seeded_requests(
        cfg, args.requests, args.seed + 1
    )
    arr_rng = np.random.RandomState(args.seed + 7)
    gaps = arr_rng.exponential(1.0 / max(args.rps, 1e-6),
                               size=args.requests)
    if client is None:
        client = ServeClient(transport)
    t0 = time.perf_counter()
    for i, prompt in enumerate(prompts):
        time.sleep(float(gaps[i]))
        ack = client.submit(
            f"req-{i}", prompt, args.max_new_tokens,
            deadline_s=args.deadline_s,
        )
        print(f"SUBMIT req-{i} status={ack.status}", flush=True)
    done = 0
    total_new = 0
    for i in range(args.requests):
        reply = client.result(f"req-{i}", timeout=args.timeout)
        n = len(reply.tokens)
        print(
            f"RESULT req-{i} state={reply.state} new_tokens={n} "
            f"replica={reply.replica}", flush=True,
        )
        if reply.state == "done":
            done += 1
            total_new += n
    dt = time.perf_counter() - t0
    extra = ""
    if core is not None:
        c = core.stats_snapshot()["counters"]
        extra = (f" redispatched={c['redispatched']} "
                 f"duplicates={c['duplicate_completions']}")
    print(
        f"FLEET_DONE requests={args.requests} completed={done} "
        f"new_tokens={total_new} tokens_per_sec={total_new / dt:.1f}"
        f"{extra}", flush=True,
    )
    return 0 if done == args.requests else 1


def main() -> int:
    args = parse_args()

    from dlrover_tpu.common.jax_env import ensure_platform

    ensure_platform()

    # Name this process's flight recorder after its role (ISSUE 12):
    # merged traces and postmortems read "gw-g1"/"rep-r0", not pids.
    # No-op beyond the label unless DLROVER_TPU_OBS_DIR is set.
    from dlrover_tpu import obs

    obs.set_process({
        "gateway": f"gw-{args.gateway_id}",
        "replica": f"rep-{args.replica_id}",
        "draft": f"draft-{args.replica_id}",
        "driver": "driver",
    }.get(args.role, "fleet"))

    def tier_registry():
        from dlrover_tpu.serving import RpcKv, ServeRegistry

        return ServeRegistry(
            RpcKv(args.registry), job=args.job,
            lease_s=args.lease_timeout,
        )

    if args.role == "gateway":
        from dlrover_tpu.serving import (
            Gateway,
            GatewayConfig,
            GatewayTierNode,
        )

        cfg = GatewayConfig(
            queue_cap=args.queue_cap,
            lease_timeout_s=args.lease_timeout,
            kv_p2p=not args.kv_relay,
            spec_decode_min_tokens=args.spec_min_tokens,
        )
        if args.registry:
            node = GatewayTierNode(
                args.gateway_id, tier_registry(), port=args.port,
                config=cfg,
                metrics_port=(
                    args.metrics_port if args.metrics_port >= 0
                    else None
                ),
            )
            node.start()
            gw = node.gateway
            print(
                f"GATEWAY_READY port={gw.port} id={args.gateway_id}"
                + (f" metrics={node.metrics_port}"
                   if node.metrics_port is not None else ""),
                flush=True,
            )
        else:
            node = None
            gw = Gateway(port=args.port, config=cfg)
            gw.start()
            print(f"GATEWAY_READY port={gw.port}", flush=True)
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        while not stop.wait(2.0):
            snap = gw.core.stats_snapshot()
            print(
                "FLEET_STATS "
                + json.dumps({
                    "queue": snap["queue_depth"],
                    "alive": snap["replicas_alive"],
                    "occupancy": round(snap["occupancy"], 3),
                    "completed": snap["counters"]["completed"],
                    "ttft_p95_ms": gw.ttft_ms.percentile(0.95),
                }), flush=True,
            )
        if node is not None:
            node.stop()
        else:
            gw.stop()
        return 0

    class _T:
        """RpcClient with the runner's best-effort budget."""

        def __init__(self, addr):
            from dlrover_tpu.common.rpc import RpcClient

            self._c = RpcClient(addr, timeout=5.0)

        def call(self, msg, **kw):
            return self._c.call(msg, deadline=10.0,
                                idempotent=True, **kw)

    if args.role == "draft":
        # Draft replica (ISSUE 11): a small proposal server registered
        # as the fifth role family; spec targets learn its address
        # from the gateway's poll replies and pull per-round
        # proposals directly (the P2P segment-path shape).
        import jax.numpy as jnp

        from dlrover_tpu.serving import (
            DraftReplicaRunner,
            DraftServer,
            DraftWorker,
        )

        try:
            from examples import serve_common
        except ImportError:
            import serve_common

        if args.draft_seed < 0:
            # Ceiling draft: the target itself (stands in for a
            # trained draft — acceptance ~k+1; the committed
            # SPEC_DECODE_CPU.json bounds the realistic range).
            dparams, dcfg = serve_common.tiny_llama(
                seed=args.seed, dtype=jnp.float32,
                n_layer=args.n_layer, d_model=args.d_model,
                d_ff=args.d_ff,
            )
        else:
            dparams, dcfg = serve_common.tiny_llama(
                seed=args.draft_seed, dtype=jnp.float32,
                n_layer=args.draft_layers, d_model=args.d_model,
                d_ff=args.d_ff,
            )
        worker = DraftWorker(
            dparams, dcfg, max_len=args.max_len,
            draft_k=args.draft_k, max_streams=args.draft_streams,
            seed=args.seed, worker_id=args.replica_id,
            round_floor_s=args.draft_floor_ms / 1000.0,
        )
        # Warm every roll/score program BEFORE registering, so target
        # TTFT never pays a draft-side XLA compile.  warm() bypasses
        # the proposal loop: the chaos site's step gate (completed
        # rolls) must only count real serving traffic.
        worker.warm()
        server = DraftServer(worker)
        runner = DraftReplicaRunner(
            server, _T(args.gateway), args.replica_id,
            poll_interval=max(args.poll_interval, 0.05),
        )
        signal.signal(signal.SIGTERM, lambda *_: runner.stop())
        print(
            f"DRAFT_READY id={args.replica_id} addr={server.addr}",
            flush=True,
        )
        runner.run()
        print(
            f"DRAFT_DONE id={args.replica_id} rolls={worker.rolls} "
            f"proposed={worker.proposed_tokens}", flush=True,
        )
        return 0

    if args.role == "replica":
        if args.registry:
            from dlrover_tpu.serving import TierReplicaLink

            transport = TierReplicaLink(
                tier_registry(), args.replica_id,
            )
        else:
            transport = _T(args.gateway)
        runner = build_replica(args, transport)
        print(f"REPLICA_READY id={args.replica_id}", flush=True)
        runner.run()
        print(
            f"REPLICA_DONE id={args.replica_id} served="
            f"{runner.served} replayed={runner.replayed}", flush=True,
        )
        return 0

    if args.role == "driver":
        if args.registry:
            from dlrover_tpu.serving import TierClient

            client = TierClient(tier_registry())
            rc = drive(args, None, client=client)
            print(
                f"DRIVER_RESUBMITTED {client.resubmitted}", flush=True,
            )
            return rc
        from dlrover_tpu.common.rpc import RpcClient

        return drive(args, RpcClient(args.gateway, timeout=10.0))

    # --role all: one-process fleet (demo): loopback gateway, replica
    # threads, inline driver.
    from dlrover_tpu.serving import (
        Gateway,
        GatewayConfig,
        LoopbackTransport,
    )

    gw = Gateway(port=0, config=GatewayConfig(queue_cap=args.queue_cap))
    gw.start()
    transport = LoopbackTransport(gw.handle)
    threads = []
    runners = []
    for i in range(args.replicas):
        rargs = argparse.Namespace(**vars(args))
        rargs.replica_id = f"r{i}"
        runner = build_replica(rargs, transport)
        runners.append(runner)
        th = threading.Thread(target=runner.run, daemon=True,
                              name=f"replica-{i}")
        th.start()
        threads.append(th)
    try:
        rc = drive(args, transport, core=gw.core)
    finally:
        for runner in runners:
            gw.core.drain(runner.replica_id)
        for th in threads:
            th.join(timeout=30)
        gw.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
