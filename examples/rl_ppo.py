"""PPO post-training example: llama actor + KV-cache rollouts + RLVR.

The framework's RL entry (reference ``atorch/rl``: PPO trainer + model
engine, generation delegated to vllm — here rollouts run through the
in-framework KV-cache decoder, ``rl/engine.py llama_cached_generate``).
The task is verifiable-reward style: the policy earns reward for
emitting a target token, so learning is measurable without a reward
model.

    python examples/rl_ppo.py --iterations 30
"""

from __future__ import annotations

import argparse
import os
import sys

# Runnable directly from a checkout: `python examples/rl_ppo.py` puts
# examples/ (not the repo root) on sys.path.
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--iterations", type=int, default=30)
    p.add_argument("--rollout_batch", type=int, default=64)
    p.add_argument("--response_len", type=int, default=4)
    p.add_argument("--prompt_len", type=int, default=2)
    p.add_argument("--target_token", type=int, default=7)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--quant_kv", action="store_true",
                   help="int8 kv cache for rollouts (half the decode "
                        "HBM traffic)")
    p.add_argument("--llama", action="store_true",
                   help="tiny-llama actor with KV-cache rollouts "
                        "(default: a 1-layer toy LM — faster on CPU)")
    return p.parse_args()


def main() -> int:
    args = parse_args()
    if args.iterations <= 0:
        print("--iterations must be positive", file=sys.stderr)
        return 2
    from dlrover_tpu.common.jax_env import ensure_platform

    ensure_platform()  # the tunnel shim can override JAX_PLATFORMS
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.rl.config import PPOConfig
    from dlrover_tpu.rl.engine import ModelEngine, ModelRole, RoleSpec
    from dlrover_tpu.rl.trainer import PPOTrainer

    cfg = PPOConfig(
        rollout_batch_size=args.rollout_batch,
        minibatch_size=args.rollout_batch // 2,
        response_length=args.response_len,
        ppo_epochs=4,
        actor_lr=args.lr,
        critic_lr=args.lr,
        init_kl_coef=0.02,
        temperature=1.0,
    )
    target = args.target_token

    def reward(tokens: np.ndarray) -> np.ndarray:
        resp = tokens[:, args.prompt_len:]
        return (resp == target).mean(axis=1).astype(np.float32) * 2.0

    rng = jax.random.PRNGKey(0)
    if args.llama:
        from dlrover_tpu.models import llama
        from dlrover_tpu.rl.engine import llama_cached_generate

        mcfg = llama.LlamaConfig.tiny(
            n_layer=2, max_seq_len=args.prompt_len + args.response_len + 8
        )
        actor_params = llama.init_params(rng, mcfg)
        actor = RoleSpec(
            lambda p, t: llama.forward(p, t, mcfg)[0],
            actor_params,
            trainable=True,
            generate_fn=llama_cached_generate(
                mcfg, cfg, quant_kv=args.quant_kv
            ),
        )
        vocab = mcfg.vocab_size
    else:
        vocab = 32
        hidden = 32
        k1, k2, k3 = jax.random.split(rng, 3)
        params = {
            "emb": jax.random.normal(k1, (vocab, hidden)) * 0.1,
            "w": jax.random.normal(k2, (hidden, hidden)) * 0.1,
            "out": jax.random.normal(k3, (hidden, vocab)) * 0.1,
        }

        def lm_apply(p, tokens):
            h = jnp.tanh(p["emb"][tokens] @ p["w"])
            return h @ p["out"]

        actor = RoleSpec(lm_apply, params, trainable=True)

    ck1, ck2 = jax.random.split(jax.random.PRNGKey(1))
    chidden = 32
    critic_params = {
        "emb": jax.random.normal(ck1, (vocab, chidden)) * 0.1,
        "v": jax.random.normal(ck2, (chidden,)) * 0.1,
    }

    def critic_apply(p, tokens):
        return jnp.tanh(p["emb"][tokens]) @ p["v"]

    engine = ModelEngine(
        {
            ModelRole.ACTOR: actor,
            ModelRole.CRITIC: RoleSpec(
                critic_apply, critic_params, trainable=True
            ),
        },
        cfg,
        reward_fn=reward,
    )
    trainer = PPOTrainer(engine, cfg, seed=0)
    prompts = np.ones(
        (cfg.rollout_batch_size, args.prompt_len), np.int32
    )

    def prompt_iter():
        while True:
            yield prompts

    first = trainer.make_experience(prompts)
    trainer.buffer.clear()
    print(f"iteration 0: score={first['score_mean']:.3f}", flush=True)
    stats = trainer.learn(
        prompt_iter(), total_iterations=args.iterations, log_every=5
    )
    toks = np.asarray(
        engine.generate(
            jnp.asarray(prompts), jax.random.PRNGKey(9)
        )
    )
    frac = float((toks[:, args.prompt_len:] == target).mean())
    print(
        f"TRAIN_DONE iterations={args.iterations} "
        f"score={stats['score_mean']:.3f} "
        f"(from {first['score_mean']:.3f}) target_frac={frac:.3f}",
        flush=True,
    )
    return 0 if stats["score_mean"] > first["score_mean"] else 1


if __name__ == "__main__":
    sys.exit(main())
