"""Elastic serving worker: a DecodeServer supervised by the elastic
agent, with a completion journal so worker kills never lose finished
requests.

Run under the launcher (the agent restarts the worker on failure; the
restarted worker replays only in-flight requests)::

    python -m dlrover_tpu.run --standalone --nproc_per_node=1 \
        examples/llama_serve_elastic.py -- \
        --requests 12 --max_new_tokens 96 --journal_dir /tmp/j

The reference's serving story has no elasticity at all (its RL stack
shells out to an unsupervised vllm, atorch/rl/model_engine/
model_engine.py:35); here the same master->agent supervision tree that
restarts training workers restarts the serving worker, and
``serve_journaled`` gives the serving-side restore contract (journal +
deterministic replay instead of shm checkpoint restore).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=12)
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--max_new_tokens", type=int, default=96)
    p.add_argument("--journal_dir", required=True)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--throttle_s", type=float, default=0.0,
                   help="sleep per completion (stretches the serve "
                        "window so tests can land a kill mid-run)")
    return p.parse_args()


def main() -> int:
    args = parse_args()

    import dlrover_tpu.trainer as trainer_sdk

    ctx = trainer_sdk.init()

    import jax.numpy as jnp

    from dlrover_tpu.models import llama_infer

    try:
        from examples import serve_common
    except ImportError:  # launched as a worker script
        import serve_common

    # Seeded model + requests: a restarted worker rebuilds the SAME
    # server, so greedy replay is byte-identical.  float32 keeps the
    # continuation independent of slot-batch shape too (bf16 argmax can
    # flip near ties between batched and solo scoring).
    params, cfg = serve_common.tiny_llama(
        seed=args.seed, dtype=jnp.float32
    )
    prompts, _ = serve_common.seeded_requests(
        cfg, args.requests, args.seed + 1
    )
    os.makedirs(args.journal_dir, exist_ok=True)
    journal = os.path.join(args.journal_dir, "results.jsonl")

    srv = llama_infer.DecodeServer(
        params, cfg, slots=args.slots,
        max_len=max(64, args.max_new_tokens + 16),
    )
    served = [0]

    def on_serve(rid, tokens):
        served[0] += 1
        # Progress for the agent's hang detector AND for kill-timing in
        # the e2e test.
        ctx.report_step(served[0])
        print(f"SERVED rid={rid} ({served[0]} new this incarnation)",
              flush=True)
        if args.throttle_s > 0:
            time.sleep(args.throttle_s)

    t0 = time.perf_counter()
    outs = llama_infer.serve_journaled(
        srv, prompts, args.max_new_tokens, journal, on_serve=on_serve,
    )
    dt = time.perf_counter() - t0
    replayed = len(prompts) - served[0]
    total_new = sum(len(o) - len(p) for o, p in zip(outs, prompts))
    print(
        f"SERVE_ELASTIC_DONE requests={len(outs)} "
        f"served_now={served[0]} from_journal={replayed} "
        f"new_tokens={total_new} tokens_per_sec={total_new / dt:.1f}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
