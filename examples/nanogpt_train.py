"""End-to-end elastic nanoGPT pretraining (BASELINE.json configs[0]).

Run standalone on one host (CPU devices or a TPU host)::

    python -m dlrover_tpu.run --standalone --nproc_per_node=2 \
        examples/nanogpt_train.py -- --steps 20

The script demonstrates the minimum elastic slice: agent-bootstrapped
``jax.distributed`` world, DP mesh, elastic sampler, per-step master
reporting, flash-checkpoint save/restore (warm restart survives worker
kills).
"""

from __future__ import annotations

import argparse
import sys
import time

import dlrover_tpu.trainer as trainer_sdk


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch_per_proc", type=int, default=4)
    p.add_argument("--seq_len", type=int, default=32)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--dataset_size", type=int, default=4096)
    p.add_argument("--ckpt_dir", default="")
    p.add_argument("--ckpt_interval", type=int, default=5)
    # 0 = memory-only periodic saves (durable persistence rides the
    # agent's breakpoint save); N = also request async storage persist
    # (and its commit protocol) every N steps.
    p.add_argument("--ckpt_storage_interval", type=int, default=0)
    return p.parse_args()


def synth_batch(indices, seq_len, vocab):
    """Deterministic synthetic tokens: record i is derived from i alone, so
    any process can materialize any record (elastic re-partition safe)."""
    import numpy as np

    rngs = np.random.RandomState(0)
    base = rngs.randint(0, vocab, size=(seq_len + 1,))
    out = np.stack(
        [(base + i) % vocab for i in indices], axis=0
    ).astype("int32")
    return out[:, :-1], out[:, 1:]


def main() -> int:
    args = parse_args()
    ctx = trainer_sdk.init()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dlrover_tpu.models import nanogpt
    from dlrover_tpu.trainer.sampler import ElasticSampler

    cfg = nanogpt.GPTConfig.tiny()
    cfg = type(cfg)(**{**cfg.__dict__, "block_size": args.seq_len})

    # Round per-proc batch up to a multiple of local devices so the global
    # batch always divides the dp axis (each process contributes
    # local_device_count devices to the mesh regardless of nproc).
    local_dev = jax.local_device_count()
    if args.batch_per_proc % local_dev:
        args.batch_per_proc = -(-args.batch_per_proc // local_dev) * local_dev

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("dp",))
    repl = NamedSharding(mesh, P())
    data_sharding = NamedSharding(mesh, P("dp"))

    params = jax.device_put(
        nanogpt.init_params(jax.random.PRNGKey(0), cfg), repl
    )
    tx = optax.adamw(args.lr)
    opt_state = jax.device_put(tx.init(params), repl)

    @jax.jit
    def train_step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(nanogpt.loss_fn)(
            params, tokens, targets, cfg
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    start_step = 0
    ckpt = None
    if args.ckpt_dir:
        from dlrover_tpu.checkpoint.checkpointer import FlashCheckpointer

        ckpt = FlashCheckpointer(args.ckpt_dir, job_name=ctx.job_name)
        restored = ckpt.load(
            target={"params": params, "opt_state": opt_state}
        )
        if restored is not None:
            state, meta = restored
            params, opt_state = state["params"], state["opt_state"]
            start_step = int(meta.get("step", 0))
            print(f"[worker {ctx.process_id}] restored step={start_step}",
                  flush=True)

    sampler = ElasticSampler(
        args.dataset_size,
        batch_size_per_process=args.batch_per_proc,
        num_processes=ctx.num_processes,
        process_id=ctx.process_id,
        seed=17,
    )
    sampler.completed_steps = start_step

    step = start_step
    loss = float("nan")
    it = iter(sampler)
    while step < args.steps:
        try:
            indices = next(it)
        except StopIteration:
            it = iter(sampler)
            continue
        x_np, y_np = synth_batch(indices, args.seq_len, cfg.vocab_size)
        x = jax.make_array_from_process_local_data(data_sharding, x_np)
        y = jax.make_array_from_process_local_data(data_sharding, y_np)
        params, opt_state, loss = train_step(params, opt_state, x, y)
        step += 1
        ctx.report_step(step)
        if ckpt is not None and step % args.ckpt_interval == 0:
            durable = (
                args.ckpt_storage_interval > 0
                and step % args.ckpt_storage_interval == 0
            )
            ckpt.save(
                {"params": params, "opt_state": opt_state},
                meta={"step": step},
                storage=durable,
            )
        if step % 10 == 0 or step == args.steps:
            print(
                f"[worker {ctx.process_id}] step {step} loss "
                f"{float(loss):.4f}", flush=True,
            )
    if ckpt is not None:
        ckpt.save(
            {"params": params, "opt_state": opt_state},
            meta={"step": step},
            storage=True,
        )
        ckpt.wait()
    print(f"TRAIN_DONE step={step} loss={float(loss):.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
