"""Shared setup for the serving examples: one seeded tiny-llama and one
seeded request stream, so ``llama_serve.py``, ``llama_serve_elastic.py``
and ``llama_serve_fleet.py`` cannot drift apart on the model/workload
they demonstrate (and the elastic/fleet replay contracts — which depend
on every incarnation rebuilding the SAME model and prompts — are
spelled in exactly one place)."""

from __future__ import annotations


def tiny_llama(seed: int = 0, n_layer: int = 2, dtype=None, **over):
    """Seeded tiny Llama: ``(params, cfg)``.  ``dtype`` (e.g.
    ``jnp.float32``) pins the decode numerics — the elastic/fleet
    examples use float32 so greedy replay is byte-identical independent
    of slot-batch shape (bf16 argmax can flip near ties).  ``over``
    passes further ``LlamaConfig.tiny`` overrides (the serve bench's
    routing rows size the model up so admission prefill costs what it
    does in production)."""
    import jax

    from dlrover_tpu.models import llama

    kw = dict(over)
    if dtype is not None:
        kw["dtype"] = dtype
    cfg = llama.LlamaConfig.tiny(n_layer=n_layer, **kw)
    params = llama.init_params(jax.random.PRNGKey(seed), cfg)
    return params, cfg


def seeded_requests(cfg, requests: int, seed: int,
                    min_len: int = 4, max_len: int = 12):
    """The seeded mixed-length request stream: ``(prompts, rng)``.
    ``rng`` continues the stream (``llama_serve.py`` draws its shared
    prefix from it) so callers reproduce the exact pre-refactor
    draws."""
    import numpy as np

    rng = np.random.RandomState(seed)
    prompts = [
        rng.randint(1, cfg.vocab_size, size=(int(n),)).astype(np.int32)
        for n in rng.randint(min_len, max_len, size=(requests,))
    ]
    return prompts, rng
