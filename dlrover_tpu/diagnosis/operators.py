"""Master-side inference operators.

Parity with reference ``master/diagnosis/inferencechain/inferenceoperator/``
(``check_training_hang_operator.py:32``, ``check_failure_node_operator.py``).
TPU signal sources: the speed monitor's global-step clock and per-node step
reports replace xpu-timer kernel-gap metrics; compile grace windows keep a
first XLA compile from reading as a hang.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

from dlrover_tpu.diagnosis.data import DiagnosisDataManager, DiagnosisDataType
from dlrover_tpu.diagnosis.inference import (
    Attribution,
    Inference,
    InferenceName,
    InferenceOperator,
)


class CheckTrainingHangOperator(InferenceOperator):
    """Flags nodes whose step reports stalled while the job is nominally
    running (reference ``check_training_hang_operator.py:32``)."""

    def __init__(
        self,
        data_manager: DiagnosisDataManager,
        speed_monitor=None,
        hang_timeout_s: float = 1800.0,
        compile_grace_s: float = 3600.0,
    ):
        self._data = data_manager
        self._speed_monitor = speed_monitor
        self._hang_timeout = hang_timeout_s
        self._compile_grace = compile_grace_s
        self._started_at = time.monotonic()

    def is_compatible(self, inference: Inference) -> bool:
        return inference.name == InferenceName.TRAINING_HANG

    def infer(self, inferences: List[Inference]) -> List[Inference]:
        now = time.monotonic()
        # Whole-job hang: the global step stopped advancing.
        if self._speed_monitor is not None:
            if (
                self._speed_monitor.completed_global_step == 0
                and now - self._started_at < self._compile_grace
            ):
                return []  # still compiling / warming up
            if self._speed_monitor.hang_detected(self._hang_timeout):
                return [
                    Inference(
                        InferenceName.TRAINING_HANG,
                        Attribution.HANG,
                        {
                            "node_id": "-1",
                            "reason": (
                                f"global step stalled >"
                                f"{self._hang_timeout:.0f}s"
                            ),
                        },
                    )
                ]
        # Per-node hang: a node's own step reports went quiet while others
        # kept reporting.
        latest = self._data.latest_per_node(DiagnosisDataType.STEP_METRICS)
        if len(latest) < 2:
            return []
        times = {nid: rec.timestamp for nid, rec in latest.items()}
        freshest = max(times.values())
        out = []
        for nid, ts in times.items():
            if freshest - ts > self._hang_timeout:
                out.append(
                    Inference(
                        InferenceName.TRAINING_HANG,
                        Attribution.HANG,
                        {
                            "node_id": str(nid),
                            "reason": (
                                f"node {nid} step reports stalled "
                                f"{freshest - ts:.0f}s behind peers"
                            ),
                        },
                    )
                )
        return out


class CheckFailureNodeOperator(InferenceOperator):
    """Classifies reported node failures (reference
    ``check_failure_node_operator.py``): fatal error patterns in the
    reported logs mean the node itself is sick -> relaunch."""

    # Patterns that indicate the *node/runtime*, not the user code, failed.
    NODE_ERROR_PATTERNS = (
        "hardware",
        "ici link",
        "device unavailable",
        "tpu initialization failed",
        "out of memory",
        "coordination service",
        "heartbeat",
    )

    def __init__(self, data_manager: DiagnosisDataManager):
        self._data = data_manager

    def is_compatible(self, inference: Inference) -> bool:
        return inference.name == InferenceName.NODE_FAILURE

    def infer(self, inferences: List[Inference]) -> List[Inference]:
        out = []
        for rec in self._data.get_data(DiagnosisDataType.FAILURE):
            content = rec.content.lower()
            node_error = any(
                p in content for p in self.NODE_ERROR_PATTERNS
            )
            out.append(
                Inference(
                    InferenceName.NODE_FAILURE,
                    Attribution.FAILED if node_error else Attribution.HEALTHY,
                    {
                        "node_id": str(rec.node_id),
                        "reason": rec.content[:200],
                        "node_error": str(node_error),
                    },
                )
            )
        return out


def parse_step_metrics(content: str) -> Optional[dict]:
    """Parse a STEP_METRICS report payload ({"step": int, "ts": float})."""
    try:
        d = json.loads(content)
        return d if isinstance(d, dict) else None
    except (ValueError, TypeError):
        return None


class CheckStragglerOperator(InferenceOperator):
    """Runtime straggler detection from per-op metrics (the in-training
    complement of the pre-flight node-check pairing; reference feeds
    xpu-timer per-op scrape into diagnosis,
    ``diagnosis/datacollector/xpu_timer_metric_collector.py:22``).

    Workers report ``utils.op_metrics`` JSON (step percentiles + device
    time split by op class) as ``DiagnosisDataType.OP_METRICS``; a node
    whose step p50 exceeds ``ratio`` x the cluster median is flagged.
    The collective fraction rides along in the reason: a sick node's
    PEERS show collective share exploding (they wait in the collective),
    while the straggler itself shows compute time growing."""

    def __init__(
        self,
        data_manager: DiagnosisDataManager,
        *,
        ratio: float = 2.0,
        min_nodes: int = 2,
        stale_s: float = 600.0,
    ):
        self._data = data_manager
        self._ratio = ratio
        self._min_nodes = min_nodes
        self._stale = stale_s

    def is_compatible(self, inference: Inference) -> bool:
        return inference.name == InferenceName.STRAGGLER

    def infer(self, inferences: List[Inference]) -> List[Inference]:
        latest = self._data.latest_per_node(DiagnosisDataType.OP_METRICS)
        now = time.time()  # vs worker-stamped record timestamps (wall)
        p50 = {}
        coll = {}
        for nid, rec in latest.items():
            # graftcheck: disable=OB301 -- rec.timestamp is the WORKER's
            # wall clock; wall is the only shared timeline
            if now - rec.timestamp > self._stale:
                continue
            try:
                payload = json.loads(rec.content)
                if not isinstance(payload, dict):
                    continue  # malformed report must not kill the pass
                metrics = payload.get("metrics", payload)
                if not isinstance(metrics, dict):
                    continue
                v = float(metrics.get("step_p50_s", 0.0))
            except (ValueError, TypeError, AttributeError):
                continue
            if v > 0:
                p50[nid] = v
                coll[nid] = float(
                    metrics.get("optime_collective_frac", 0.0)
                )
        if len(p50) < self._min_nodes:
            return []
        xs = sorted(p50.values())
        # LOWER median: with 2 nodes the upper median is the straggler's
        # own value and the ratio test could never fire.
        median = xs[(len(xs) - 1) // 2]
        out = []
        for nid, v in p50.items():
            if median > 0 and v > self._ratio * median:
                out.append(
                    Inference(
                        InferenceName.STRAGGLER,
                        Attribution.STRAGGLER,
                        {
                            "node_id": str(nid),
                            "reason": (
                                f"node {nid} step p50 {v * 1e3:.0f}ms > "
                                f"{self._ratio:.1f}x cluster median "
                                f"{median * 1e3:.0f}ms "
                                f"(collective_frac={coll.get(nid, 0):.2f})"
                            ),
                        },
                    )
                )
        return out
