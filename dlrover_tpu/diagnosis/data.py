"""Diagnosis data store: reported metrics with TTL.

Parity with reference ``master/diagnosis/diagnosis_data_manager.py:22``
(``DiagnosisDataManager``: bounded per-type time series of agent reports).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional


class DiagnosisDataType:
    """Well-known ``DiagnosisReport.data_type`` values (reference
    ``diagnosis/common/constants.py DiagnosisDataType``)."""

    TRAINING_LOG = "training_log"
    STEP_METRICS = "step_metrics"  # xpu-timer analogue: step heartbeats
    OP_METRICS = "op_metrics"  # per-op timings (utils.op_metrics JSON)
    NODE_RESOURCE = "node_resource"
    FAILURE = "failure"
    # Checkpoint corruption / quarantine / replica-rejection events
    # (checkpoint.engine/_replica integrity checks, ISSUE 3).
    CKPT_INTEGRITY = "ckpt_integrity"


@dataclasses.dataclass
class DiagnosisRecord:
    node_id: int
    data_type: str
    content: str
    timestamp: float


class DiagnosisDataManager:
    def __init__(self, ttl_s: float = 600.0, max_per_type: int = 1000):
        self._ttl = ttl_s
        self._max = max_per_type
        self._lock = threading.Lock()
        self._data: Dict[str, List[DiagnosisRecord]] = {}

    def store_data(
        self,
        node_id: int,
        data_type: str,
        content: str,
        timestamp: Optional[float] = None,
    ) -> None:
        rec = DiagnosisRecord(
            node_id, data_type, content, timestamp or time.time()
        )
        with self._lock:
            series = self._data.setdefault(data_type, [])
            series.append(rec)
            self._expire_locked(series)

    def get_data(self, data_type: str) -> List[DiagnosisRecord]:
        with self._lock:
            series = self._data.get(data_type, [])
            self._expire_locked(series)
            return list(series)

    def latest_per_node(self, data_type: str) -> Dict[int, DiagnosisRecord]:
        out: Dict[int, DiagnosisRecord] = {}
        for rec in self.get_data(data_type):
            cur = out.get(rec.node_id)
            if cur is None or rec.timestamp > cur.timestamp:
                out[rec.node_id] = rec
        return out

    def _expire_locked(self, series: List[DiagnosisRecord]) -> None:
        # graftcheck: disable=OB301 -- record timestamps arrive from
        # WORKERS' wall clocks (DiagnosisReport.timestamp); wall is the
        # only shared timeline, and a step only bends a coarse TTL
        cutoff = time.time() - self._ttl
        while series and series[0].timestamp < cutoff:
            series.pop(0)
        if len(series) > self._max:
            del series[: len(series) - self._max]
