"""Inference chain: problem hypotheses resolved by pluggable operators.

Parity with reference ``master/diagnosis/inferencechain/``
(``Inference``/``InferenceOperator`` ``common/inference_chain.py``,
``InferenceChain inference_chain.py:24``, ``coordinate_solutions
coordinator.py:33``).  An :class:`Inference` is a (name, attribution,
configs) fact; operators expand unresolved facts into observed/resolved
ones; the coordinator maps conclusions to :class:`DiagnosisAction` s.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

from dlrover_tpu.common import messages as m
from dlrover_tpu.common.constants import DiagnosisActionType
from dlrover_tpu.common.log import logger


class InferenceName:
    TRAINING_HANG = "training_hang"
    NODE_FAILURE = "node_failure"
    STRAGGLER = "straggler"


@dataclasses.dataclass
class Inference:
    """One hypothesis or conclusion (reference ``Inference``)."""

    name: str
    attribution: str = ""  # "" = unresolved hypothesis
    configs: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def resolved(self) -> bool:
        return bool(self.attribution)


class InferenceOperator:
    """ABC (reference ``InferenceOperator``)."""

    def is_compatible(self, inference: Inference) -> bool:
        raise NotImplementedError

    def infer(self, inferences: List[Inference]) -> List[Inference]:
        raise NotImplementedError


class InferenceChain:
    """Runs operators over hypotheses until resolved
    (reference ``inference_chain.py:24``)."""

    def __init__(self, operators: List[InferenceOperator]):
        self._operators = operators

    def infer(self, hypotheses: List[Inference]) -> List[Inference]:
        results: List[Inference] = []
        for hyp in hypotheses:
            expanded = [hyp]
            for op in self._operators:
                if not op.is_compatible(hyp):
                    continue
                try:
                    expanded = op.infer(expanded)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "inference operator %s failed", type(op).__name__
                    )
            results.extend(i for i in expanded if i.resolved)
        return results


class Attribution:
    HANG = "hang"
    FAILED = "failed"
    STRAGGLER = "straggler"
    HEALTHY = "healthy"


def coordinate_solutions(
    conclusions: List[Inference],
) -> Dict[int, List[m.DiagnosisAction]]:
    """Conclusions -> per-node actions (reference ``coordinator.py:33``).

    Hang -> restart the hung node's workers; failure -> relaunch the node.
    """
    actions: Dict[int, List[m.DiagnosisAction]] = {}
    for c in conclusions:
        node_id = int(c.configs.get("node_id", -1))
        if c.attribution == Attribution.HANG:
            act = m.DiagnosisAction(
                action_type=DiagnosisActionType.RESTART_WORKER,
                instance=str(node_id),
                reason=c.configs.get("reason", "training hang"),
            )
        elif c.attribution == Attribution.FAILED:
            act = m.DiagnosisAction(
                action_type=DiagnosisActionType.RELAUNCH_WORKER,
                instance=str(node_id),
                reason=c.configs.get("reason", "node failure"),
            )
        else:
            continue
        actions.setdefault(node_id, []).append(act)
    return actions
