"""Diagnosis subsystem: observe -> infer -> act on training anomalies.

Parity with reference ``dlrover/python/master/diagnosis/`` (master side:
``DiagnosisManager diagnosis_manager.py:46``, inference chain + operators)
and ``dlrover/python/elastic_agent/diagnosis/`` (agent side:
``DiagnosisAgent diagnosis_agent.py:59`` deciding RESTART vs RELAUNCH,
data collectors).  TPU-adapted signals: per-step heartbeat files written by
workers replace xpu-timer CUDA kernel probes; XLA compile stalls are
whitelisted so a 30-min first compile is not "hung".
"""

from dlrover_tpu.diagnosis.data import DiagnosisDataManager
from dlrover_tpu.diagnosis.inference import (
    Inference,
    InferenceChain,
    InferenceOperator,
    coordinate_solutions,
)
from dlrover_tpu.diagnosis.manager import DiagnosisManager
from dlrover_tpu.diagnosis.agent import DiagnosisAgent, HangingDetector

__all__ = [
    "DiagnosisDataManager",
    "Inference",
    "InferenceChain",
    "InferenceOperator",
    "coordinate_solutions",
    "DiagnosisManager",
    "DiagnosisAgent",
    "HangingDetector",
]
