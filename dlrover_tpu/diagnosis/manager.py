"""Master-side diagnosis manager.

Parity with reference ``master/diagnosis/diagnosis_manager.py:46``
(``DiagnosisManager``: periodic observe -> resolve loop over reported data,
producing per-node actions delivered on heartbeat replies) +
``pre_check`` stub.  Plugs into :class:`MasterServicer` via the
``diagnosis_manager`` slot (``collect_data`` / ``report_failure`` /
``pop_actions``).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common import messages as m
from dlrover_tpu.common.constants import DiagnosisActionType
from dlrover_tpu.common.log import logger
from dlrover_tpu.diagnosis.data import (
    DiagnosisDataManager,
    DiagnosisDataType,
)
from dlrover_tpu.diagnosis.inference import (
    Inference,
    InferenceChain,
    InferenceName,
    coordinate_solutions,
)
from dlrover_tpu.diagnosis.operators import (
    CheckFailureNodeOperator,
    CheckStragglerOperator,
    CheckTrainingHangOperator,
)


class DiagnosisManager:
    def __init__(
        self,
        speed_monitor=None,
        interval_s: float = 60.0,
        hang_timeout_s: float = 1800.0,
        alive_nodes_fn=None,  # () -> node ids; expands whole-job actions
    ):
        self.alive_nodes_fn = alive_nodes_fn
        self.speed_monitor = speed_monitor
        # TTL must exceed the hang timeout or per-node stall detection can
        # never fire: a stalled node's records would expire before the
        # stall becomes diagnosable.
        self.data_manager = DiagnosisDataManager(
            ttl_s=max(600.0, 2.0 * hang_timeout_s)
        )
        self._interval = interval_s
        self._chain = InferenceChain(
            [
                CheckTrainingHangOperator(
                    self.data_manager,
                    speed_monitor,
                    hang_timeout_s=hang_timeout_s,
                ),
                CheckFailureNodeOperator(self.data_manager),
                CheckStragglerOperator(self.data_manager),
            ]
        )
        # Latest runtime-straggler conclusions (op-metrics based);
        # observational — exposed for queries/operators, no destructive
        # action is taken on a slow-but-alive node.
        self.runtime_stragglers: Dict[int, str] = {}
        self._lock = threading.Lock()
        self._pending: Dict[int, List[m.DiagnosisAction]] = {}
        # (node_id, action_type, reason) -> delivery time: an action is not
        # re-queued while its source record still sits in the data store,
        # or a relaunched replacement node would be killed again by the
        # same stale failure record on every diagnosis pass.
        self._delivered: Dict[tuple, float] = {}
        self._redeliver_cooldown_s = self.data_manager._ttl
        # Newest ckpt-integrity record already echoed to the master log.
        self._integrity_seen_ts = 0.0
        # Last (agg_mbps, skipped) ckpt-perf pair already surfaced.
        self._ckpt_perf_seen: tuple = (0.0, 0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- servicer entry points ---------------------------------------------
    def collect_data(self, msg: m.DiagnosisReport) -> None:
        self.data_manager.store_data(
            msg.node_id, msg.data_type, msg.content, msg.timestamp or None
        )

    def report_failure(self, msg: m.NodeFailure) -> None:
        self.data_manager.store_data(
            msg.node_id, DiagnosisDataType.FAILURE, msg.error_data
        )

    BROADCAST_TTL_S = 300.0

    def enqueue_broadcast(
        self, action_type: str, reason: str, node_ids
    ) -> int:
        """Queue an action for each of ``node_ids``' next heartbeats (the
        master-initiated path — e.g. a peer died, survivors must rebuild
        the collective world now rather than wait out its timeout).

        Fan-out happens HERE, scoped to the nodes alive at enqueue time:
        a node that joins later never inherits the stale instruction, a
        repeat failure re-queues cleanly once the prior entries were
        delivered, and every reply carries its own action object (no
        master-internal bookkeeping leaks into RPC payloads)."""
        now = time.time()
        queued = 0
        with self._lock:
            for nid in node_ids:
                # Expired-but-undelivered entries must not mask a FRESH
                # incident with the same reason: purge them first.
                existing = [
                    e for e in self._pending.get(nid, [])
                    # graftcheck: disable=OB301 -- "created" rides the
                    # DiagnosisAction payload (wire contract: wall);
                    # a step only bends a coarse TTL
                    if now - e.payload.get("created", now)
                    < self.BROADCAST_TTL_S
                ]
                self._pending[nid] = existing
                if any(
                    e.action_type == action_type and e.reason == reason
                    for e in existing
                ):
                    continue  # this node already has the instruction
                existing.append(
                    m.DiagnosisAction(
                        action_type=action_type, reason=reason,
                        payload={"created": now},
                    )
                )
                queued += 1
        if queued:
            logger.info(
                "diagnosis: broadcast %s to %d node(s) (%s)",
                action_type, queued, reason,
            )
        return queued

    # graftcheck: disable=PC404 -- deliberately unjournaled: heartbeat
    # action delivery is at-most-once BY DESIGN (Heartbeat is never
    # DEADLINE-retried for the same reason); pending actions lost in a
    # failover are re-derived by the next diagnose_once pass
    def pop_actions(self, node_id: int) -> List[m.DiagnosisAction]:
        """Actions for ``node_id``, consumed on delivery (reference
        heartbeat-reply piggyback).  Entries older than
        ``BROADCAST_TTL_S`` are dropped — a node that was unreachable for
        minutes must not be restarted by a long-resolved incident."""
        now = time.time()
        with self._lock:
            out = [
                a for a in self._pending.pop(node_id, [])
                # graftcheck: disable=OB301 -- "created" is wall by the
                # payload's wire contract (see enqueue_broadcast)
                if now - a.payload.get("created", now)
                < self.BROADCAST_TTL_S
            ]
        return out

    # -- pre-check (reference pre_check stub) ------------------------------
    def pre_check(self) -> bool:
        return True

    # -- observe loop ------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="diagnosis", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.diagnose_once()
            except Exception:  # noqa: BLE001
                logger.exception("diagnosis pass failed")

    def _surface_integrity_reports(self) -> None:
        """Echo new checkpoint-integrity events (corruption detected, step
        quarantined, replica rejected) into the master log.  They are rare
        and serious — silent bit-rot must be an operator signal — but
        observational: the agent-side restore ladder already routed around
        the damage, so no destructive action is queued here."""
        recs = self.data_manager.get_data(DiagnosisDataType.CKPT_INTEGRITY)
        fresh = [r for r in recs if r.timestamp > self._integrity_seen_ts]
        if not fresh:
            return
        self._integrity_seen_ts = max(r.timestamp for r in fresh)
        for rec in fresh:
            logger.warning(
                "ckpt integrity (node %d): %s", rec.node_id, rec.content
            )

    def _surface_ckpt_perf(self) -> None:
        """Echo the scale-out checkpoint gauges into the master log when
        they move (once per diagnosis pass at most): aggregate sliced-
        persist bandwidth and the dirty-fence skip count are the two
        numbers an operator needs to see that save cost is scaling with
        the fleet and shrinking with the dirty set."""
        sm = self.speed_monitor
        if sm is None:
            return
        try:
            cur = (
                round(float(sm.ckpt_agg_persist_mbps), 1),
                int(sm.ckpt_tensors_skipped),
            )
        except AttributeError:  # a bare stub monitor in tests
            return
        if cur == self._ckpt_perf_seen or cur[0] <= 0.0:
            return
        self._ckpt_perf_seen = cur
        logger.info(
            "ckpt perf: aggregate persist %.0f MB/s, %d tensors skipped "
            "by dirty fences (goodput %.3f)",
            cur[0], cur[1], sm.goodput(),
        )

    def diagnose_once(self) -> Dict[int, List[m.DiagnosisAction]]:
        self._surface_integrity_reports()
        self._surface_ckpt_perf()
        hypotheses = [
            Inference(InferenceName.TRAINING_HANG),
            Inference(InferenceName.NODE_FAILURE),
            Inference(InferenceName.STRAGGLER),
        ]
        conclusions = self._chain.infer(hypotheses)
        # Straggler conclusions are observational: record + log, never
        # restart a slow-but-progressing node.
        stragglers = {
            int(c.configs.get("node_id", -1)): c.configs.get("reason", "")
            for c in conclusions
            if c.name == InferenceName.STRAGGLER and c.resolved
        }
        # Dedup on the node SET: reasons embed fluctuating p50 numbers,
        # so comparing whole dicts would log every pass.
        if stragglers and (
            stragglers.keys() != self.runtime_stragglers.keys()
        ):
            logger.warning("runtime stragglers: %s", stragglers)
        self.runtime_stragglers = stragglers
        actions = coordinate_solutions(conclusions)
        if actions:
            logger.info(
                "diagnosis: %s",
                {
                    nid: [a.action_type for a in acts]
                    for nid, acts in actions.items()
                },
            )
        now = time.time()
        with self._lock:
            for key, ts in list(self._delivered.items()):
                # graftcheck: disable=OB301 -- shares the wall clock of
                # the payload "created" stamps set below (one clock
                # family per record; a step bends a coarse cooldown)
                if now - ts > self._redeliver_cooldown_s:
                    del self._delivered[key]
            whole_job: List[tuple] = []
            for nid, acts in actions.items():
                for act in acts:
                    # Cooldown keys on the DIAGNOSED scope (a whole-job
                    # incident is one incident, however many nodes it
                    # fans out to below).
                    key = (nid, act.action_type, act.reason)
                    if key in self._delivered:
                        continue  # already acted on this record
                    act.payload.setdefault("created", now)
                    if nid == -1:
                        # Whole-job diagnosis (e.g. global hang): fan out
                        # to every currently-alive node outside the lock.
                        # The cooldown is recorded only once the fan-out
                        # actually queues somewhere — an empty alive set
                        # (everyone just died) must not suppress the
                        # incident for the whole cooldown window.
                        whole_job.append((key, act.action_type, act.reason))
                        continue
                    existing = self._pending.setdefault(nid, [])
                    if not any(
                        e.action_type == act.action_type
                        and e.reason == act.reason
                        for e in existing
                    ):
                        existing.append(act)
                        self._delivered[key] = now
        for key, action_type, reason in whole_job:
            targets = self.alive_nodes_fn() if self.alive_nodes_fn else []
            if targets:
                # queued == 0 here only when every target already holds
                # the identical pending instruction — delivered either
                # way, so start the incident cooldown.
                self.enqueue_broadcast(action_type, reason, targets)
                with self._lock:
                    self._delivered[key] = now
            else:
                logger.warning(
                    "whole-job action %s (%s) has no alive nodes yet; "
                    "will retry next diagnosis pass", action_type, reason,
                )
        return actions
