"""Agent/worker-side diagnosis: failure classification and hang watching.

Parity with reference ``elastic_agent/diagnosis/diagnosis_agent.py:59``
(``DiagnosisAgent.diagnose_training_failure`` -> RESTART vs RELAUNCH),
``datacollector/training_log_collector.py`` (log tail scan) and ATorch's
``fault_tolerance/hanging_detector.py:86`` (``HangingDetector``).  TPU
signals: worker step heartbeats (file or callback) replace xpu-timer's CUDA
kernel-launch gap metrics.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional, Tuple

from dlrover_tpu.common.constants import DiagnosisActionType
from dlrover_tpu.common.log import logger


# Error patterns in worker logs that user-code restarts cannot fix: the
# node must be replaced (reference diagnosis_agent's relaunch decision).
NODE_ERROR_PATTERNS = (
    "hardware error",
    "tpu initialization failed",
    "device unavailable",
    "ici link",
    "failed to allocate",
    "resource_exhausted: out of memory",
)

# Patterns that are transient: in-place restart is enough.
TRANSIENT_PATTERNS = (
    "coordination service",
    "deadline_exceeded",
    "barrier timed out",
    "connection reset",
    "unavailable:",
)


class TrainingLogCollector:
    """Tails worker log files for error evidence (reference
    ``training_log_collector.py``)."""

    def __init__(
        self,
        log_dir: str = "",
        tail_bytes: int = 65536,
        max_age_s: float = 600.0,
    ):
        self._log_dir = log_dir
        self._tail = tail_bytes
        # Only logs written recently are evidence for the CURRENT failure;
        # a node-error pattern in an old round's log must not force
        # RELAUNCH for every later unrelated crash.
        self._max_age = max_age_s

    def collect(self) -> str:
        if not self._log_dir or not os.path.isdir(self._log_dir):
            return ""
        chunks: List[str] = []
        now = time.time()
        try:
            for name in sorted(os.listdir(self._log_dir)):
                path = os.path.join(self._log_dir, name)
                if not os.path.isfile(path):
                    continue
                # graftcheck: disable=OB301 -- vs the log file's wall
                # mtime; wall time is the point
                if now - os.stat(path).st_mtime > self._max_age:
                    continue
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - self._tail))
                    chunks.append(
                        f.read().decode("utf-8", errors="replace")
                    )
        except OSError:
            return ""
        return "\n".join(chunks)


class DiagnosisAgent:
    """Per-node failure diagnosis (reference ``diagnosis_agent.py:59``)."""

    def __init__(
        self,
        master_client=None,
        log_dir: str = "",
        max_in_place_restarts: int = 3,
    ):
        self.client = master_client
        self._log_collector = TrainingLogCollector(log_dir)
        self._max_restarts = max_in_place_restarts

    def diagnose_training_failure(
        self, failures: List[Tuple[int, int]], restart_count: int
    ) -> str:
        """Decide the recovery action after worker failures.

        ``failures``: [(local_rank, exit_code)].  Returns a
        ``DiagnosisActionType``: RESTART_WORKER keeps this node and respawns
        processes; RELAUNCH_WORKER asks the master to replace the node.
        """
        logs = self._log_collector.collect().lower()
        node_sick = any(p in logs for p in NODE_ERROR_PATTERNS)
        # SIGKILLs (-9) from the OOM killer also mean the node is sick.
        oom_kill = any(code == -9 for _, code in failures) and (
            "out of memory" in logs or "oom" in logs
        )
        if node_sick or oom_kill:
            reason = "node-level error in worker logs"
            action = DiagnosisActionType.RELAUNCH_WORKER
        elif restart_count > self._max_restarts:
            reason = f"in-place restart budget ({self._max_restarts}) spent"
            action = DiagnosisActionType.RELAUNCH_WORKER
        else:
            reason = "transient/user error; restarting in place"
            action = DiagnosisActionType.RESTART_WORKER
        logger.info(
            "failure diagnosis: %s (%s; failures=%s restarts=%d)",
            action, reason, failures, restart_count,
        )
        if self.client is not None:
            try:
                self.client.report_diagnosis_data(
                    "failure",
                    json.dumps(
                        {
                            "failures": failures,
                            "restart_count": restart_count,
                            "action": action,
                            "reason": reason,
                        }
                    ),
                )
            except Exception as e:  # noqa: BLE001
                # The restart decision stands either way; only the
                # master-side diagnosis record is lost.
                logger.debug("failure-diagnosis report failed: %s", e)
        return action


class HangingDetector:
    """Watches step progression; fires a callback when stalled
    (ATorch ``hanging_detector.py:86``, TPU-adapted: step timestamps come
    from ``record_step`` calls or a heartbeat file workers touch).

    ``compile_grace_s`` suppresses alarms before the first recorded step
    (XLA compilation can legitimately take tens of minutes).
    """

    def __init__(
        self,
        hang_timeout_s: float = 1800.0,
        compile_grace_s: float = 3600.0,
        on_hang=None,
        heartbeat_file: str = "",
        check_interval_s: float = 30.0,
    ):
        self._timeout = hang_timeout_s
        self._grace = compile_grace_s
        self._on_hang = on_hang
        self._hb_file = heartbeat_file
        self._interval = check_interval_s
        self._last_step = -1
        self._last_progress = time.time()
        self._started = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- feed --------------------------------------------------------------
    def record_step(self, step: int) -> None:
        with self._lock:
            if step != self._last_step:
                self._last_step = step
                self._last_progress = time.time()

    def _file_mtime(self) -> Optional[float]:
        if not self._hb_file:
            return None
        try:
            return os.stat(self._hb_file).st_mtime
        except OSError:
            return None

    # -- query -------------------------------------------------------------
    def is_hanging(self) -> bool:
        now = time.time()
        with self._lock:
            last_step = self._last_step
            last_progress = self._last_progress
        mtime = self._file_mtime()
        if mtime is not None:
            last_progress = max(last_progress, mtime)
        if last_step < 0:
            # No step ever recorded: the first XLA compile can take tens
            # of minutes — apply the grace window even if a heartbeat
            # file was created (but not yet touched) at startup.
            # last_progress folds in the heartbeat FILE's wall mtime,
            # so the compare clock must be wall too; a step only bends
            # a coarse grace window.
            return (
                now - self._started > self._grace  # graftcheck: disable=OB301 -- wall-mtime family (see above)
                and now - last_progress > self._timeout  # graftcheck: disable=OB301 -- wall-mtime family
            )
        return now - last_progress > self._timeout  # graftcheck: disable=OB301 -- wall-mtime family

    # -- background watcher ------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="hang-detector", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            if self.is_hanging():
                logger.warning(
                    "hang detected: no step progress for >%.0fs",
                    self._timeout,
                )
                if self._on_hang is not None:
                    try:
                        self._on_hang()
                    except Exception:  # noqa: BLE001
                        logger.exception("on_hang callback failed")
                # One alarm per stall: reset the clock so the callback is
                # not hammered every interval.
                with self._lock:
                    self._last_progress = time.time()
