"""Agent/worker -> master client: every control-plane call in one place.

Parity with reference ``elastic_agent/master_client.py:60`` (~50 wrappers +
singleton ``build_master_client :480``).  Each method is a typed wrapper over
``RpcClient.call``; the transport retry lives in the RPC layer.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common import messages as m
from dlrover_tpu.common.env import (
    get_master_addr,
    get_master_standby_addr,
    get_master_state_dir,
    get_node_id,
)
from dlrover_tpu.common.rpc import RpcClient, addr_connectable


class MasterClient:
    def __init__(self, master_addr: str, node_id: int = 0,
                 state_dir: str = "", standby_addr: str = ""):
        # Failover re-resolve (ISSUE 13): on every channel rebuild the
        # RPC layer asks _resolve_addr for the freshest master address.
        # Source order: (1) the ``addr`` file the CURRENT leader
        # publishes in the HA state dir (env DLROVER_TPU_MASTER_STATE_DIR
        # — works across repeated failovers), (2) the static standby
        # address (env DLROVER_TPU_MASTER_STANDBY_ADDR) once the primary
        # stops answering a quick TCP probe, (3) the address we have.
        self._state_dir = state_dir or get_master_state_dir()
        self._standby_addr = standby_addr or get_master_standby_addr()
        self._client = RpcClient(
            master_addr, addr_provider=self._resolve_addr
        )
        self.node_id = node_id
        self.master_addr = master_addr

    def _resolve_addr(self) -> str:
        if self._state_dir:
            from dlrover_tpu.master.state import read_addr

            published = read_addr(self._state_dir)
            if published:
                self.master_addr = published
                return published
        if self._standby_addr and self._standby_addr != self.master_addr:
            # Cheap probes only on the (rate-limited) reconnect path.
            if not addr_connectable(self.master_addr, timeout=0.5) and \
                    addr_connectable(self._standby_addr, timeout=0.5):
                self.master_addr = self._standby_addr
                return self._standby_addr
        return self.master_addr

    # -- registration / lifecycle -----------------------------------------
    def register_node(
        self,
        *,
        node_type: str = "worker",
        node_rank: int = -1,
        host: str = "",
        agent_port: int = 0,
        slice_id: str = "",
        host_id: str = "",
        tpu_chips: int = 0,
        local_world_size: int = 1,
    ) -> None:
        self._client.call(
            m.NodeMeta(
                node_type=node_type,
                node_id=self.node_id,
                node_rank=node_rank,
                host=host,
                agent_port=agent_port,
                slice_id=slice_id,
                host_id=host_id,
                tpu_chips=tpu_chips,
                local_world_size=local_world_size,
            )
        )

    def report_node_status(
        self, status: str, node_type: str = "worker", exit_reason: str = "",
        restart_count: int = 0,
    ) -> None:
        self._client.call(
            m.ReportNodeStatus(
                node_id=self.node_id,
                node_type=node_type,
                status=status,
                exit_reason=exit_reason,
                restart_count=restart_count,
            )
        )

    def report_failure(
        self, error_data: str, level: str = "error", restart_count: int = 0,
        node_rank: int = -1,
    ) -> None:
        self._client.call(
            m.NodeFailure(
                node_id=self.node_id,
                node_rank=node_rank,
                error_data=error_data,
                level=level,
                restart_count=restart_count,
            )
        )

    def report_heartbeat(self) -> List[m.DiagnosisAction]:
        # NOT idempotent: the master's heartbeat handler destructively
        # pops pending DiagnosisActions, so a DEADLINE retry could eat an
        # action whose first reply was lost.  UNAVAILABLE-only retry; the
        # next interval's heartbeat covers the gap.
        resp = self._client.call(
            m.Heartbeat(node_id=self.node_id, timestamp=time.time())
        )
        if isinstance(resp, m.HeartbeatResponse):
            return resp.actions
        return []

    def report_job_exit(self, success: bool, reason: str = "") -> None:
        self._client.call(
            m.JobExitRequest(node_id=self.node_id, success=success, reason=reason)
        )

    # -- rendezvous --------------------------------------------------------
    def join_rendezvous(
        self,
        node_rank: int,
        local_world_size: int,
        rdzv_name: str = "elastic-training",
        slice_id: str = "",
        attempt_id: str = "",
    ) -> int:
        # The attempt_id makes the join idempotent master-side (a retried
        # duplicate is a no-op), so DEADLINE_EXCEEDED is safe to retry.
        resp = self._client.call(
            m.JoinRendezvous(
                node_id=self.node_id,
                node_rank=node_rank,
                local_world_size=local_world_size,
                rdzv_name=rdzv_name,
                slice_id=slice_id,
                attempt_id=attempt_id or uuid.uuid4().hex,
            ),
            idempotent=True,
        )
        return resp.round if isinstance(resp, m.RendezvousRound) else -1

    def get_comm_world(
        self, rdzv_name: str = "elastic-training"
    ) -> Tuple[int, int, Dict[int, dict], str]:
        # graftcheck: disable=PC403 -- the handler's only mutation is
        # the rendezvous world latch, which fires at most once per
        # round behind its own quiescence guard: a retried fetch
        # evaluates it exactly like any other agent's poll and then
        # reads the latched world — idempotent by design
        resp = self._client.call(
            m.CommWorldRequest(node_id=self.node_id, rdzv_name=rdzv_name),
            idempotent=True,
        )
        if isinstance(resp, m.CommWorld):
            return resp.round, resp.group, resp.world, resp.coordinator
        return -1, 0, {}, ""

    def num_nodes_waiting(self, rdzv_name: str = "elastic-training") -> int:
        resp = self._client.call(
            m.WaitingNodeNumRequest(rdzv_name=rdzv_name), idempotent=True
        )
        return resp.waiting_num if isinstance(resp, m.WaitingNodeNum) else 0

    # -- kv store ----------------------------------------------------------
    def kv_store_set(self, key: str, value: bytes) -> None:
        # Last-writer-wins set: re-sending the same value is harmless.
        self._client.call(m.KVStoreSet(key=key, value=value), idempotent=True)

    def kv_store_get(self, key: str) -> Optional[bytes]:
        resp = self._client.call(m.KVStoreGet(key=key), idempotent=True)
        if isinstance(resp, m.KVStoreValue) and resp.found:
            return resp.value
        return None

    def kv_store_wait_get(
        self, key: str, timeout: float = 60.0, poll: float = 0.2
    ) -> Optional[bytes]:
        deadline = time.monotonic() + timeout
        while True:
            val = self.kv_store_get(key)
            if val is not None:
                return val
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            time.sleep(min(poll, remaining))

    def kv_store_multi_set(self, kvs: Dict[str, bytes]) -> None:
        self._client.call(m.KVStoreMultiSet(kvs=kvs), idempotent=True)

    def kv_store_multi_get(self, keys: List[str]) -> Dict[str, bytes]:
        resp = self._client.call(
            m.KVStoreMultiGet(keys=keys), idempotent=True
        )
        return resp.kvs if isinstance(resp, m.KVStoreMultiValue) else {}

    def kv_store_scan(self, prefix: str) -> Dict[str, bytes]:
        resp = self._client.call(
            m.KVStoreScan(prefix=prefix), idempotent=True
        )
        return resp.kvs if isinstance(resp, m.KVStoreScanResult) else {}

    def kv_store_delete(self, key: str) -> bool:
        # Tokened like add: the reply ("did THIS call remove it") is
        # what a DEADLINE retry would otherwise corrupt.
        resp = self._client.call(
            m.KVStoreDelete(key=key, token=uuid.uuid4().hex),
            idempotent=True,
        )
        return bool(getattr(resp, "success", False))

    def kv_store_add(self, key: str, delta: int = 1) -> int:
        # The token lets the master dedupe a retried add (exactly-once
        # counter semantics even when the first reply was lost).
        resp = self._client.call(
            m.KVStoreAdd(key=key, delta=delta, token=uuid.uuid4().hex),
            idempotent=True,
        )
        return resp.value if isinstance(resp, m.KVStoreCount) else 0

    # -- data sharding -----------------------------------------------------
    def report_dataset_shard_params(
        self,
        *,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
        batch_size: int = 0,
    ) -> None:
        self._client.call(
            m.DatasetShardParams(
                dataset_name=dataset_name,
                dataset_size=dataset_size,
                shard_size=shard_size,
                num_epochs=num_epochs,
                shuffle=shuffle,
                storage_type=storage_type,
                batch_size=batch_size,
            )
        )

    def get_task(self, dataset_name: str) -> m.Task:
        # Tokened fetch: a retried request returns the SAME task instead of
        # popping a second shard (exactly-once dispatch under retry).
        resp = self._client.call(
            m.TaskRequest(
                dataset_name=dataset_name,
                worker_id=self.node_id,
                token=uuid.uuid4().hex,
            ),
            idempotent=True,
        )
        return resp if isinstance(resp, m.Task) else m.Task(task_id=-1)

    def report_task_result(
        self, dataset_name: str, task_id: int, success: bool = True,
        err_message: str = "",
    ) -> None:
        self._client.call(
            m.TaskResult(
                dataset_name=dataset_name,
                task_id=task_id,
                worker_id=self.node_id,
                success=success,
                err_message=err_message,
            )
        )

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp = self._client.call(
            m.ShardCheckpointRequest(dataset_name=dataset_name),
            idempotent=True,
        )
        return resp.content if isinstance(resp, m.ShardCheckpoint) else ""

    def restore_shard_checkpoint(self, dataset_name: str, content: str) -> bool:
        resp = self._client.call(
            m.ShardCheckpoint(dataset_name=dataset_name, content=content)
        )
        return isinstance(resp, m.BaseResponse) and resp.success

    # -- health check ------------------------------------------------------
    def report_network_check(
        self, succeeded: bool, elapsed: float, round_: int = -1
    ) -> None:
        self._client.call(
            m.NetworkCheckResult(
                node_id=self.node_id,
                succeeded=succeeded,
                elapsed=elapsed,
                round=round_,
            )
        )

    def network_ready(self) -> bool:
        resp = self._client.call(m.NetworkReadyRequest(), idempotent=True)
        return isinstance(resp, m.BaseResponse) and resp.success

    def get_fault_nodes(self) -> Tuple[List[int], str]:
        resp = self._client.call(m.FaultNodeRequest(), idempotent=True)
        if isinstance(resp, m.FaultNodes):
            return resp.nodes, resp.reason
        return [], ""

    def get_stragglers(self) -> Tuple[List[int], dict]:
        """(straggler node ids, elapsed-by-node)."""
        nodes, times, _ = self.get_stragglers_full()
        return nodes, times

    def get_stragglers_full(self) -> Tuple[List[int], dict, bool]:
        """(straggler node ids, elapsed-by-node, results-complete flag)."""
        resp = self._client.call(m.StragglerRequest(), idempotent=True)
        if isinstance(resp, m.Stragglers):
            return resp.nodes, resp.times, resp.complete
        return [], {}, False

    # -- metrics -----------------------------------------------------------
    def report_global_step(self, step: int, timestamp: float = 0.0) -> None:
        self._client.call(
            m.GlobalStep(
                node_id=self.node_id, step=step,
                timestamp=timestamp or time.time(),
            )
        )

    def report_ckpt_perf(
        self, step: int, stall_ms: float,
        staged_mbps: float = 0.0, persist_mbps: float = 0.0,
        agg_persist_mbps: float = 0.0, tensors_skipped: int = -1,
    ) -> None:
        """Feed the master's goodput accounting with the measured
        save_to_memory stall (flash-ckpt fast path observability).

        Single attempt, 1s budget, no retries: this call sits inside the
        trainer's save path, whose whole point is a tens-of-ms stall — a
        master outage must cost at most one short timeout, not the
        default retry ladder.  Losing a sample is fine (it's a gauge)."""
        self._client.call(
            m.CkptPerf(
                node_id=self.node_id, step=step, stall_ms=stall_ms,
                staged_mbps=staged_mbps, persist_mbps=persist_mbps,
                agg_persist_mbps=agg_persist_mbps,
                tensors_skipped=int(tensors_skipped),
            ),
            timeout=1.0, retries=1, deadline=1.0,
        )

    def get_reshard_epoch(self) -> m.ReshardEpochInfo:
        """Poll the master's resize-epoch broadcast (live resharding).
        Short budget, no retries: this rides the step loop — a sick
        master must cost one bounded timeout, not a retry ladder; the
        next step polls again anyway."""
        resp = self._client.call(
            m.ReshardEpochRequest(node_id=self.node_id),
            timeout=2.0, retries=1, deadline=2.0,
        )
        if isinstance(resp, m.ReshardEpochInfo):
            return resp
        return m.ReshardEpochInfo()

    def announce_reshard(
        self,
        target_num_processes: int,
        target_spec: Optional[dict] = None,
        expected_reports: int = 0,
        deadline_s: float = 0.0,
    ) -> m.ReshardEpochInfo:
        """Operator/admin resize request (ISSUE 13): open a live resize
        epoch from outside the master process."""
        resp = self._client.call(
            m.ReshardAnnounce(
                node_id=self.node_id,
                target_num_processes=target_num_processes,
                target_spec=dict(target_spec or {}),
                expected_reports=expected_reports,
                deadline_s=deadline_s,
            )
        )
        if isinstance(resp, m.ReshardEpochInfo):
            return resp
        return m.ReshardEpochInfo()

    def journal_fetch(self, offset: int, max_bytes: int = 1 << 20) \
            -> m.JournalChunk:
        """Raw control-state journal bytes (standby streaming
        replication; ``offset=-1`` = the snapshot file)."""
        resp = self._client.call(
            m.JournalFetch(offset=offset, max_bytes=max_bytes),
            idempotent=True,
        )
        if isinstance(resp, m.JournalChunk):
            return resp
        return m.JournalChunk(found=False)

    def report_reshard(
        self,
        epoch: int,
        ok: bool,
        reason: str = "",
        downtime_ms: float = 0.0,
        moved_mb: float = 0.0,
    ) -> bool:
        """Report this node's verdict on a resize epoch.  ``idempotent``:
        the master keys reports by node — a retried duplicate is a
        harmless overwrite."""
        resp = self._client.call(
            m.ReshardReport(
                node_id=self.node_id, epoch=epoch, ok=ok, reason=reason,
                downtime_ms=downtime_ms, moved_mb=moved_mb,
            ),
            idempotent=True,
        )
        return bool(getattr(resp, "success", False))

    def report_used_resource(
        self, cpu_percent: float, memory_mb: float,
        tpu_duty_cycle: float = 0.0, hbm_used_mb: float = 0.0,
    ) -> None:
        self._client.call(
            m.UsedResource(
                node_id=self.node_id,
                cpu_percent=cpu_percent,
                memory_mb=memory_mb,
                tpu_duty_cycle=tpu_duty_cycle,
                hbm_used_mb=hbm_used_mb,
            )
        )

    def report_model_info(
        self, num_params: int, flops_per_step: float = 0.0,
        batch_size_per_step: int = 0, **extra,
    ) -> None:
        self._client.call(
            m.ModelInfo(
                num_params=num_params,
                flops_per_step=flops_per_step,
                batch_size_per_step=batch_size_per_step,
                extra=extra,
            )
        )

    def report_diagnosis_data(self, data_type: str, content: str) -> None:
        self._client.call(
            m.DiagnosisReport(
                node_id=self.node_id,
                data_type=data_type,
                content=content,
                timestamp=time.time(),
            )
        )

    # -- sync / ckpt -------------------------------------------------------
    def join_sync(self, sync_name: str, node_rank: int = -1) -> None:
        self._client.call(
            m.SyncJoin(
                sync_name=sync_name, node_id=self.node_id, node_rank=node_rank
            )
        )

    def sync_finished(self, sync_name: str) -> bool:
        resp = self._client.call(
            m.SyncQuery(sync_name=sync_name), idempotent=True
        )
        return isinstance(resp, m.BaseResponse) and resp.success

    def barrier(self, sync_name: str, timeout: float = 120.0) -> bool:
        """Join + poll a named barrier until it opens."""
        self.join_sync(sync_name)
        deadline = time.monotonic() + timeout
        while True:
            if self.sync_finished(sync_name):
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            time.sleep(min(0.2, remaining))

    def sync_checkpoint(self, step: int) -> bool:
        resp = self._client.call(
            m.CheckpointSync(node_id=self.node_id, step=step)
        )
        return isinstance(resp, m.BaseResponse) and resp.success

    # -- config ------------------------------------------------------------
    def get_elastic_run_config(self) -> dict:
        resp = self._client.call(
            m.ElasticRunConfigRequest(), idempotent=True
        )
        return resp.configs if isinstance(resp, m.ElasticRunConfig) else {}

    def get_parallel_config(self) -> m.ParallelConfig:
        resp = self._client.call(
            m.ParallelConfigRequest(node_id=self.node_id), idempotent=True
        )
        return resp if isinstance(resp, m.ParallelConfig) else m.ParallelConfig()

    def reconnect(self) -> None:
        """Rebuild the underlying channel after a persistent outage (see
        ``RpcClient.reconnect``)."""
        self._client.reconnect(force=True)

    def close(self) -> None:
        self._client.close()


_client_lock = threading.Lock()
_client: Optional[MasterClient] = None
#: The env-resolved address the cached singleton was built from.  An
#: env-default build latched the address forever (ISSUE 13 satellite): a
#: post-failover DLROVER_TPU_MASTER_ADDR change was silently ignored for
#: the life of the process.  Tracking the source lets build re-resolve.
_client_env_addr: str = ""


def build_master_client(
    master_addr: str = "", node_id: Optional[int] = None
) -> MasterClient:
    """Process-wide singleton (reference ``build_master_client :480``);
    defaults from the agent-provided env contract.

    An env-defaulted singleton is INVALIDATED (closed + rebuilt) when
    the env-resolved address has changed since it was built — a
    supervisor that re-points DLROVER_TPU_MASTER_ADDR after a failover
    must be picked up, not latched over.  An explicit ``master_addr``
    returns the cached client as before when it matches; use
    :func:`reset_master_client` to force a rebuild.
    """
    global _client, _client_env_addr
    with _client_lock:
        if _client is not None and not master_addr and _client_env_addr:
            # Only an ENV-BUILT singleton re-resolves: a client built
            # with an explicit address (_client_env_addr == "") stays
            # authoritative — tearing it down under concurrent RPC
            # threads because the env happens to be set would fail
            # their in-flight calls for no reason.
            env_addr = get_master_addr()
            if env_addr and env_addr != _client_env_addr:
                _client.close()
                _client = None
        if _client is None:
            addr = master_addr or get_master_addr()
            nid = node_id if node_id is not None else get_node_id()
            if not addr:
                raise RuntimeError(
                    "no master address: set DLROVER_TPU_MASTER_ADDR or pass "
                    "master_addr"
                )
            _client = MasterClient(addr, nid)
            _client_env_addr = "" if master_addr else addr
        return _client


def invalidate_master_client() -> None:
    """Explicit re-resolve hook (ISSUE 13 satellite): drop the cached
    singleton so the NEXT :func:`build_master_client` re-reads the env
    contract.  Unlike :func:`reset_master_client` this is safe to call
    speculatively from failover paths — it never raises (a failing
    channel teardown is logged, the cache is dropped regardless)."""
    global _client, _client_env_addr
    with _client_lock:
        if _client is not None:
            try:
                _client.close()
            except Exception as e:  # noqa: BLE001 - speculative path
                from dlrover_tpu.common.log import logger

                logger.debug("stale master client close failed: %s", e)
        _client = None
        _client_env_addr = ""


def reset_master_client() -> None:
    global _client, _client_env_addr
    with _client_lock:
        if _client is not None:
            _client.close()
        _client = None
        _client_env_addr = ""
