"""Elastic training agent: the per-node supervisor process.

Parity with reference ``elastic_agent/torch/training.py``
(``ElasticLaunchConfig :143``, ``MasterRendezvousHandler :217``,
``ElasticTrainingAgent :405``, ``launch_agent :1098``) re-designed for the
JAX runtime: instead of torchelastic's c10d store bootstrap, a completed
master rendezvous elects a **JAX coordinator** (rank-0 node, fresh port per
round) and assigns contiguous ``process_id`` s; workers then run
``jax.distributed.initialize``.  A membership change or worker failure tears
the round down and re-forms the world (JAX requires runtime re-init +
recompile — the flash-checkpoint shm restore hides the state reload,
SURVEY.md §7 "hard parts").

Agent responsibilities each round (reference ``_invoke_run :863``):
  rendezvous -> spawn workers -> monitor (exit codes, heartbeats,
  membership) -> on failure: breakpoint-save + diagnose -> restart/relaunch.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import (
    DiagnosisActionType,
    NodeEnv,
    NodeStatus,
    RendezvousName,
)
from dlrover_tpu.common.env import worker_env
from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.rpc import find_free_port, local_ip


@dataclasses.dataclass
class ElasticLaunchConfig:
    """Launch knobs (reference ``ElasticLaunchConfig :143`` +
    ``auto_configure_params :186``)."""

    min_nodes: int = 1
    max_nodes: int = 1
    nproc_per_node: int = 1
    node_id: int = 0
    node_rank: int = 0
    max_restarts: int = 3
    monitor_interval: float = 2.0
    rdzv_timeout: float = 600.0
    network_check: bool = False
    comm_perf_test: bool = False
    log_dir: str = ""
    job_name: str = "local-job"
    slice_id: str = ""
    #: Fleet role of this node (ISSUE 10): the master's job manager
    #: files it under the matching node group (worker / gateway /
    #: embedding) so one ElasticJob can launch heterogeneous roles.
    node_role: str = "worker"

    def auto_configure(self) -> None:
        """Fill derived params from env (chips per host etc.)."""
        env_chips = os.environ.get("TPU_ACCELERATOR_TYPE", "")
        if self.slice_id == "":
            self.slice_id = os.environ.get("TPU_WORKER_HOSTNAMES", "")


class WorkerProcess:
    def __init__(self, local_rank: int, proc: subprocess.Popen, log_file=None):
        self.local_rank = local_rank
        self.proc = proc
        self.log_file = log_file

    def poll(self) -> Optional[int]:
        return self.proc.poll()


class RunResult:
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    MEMBERSHIP_CHANGED = "membership_changed"
    STOP_JOB = "stop_job"
    RESTART_REQUESTED = "restart_requested"
    RELAUNCH_REQUESTED = "relaunch_requested"


class ElasticTrainingAgent:
    """One agent per node; supervises ``nproc_per_node`` worker processes
    running the user script (reference ``ElasticTrainingAgent :405``)."""

    def __init__(
        self,
        config: ElasticLaunchConfig,
        entrypoint: List[str],
        master_addr: str,
        client: Optional[MasterClient] = None,
    ):
        self.config = config
        self.entrypoint = entrypoint
        self.master_addr = master_addr
        self.client = client or MasterClient(master_addr, config.node_id)
        self._ctx = get_context()
        self._workers: List[WorkerProcess] = []
        self._stop_evt = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._pending_action: Optional[str] = None
        self._restart_count = 0
        self._host = local_ip()
        self._rdzv_round = -1
        # Hooks the checkpoint saver plugs into (task: flash checkpoint).
        self.on_workers_stopping = None  # callable(reason) before kill
        self.saver = None  # AsyncCheckpointSaver, attached by launcher
        self._last_failures: List[tuple] = []
        # Sticky: chaos crash sites observed once (by their exit codes)
        # stay scrubbed from every later worker generation
        # (see _start_workers).
        self._spent_crash_sites: set = set()
        from dlrover_tpu.diagnosis.agent import DiagnosisAgent

        self.diagnosis = DiagnosisAgent(
            self.client,
            log_dir=config.log_dir,
            max_in_place_restarts=config.max_restarts,
        )
        from dlrover_tpu.agent.config_tuner import ParalConfigTuner
        from dlrover_tpu.agent.monitor import ResourceMonitor

        self.resource_monitor = ResourceMonitor(self.client)
        self.config_tuner = ParalConfigTuner(self.client)

    def _report_status(self, status: str, exit_reason: str = "") -> None:
        """Status reports are at-least-once best-effort: a report that
        exhausts its RPC retries (master restarting, network flap) must
        never take down the agent that is supposed to survive it."""
        try:
            self.client.report_node_status(
                status, node_type=self.config.node_role or "worker",
                exit_reason=exit_reason,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "status report %r failed (continuing): %s", status, e
            )

    def _report_failure_safe(
        self, error_data: str, restart_count: int = 0
    ) -> None:
        """Best-effort failure report (same contract as _report_status):
        the agent is about to recover from the failure locally, and a
        flaky master must not turn that recovery into a crash."""
        try:
            self.client.report_failure(
                error_data, restart_count=restart_count
            )
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "failure report failed (continuing): %s", e
            )

    # -- heartbeats --------------------------------------------------------
    def _start_heartbeat(self) -> None:
        if self._hb_thread is not None:
            return

        def loop():
            while not self._stop_evt.wait(self._ctx.node_heartbeat_interval):
                try:
                    actions = self.client.report_heartbeat()
                    for a in actions:
                        if a.action_type != DiagnosisActionType.NONE:
                            logger.info("heartbeat action: %s (%s)",
                                        a.action_type, a.reason)
                            self._pending_action = a.action_type
                except Exception as e:  # noqa: BLE001
                    logger.warning("heartbeat failed: %s", e)

        self._hb_thread = threading.Thread(
            target=loop, name="agent-heartbeat", daemon=True
        )
        self._hb_thread.start()

    # -- rendezvous (reference MasterRendezvousHandler.next_rendezvous) ----
    def _rendezvous(self) -> dict:
        """Join + poll until this node is in a completed world.

        Returns {round, world, my_rank, coordinator, num_processes}.

        Hardened against a master restart mid-rendezvous (chaos
        ``master.restart`` / ``rdzv.lost_node``): RPC failures during the
        poll are retried until the rendezvous deadline, and while no world
        has formed the join (+ registration, which the join's world
        metadata depends on) is re-sent every ``rdzv_rejoin_interval``
        seconds with the SAME attempt id — a no-op on a healthy master,
        a state re-seed on one that lost its membership.
        """
        cfg = self.config
        coord_port = find_free_port()
        attempt_id = uuid.uuid4().hex
        deadline = time.monotonic() + cfg.rdzv_timeout
        rejoin_interval = max(1.0, self._ctx.rdzv_rejoin_interval)
        joined = False
        last_join = 0.0
        join_failures = 0

        if cfg.node_role not in ("worker", "chief"):
            # Service roles (gateway / embedding store, ISSUE 10)
            # register for supervision + heartbeats but must NOT join
            # the training rendezvous — they have no place in the XLA
            # mesh, and a join would count them into the world size.
            # Their "world" is themselves.
            while True:
                try:
                    self.client.register_node(
                        node_type=cfg.node_role,
                        node_rank=cfg.node_rank,
                        host=self._host,
                        agent_port=coord_port,
                        slice_id=cfg.slice_id,
                        local_world_size=cfg.nproc_per_node,
                    )
                    break
                except Exception as e:  # noqa: BLE001
                    if time.time() >= deadline:
                        # Same contract as the worker path's rendezvous
                        # timeout: an agent that never registered must
                        # NOT launch an unsupervised orphan (the fleet
                        # reconciler would spawn a duplicate beside it).
                        raise TimeoutError(
                            f"{cfg.node_role}-role registration did "
                            f"not succeed within {cfg.rdzv_timeout}s"
                        ) from e
                    logger.warning(
                        "%s-role registration failed (will retry): %s",
                        cfg.node_role, e,
                    )
                    time.sleep(1.0)
            return {
                "round": 0,
                "world": {0: {
                    "node_id": cfg.node_id,
                    "local_world_size": cfg.nproc_per_node,
                    "process_id_base": 0,
                }},
                "my_rank": 0,
                "coordinator": "",
                "num_processes": cfg.nproc_per_node,
            }

        def _join() -> None:
            self.client.register_node(
                node_type=cfg.node_role,
                node_rank=cfg.node_rank,
                host=self._host,
                agent_port=coord_port,
                slice_id=cfg.slice_id,
                local_world_size=cfg.nproc_per_node,
            )
            self.client.join_rendezvous(
                cfg.node_rank, cfg.nproc_per_node,
                rdzv_name=RendezvousName.TRAINING, slice_id=cfg.slice_id,
                attempt_id=attempt_id,
            )

        while time.monotonic() < deadline:
            if not joined or time.monotonic() - last_join >= rejoin_interval:
                try:
                    _join()
                    if joined:
                        logger.info(
                            "rendezvous: re-sent join (no world after "
                            "%.0fs; master may have restarted)",
                            time.monotonic() - last_join,
                        )
                    joined = True
                    last_join = time.monotonic()
                    join_failures = 0
                except Exception as e:  # noqa: BLE001
                    join_failures += 1
                    logger.warning(
                        "rendezvous join failed (will retry): %s", e
                    )
                    if join_failures % 3 == 0:
                        # A channel that rode out a master restart can
                        # stay wedged in TRANSIENT_FAILURE; start fresh.
                        self.client.reconnect()
                    time.sleep(min(1.0, max(0.0, deadline - time.monotonic())))
                    continue
            try:
                round_, _, world, coordinator = self.client.get_comm_world(
                    RendezvousName.TRAINING
                )
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "rendezvous poll failed (will retry): %s", e
                )
                time.sleep(min(1.0, max(0.0, deadline - time.monotonic())))
                continue
            if world:
                my_rank = None
                for rank, meta in world.items():
                    if meta["node_id"] == cfg.node_id:
                        my_rank = int(rank)
                        break
                if my_rank is None:
                    # Completed without us (node-unit cut) - keep waiting for
                    # the next round.
                    time.sleep(1.0)
                    continue
                num_processes = sum(
                    w["local_world_size"] for w in world.values()
                )
                self._rdzv_round = round_
                logger.info(
                    "rendezvous round %d: world=%d nodes, my_rank=%d, "
                    "coordinator=%s", round_, len(world), my_rank, coordinator,
                )
                return {
                    "round": round_,
                    "world": world,
                    "my_rank": my_rank,
                    "coordinator": coordinator,
                    "num_processes": num_processes,
                }
            time.sleep(0.5)
        raise TimeoutError(
            f"rendezvous did not complete within {cfg.rdzv_timeout}s"
        )

    # -- worker lifecycle ---------------------------------------------------
    def _start_workers(self, world_info: dict) -> None:
        cfg = self.config
        world = world_info["world"]
        my = world[world_info["my_rank"]]
        base = my["process_id_base"]
        self._workers = []
        if self.saver is not None:
            # Refresh replica ring + seed arenas from peers (a replaced
            # node recovers the last staged step without storage).
            try:
                self.saver.update_world(world_info["my_rank"], len(world))
                self.saver.seed_from_replicas(
                    {lr: base + lr for lr in range(cfg.nproc_per_node)},
                    world_info["num_processes"],
                )
            except Exception:  # noqa: BLE001
                logger.exception("replica seeding failed")
        # Workers run `python script.py`, whose sys.path[0] is the script's
        # dir; make the launcher's cwd and this framework importable
        # (torchrun's PYTHONPATH contract).
        import dlrover_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
            dlrover_tpu.__file__)))
        extra_path = [os.getcwd(), pkg_root]
        # A one-shot chaos crash fault that already fired in a worker
        # (worker.kill, or ckpt.crash_* in standalone-engine mode) must
        # not re-arm in the replacements — fault-firing state is per
        # process, so an inherited plan would crash-loop the job.
        # Non-crash faults intentionally survive the restart.  The spent
        # set is sticky (a later unrelated failure must not resurrect a
        # fault) and keyed on the plan's own exit codes, so exit=
        # overrides are recognized.
        from dlrover_tpu import chaos

        plan = chaos.active_plan()
        if plan is not None:
            crash_sites = {
                s.exit_code: s.site
                for s in plan.specs
                if s.kind == "crash" and s.site != "master.restart"
            }
            for _, code in self._last_failures:
                site = crash_sites.get(code)
                if site:
                    self._spent_crash_sites.add(site)
        for lr in range(cfg.nproc_per_node):
            env = dict(os.environ)
            if self._spent_crash_sites:
                chaos.scrub_env(env, self._spent_crash_sites)
            old_pp = env.get("PYTHONPATH", "")
            env["PYTHONPATH"] = os.pathsep.join(
                [p for p in extra_path if p]
                + ([old_pp] if old_pp else [])
            )
            env.update(
                worker_env(
                    job_name=cfg.job_name,
                    master_addr=self.master_addr,
                    node_id=cfg.node_id,
                    node_rank=world_info["my_rank"],
                    node_num=len(world),
                    process_id=base + lr,
                    num_processes=world_info["num_processes"],
                    coordinator=world_info["coordinator"],
                    restart_count=self._restart_count,
                )
            )
            env["DLROVER_TPU_LOCAL_RANK"] = str(lr)
            env["DLROVER_TPU_LOCAL_WORLD_SIZE"] = str(cfg.nproc_per_node)
            env["DLROVER_TPU_RDZV_ROUND"] = str(world_info["round"])
            env["DLROVER_TPU_NODE_ROLE"] = cfg.node_role or "worker"
            log_file = None
            stdout = stderr = None
            if cfg.log_dir:
                os.makedirs(cfg.log_dir, exist_ok=True)
                path = os.path.join(
                    cfg.log_dir,
                    f"worker_r{world_info['my_rank']}_l{lr}"
                    f"_round{world_info['round']}.log",
                )
                log_file = open(path, "ab")
                stdout = stderr = log_file
            proc = subprocess.Popen(
                self.entrypoint,
                env=env,
                stdout=stdout,
                stderr=stderr,
                start_new_session=True,  # own process group for clean kill
            )
            self._workers.append(WorkerProcess(lr, proc, log_file))
        logger.info(
            "started %d worker(s): pids=%s",
            len(self._workers), [w.proc.pid for w in self._workers],
        )

    def _stop_workers(self, reason: str = "", grace: float = 10.0) -> None:
        if not self._workers:
            return
        if self.on_workers_stopping is not None:
            try:
                self.on_workers_stopping(reason)
            except Exception:  # noqa: BLE001
                logger.exception("on_workers_stopping hook failed")
        for w in self._workers:
            if w.poll() is None:
                try:
                    os.killpg(os.getpgid(w.proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.monotonic() + grace
        for w in self._workers:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                w.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(w.proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                w.proc.wait()
        for w in self._workers:
            if w.log_file:
                w.log_file.close()
        logger.info("stopped workers (%s)", reason or "requested")
        self._workers = []

    # -- monitor loop (reference training.py:886) ---------------------------
    def _monitor(self) -> str:
        cfg = self.config
        while True:
            time.sleep(cfg.monitor_interval)
            # 1. master-pushed actions (via heartbeat thread)
            action = self._pending_action
            self._pending_action = None
            if action == DiagnosisActionType.STOP_JOB:
                return RunResult.STOP_JOB
            if action == DiagnosisActionType.RELAUNCH_WORKER:
                return RunResult.RELAUNCH_REQUESTED
            if action == DiagnosisActionType.RESTART_WORKER:
                return RunResult.RESTART_REQUESTED
            # 2. worker process health
            codes = [w.poll() for w in self._workers]
            if all(c == 0 for c in codes):
                return RunResult.SUCCEEDED
            if any(c is not None and c != 0 for c in codes):
                bad = [
                    (w.local_rank, c)
                    for w, c in zip(self._workers, codes)
                    if c not in (None, 0)
                ]
                logger.warning("worker failure(s): %s", bad)
                self._last_failures = bad
                return RunResult.FAILED
            # 3. membership change -> re-rendezvous (reference
            #    _membership_changed :1028)
            try:
                if self.client.num_nodes_waiting(RendezvousName.TRAINING) > 0:
                    return RunResult.MEMBERSHIP_CHANGED
            except Exception as e:  # noqa: BLE001
                logger.warning("num_nodes_waiting failed: %s", e)

    # -- main entry (reference _invoke_run :863) ----------------------------
    def run(self) -> int:
        cfg = self.config
        self._start_heartbeat()
        self.resource_monitor.start()
        if self._ctx.auto_tune:
            self.config_tuner.start()
        metrics_port = int(os.environ.get("DLROVER_TPU_METRICS_PORT", "0"))
        if metrics_port:
            from dlrover_tpu.agent.metrics import (
                INTEGRITY_COUNTER_NAMES,
                MetricsRegistry,
                MetricsServer,
                integrity_counters,
                perf_stats,
            )
            from dlrover_tpu.agent.monitor import current_usage

            reg = MetricsRegistry()
            reg.gauge("restart_count", lambda: float(self._restart_count))
            reg.gauge("rdzv_round", lambda: float(self._rdzv_round))
            # Checkpoint-integrity signals (replica rejections and staged
            # -state rejections happen in this process; corruption found
            # by worker-side restores reaches the master via the
            # ckpt_integrity diagnosis reports instead).
            for cname in INTEGRITY_COUNTER_NAMES:
                reg.gauge(
                    cname,
                    lambda n=cname: float(integrity_counters.get(n)),
                )
            # Flash-ckpt fast-path signals (ISSUE 4): persist throughput
            # is set by the in-process saver; the train-stall and staging
            # gauges read the workers' reports out of the saver's shared
            # stat dict (one short-budget snapshot per gauge sample).
            reg.gauge(
                "ckpt_persist_mbps",
                lambda: perf_stats.get("ckpt_persist_mbps"),
            )
            reg.gauge(
                "ckpt_stall_ms_last",
                lambda: (
                    self.saver.last_stall_ms()
                    if self.saver is not None
                    else perf_stats.get("ckpt_stall_ms_last")
                ),
            )
            reg.gauge(
                "ckpt_staged_mbps",
                lambda: (
                    self.saver.staged_mbps()
                    if self.saver is not None
                    else perf_stats.get("ckpt_staged_mbps")
                ),
            )
            # Scale-out checkpoint gauges (ISSUE 7), riding the saver's
            # one-round-trip stat snapshot: aggregate = the node's summed
            # per-rank slice-write bandwidth; skipped = dirty-fence refs
            # in the ranks' last incremental saves.
            reg.gauge(
                "ckpt_agg_persist_mbps",
                lambda: (
                    self.saver.agg_persist_mbps()
                    if self.saver is not None
                    else perf_stats.get("ckpt_agg_persist_mbps")
                ),
            )
            reg.gauge(
                "ckpt_tensors_skipped",
                lambda: (
                    float(self.saver.tensors_skipped_total())
                    if self.saver is not None
                    else perf_stats.get("ckpt_tensors_skipped")
                ),
            )
            reg.gauge(
                "node_cpu_percent",
                lambda: current_usage()["cpu_percent"],
            )
            reg.gauge(
                "node_memory_mb", lambda: current_usage()["memory_mb"]
            )
            try:
                self.metrics_server = MetricsServer(reg, metrics_port)
                self.metrics_server.start()
            except OSError:
                logger.warning(
                    "metrics port %d unavailable; endpoint disabled",
                    metrics_port,
                )
        # Flash-checkpoint saver daemon: lives in the agent so persistence
        # survives worker crashes (reference start_async_saving_ckpt :869).
        if self.saver is None:
            try:
                from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

                self.saver = AsyncCheckpointSaver(
                    cfg.job_name, cfg.nproc_per_node,
                    master_client=self.client,
                )
                self.saver.start()
                self.on_workers_stopping = self.saver.save_shm_to_storage
            except Exception:  # noqa: BLE001
                logger.exception("could not start async checkpoint saver")
        try:
            while True:
                world_info = self._rendezvous()
                self._report_status(NodeStatus.RUNNING)
                self._start_workers(world_info)
                result = self._monitor()
                if result == RunResult.SUCCEEDED:
                    self._stop_workers("success", grace=5.0)
                    self._report_status(NodeStatus.SUCCEEDED)
                    logger.info("node %d training succeeded", cfg.node_id)
                    return 0
                if result == RunResult.STOP_JOB:
                    self._stop_workers("stop-job")
                    self._report_status(
                        NodeStatus.FAILED, exit_reason="stopped_by_master"
                    )
                    return 1
                if result == RunResult.RELAUNCH_REQUESTED:
                    # Master diagnosed this node as sick: exit so the
                    # platform replaces it (in-place restart won't help).
                    self._stop_workers("master requested node relaunch")
                    self._report_status(
                        NodeStatus.FAILED, exit_reason="relaunch_requested"
                    )
                    return 1
                if result == RunResult.FAILED:
                    self._restart_count += 1
                    self._report_failure_safe(
                        f"worker failure (restart {self._restart_count}/"
                        f"{cfg.max_restarts}): {self._last_failures}",
                        restart_count=self._restart_count,
                    )
                    # RESTART (in place) vs RELAUNCH (replace this node) —
                    # reference diagnose_training_failure training.py:934.
                    action = self.diagnosis.diagnose_training_failure(
                        self._last_failures, self._restart_count
                    )
                    if (
                        action == DiagnosisActionType.RELAUNCH_WORKER
                        or self._restart_count > cfg.max_restarts
                    ):
                        self._stop_workers("relaunch requested")
                        self._report_status(
                            NodeStatus.FAILED,
                            exit_reason="relaunch_requested"
                            if self._restart_count <= cfg.max_restarts
                            else "max_restarts",
                        )
                        return 1
                    self._stop_workers("worker failure; re-rendezvous")
                elif result in (
                    RunResult.MEMBERSHIP_CHANGED,
                    RunResult.RESTART_REQUESTED,
                ):
                    logger.info("restarting workers: %s", result)
                    self._stop_workers(result)
                # loop -> new rendezvous round
        finally:
            self._stop_evt.set()
            self._stop_workers("agent exiting")
            if self.saver is not None:
                self.saver.stop()


def launch_agent(
    config: ElasticLaunchConfig,
    entrypoint: List[str],
    master_addr: str,
) -> int:
    """Build and run the agent (reference ``launch_agent :1098``)."""
    agent = ElasticTrainingAgent(config, entrypoint, master_addr)
    return agent.run()
