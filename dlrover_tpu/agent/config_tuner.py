"""Agent-side parallel-config tuner.

Parity with reference ``elastic_agent/config/paral_config_tuner.py:29``
(``ParalConfigTuner``: poll the master's ``ParallelConfig``, write a JSON
file the trainer hot-reloads).  The file path is exported to workers via
``DLROVER_TPU_PARAL_CONFIG_PATH``.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from dlrover_tpu.common.log import logger

CONFIG_PATH_ENV = "DLROVER_TPU_PARAL_CONFIG_PATH"


class ParalConfigTuner:
    def __init__(
        self,
        master_client,
        config_path: str = "",
        interval_s: float = 30.0,
    ):
        self._client = master_client
        self._path = config_path or os.path.join(
            os.environ.get("TMPDIR", "/tmp"),
            f"dlrover_tpu_paral_config_{os.getpid()}.json",
        )
        self._interval = interval_s
        self._last_version = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.environ[CONFIG_PATH_ENV] = self._path

    @property
    def config_path(self) -> str:
        return self._path

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="paral-config-tuner", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def poll_once(self) -> bool:
        """Fetch the config; write the file if the version advanced."""
        cfg = self._client.get_parallel_config()
        if cfg is None or cfg.version <= self._last_version:
            return False
        payload = {
            "version": cfg.version,
            "dataloader": cfg.dataloader,
            "optimizer": cfg.optimizer,
            "mesh": cfg.mesh,
        }
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._path)
        self._last_version = cfg.version
        logger.info(
            "paral config v%d written to %s", cfg.version, self._path
        )
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001
                logger.debug("paral config poll failed: %s", e)


def read_paral_config(path: str = "") -> Optional[dict]:
    """Trainer-side hot-reload helper."""
    path = path or os.environ.get(CONFIG_PATH_ENV, "")
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
