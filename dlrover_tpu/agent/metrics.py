"""Agent /metrics endpoint (Prometheus text exposition).

The TPU stand-in for the reference's xpu-timer Prometheus scrape
(``xpu_timer_metric_collector.py:22`` reads a worker-local metrics port):
here the *agent* exposes its own gauges — restart counts, persisted
checkpoint steps, host resource usage — for cluster scrapers.  Enabled by
``DLROVER_TPU_METRICS_PORT`` (0/unset = off).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from dlrover_tpu.common.log import logger

PREFIX = "dlrover_tpu"


class CounterSet:
    """Monotonic named counters, thread-safe, sampled by gauges.

    Process-global instances (``integrity_counters``) let deep layers
    (checkpoint engine, replica exchange, saver) count rare-but-serious
    events without holding a registry reference; the agent registers one
    gauge per name at startup so the counts reach Prometheus."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            val = self._counts.get(name, 0) + n
            self._counts[name] = val
            return val

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


#: Checkpoint-integrity signals (ISSUE 3): silent bit-rot must surface as
#: an operator signal, not a log line lost in the noise.
INTEGRITY_COUNTER_NAMES = (
    "ckpt_corruption_detected",  # shard failed CRC/structural verification
    "ckpt_step_quarantined",  # step dir renamed/markered out of the ladder
    "ckpt_replica_rejected",  # replica payload failed verification
    "ckpt_staged_rejected",  # shm-staged state refused before persist
)

integrity_counters = CounterSet()


class StatSet:
    """Last-value named stats, thread-safe, sampled by gauges — the
    peer of :class:`CounterSet` for non-monotonic signals (latencies,
    throughputs) that deep layers set and the agent exposes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._vals: Dict[str, float] = {}

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._vals[name] = float(value)

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._vals.get(name, default)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._vals)


#: Checkpoint fast-path signals (ISSUE 4): the trainer's save_to_memory
#: stall and the saver's persist throughput are the paper's headline
#: numbers — they must be scrapeable, not grep-able.  The agent registers
#: three gauges (training.py): ``ckpt_persist_mbps`` from this process's
#: ``perf_stats`` (the saver persists in-process), and
#: ``ckpt_stall_ms_last`` / ``ckpt_staged_mbps`` from the workers'
#: reports in the saver's stat SharedDict (the engines run in worker
#: processes, so their in-memory ``perf_stats`` is invisible here).
perf_stats = StatSet()


class MetricsRegistry:
    """Name -> callable returning a float (sampled at scrape time)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gauges: Dict[str, Callable[[], float]] = {}

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = fn

    def set(self, name: str, value: float) -> None:
        self.gauge(name, lambda v=value: v)

    def render(self) -> str:
        lines = []
        with self._lock:
            items = list(self._gauges.items())
        for name, fn in items:
            try:
                val = float(fn())
            except Exception as e:  # noqa: BLE001
                # A broken gauge callback should not kill the scrape,
                # but a permanently-failing one deserves a trace.
                logger.debug("metrics: gauge %s failed: %s", name, e)
                continue
            lines.append(f"# TYPE {PREFIX}_{name} gauge")
            lines.append(f"{PREFIX}_{name} {val}")
        return "\n".join(lines) + "\n"


class MetricsServer:
    def __init__(self, registry: MetricsRegistry, port: int = 0):
        self.registry = registry
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = reg.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request logs
                pass

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="metrics-http",
                daemon=True,
            )
            self._thread.start()
            logger.info("metrics endpoint on :%d/metrics", self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
