"""Agent /metrics endpoint (Prometheus text exposition).

The TPU stand-in for the reference's xpu-timer Prometheus scrape
(``xpu_timer_metric_collector.py:22`` reads a worker-local metrics port):
here the *agent* exposes its own gauges — restart counts, persisted
checkpoint steps, host resource usage — for cluster scrapers.  Enabled by
``DLROVER_TPU_METRICS_PORT`` (0/unset = off).
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from dlrover_tpu.common.log import logger

PREFIX = "dlrover_tpu"


class CounterSet:
    """Monotonic named counters, thread-safe, sampled by gauges.

    Process-global instances (``integrity_counters``) let deep layers
    (checkpoint engine, replica exchange, saver) count rare-but-serious
    events without holding a registry reference; the agent registers one
    gauge per name at startup so the counts reach Prometheus."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            val = self._counts.get(name, 0) + n
            self._counts[name] = val
            return val

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


#: Checkpoint-integrity signals (ISSUE 3): silent bit-rot must surface as
#: an operator signal, not a log line lost in the noise.
INTEGRITY_COUNTER_NAMES = (
    "ckpt_corruption_detected",  # shard failed CRC/structural verification
    "ckpt_step_quarantined",  # step dir renamed/markered out of the ladder
    "ckpt_replica_rejected",  # replica payload failed verification
    "ckpt_staged_rejected",  # shm-staged state refused before persist
    "ckpt_commit_blocked",  # slice coverage proof refused a commit
)

integrity_counters = CounterSet()


class StatSet:
    """Last-value named stats, thread-safe, sampled by gauges — the
    peer of :class:`CounterSet` for non-monotonic signals (latencies,
    throughputs) that deep layers set and the agent exposes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._vals: Dict[str, float] = {}

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._vals[name] = float(value)

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._vals.get(name, default)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._vals)


#: Checkpoint fast-path signals (ISSUE 4): the trainer's save_to_memory
#: stall and the saver's persist throughput are the paper's headline
#: numbers — they must be scrapeable, not grep-able.  The agent registers
#: three gauges (training.py): ``ckpt_persist_mbps`` from this process's
#: ``perf_stats`` (the saver persists in-process), and
#: ``ckpt_stall_ms_last`` / ``ckpt_staged_mbps`` from the workers'
#: reports in the saver's stat SharedDict (the engines run in worker
#: processes, so their in-memory ``perf_stats`` is invisible here).
perf_stats = StatSet()


class Histogram:
    """Fixed-bucket latency histogram, thread-safe — the gateway's
    request-latency / TTFT instrument (ISSUE 5).  Prometheus-shaped:
    ``observe`` increments the first bucket whose upper bound holds the
    value; ``percentile`` answers with that bucket's upper bound (the
    standard conservative bucketed estimate), so p50/p95/p99 gauges are
    O(buckets) at scrape time with no per-observation allocation."""

    #: Default bounds in milliseconds: sub-ms through 30s.
    DEFAULT_BUCKETS_MS = (
        1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
        1000.0, 2000.0, 5000.0, 10000.0, 30000.0,
    )

    def __init__(self, buckets=DEFAULT_BUCKETS_MS,
                 window_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._bounds = tuple(sorted(float(b) for b in buckets))
        # RLock: _roll_locked re-takes it under the public methods'
        # hold, keeping every state write lexically inside a lock.
        self._lock = threading.RLock()
        self._counts = [0] * (len(self._bounds) + 1)  # +inf tail
        self._total = 0
        self._sum = 0.0
        #: ``window_s``: percentiles cover the current + previous
        #: window only, instead of the process lifetime.  A signal that
        #: drives CONTROL (the autoscaler's TTFT pressure) must decay:
        #: a cumulative histogram ratchets — one bad cold-start period
        #: keeps p95 above threshold ~forever and the fleet would scale
        #: up and never back down.
        self._window_s = window_s
        self._clock = clock
        self._epoch_start = clock()
        self._prev_counts = [0] * (len(self._bounds) + 1)
        self._prev_total = 0
        self._prev_sum = 0.0

    def _roll_locked(self) -> None:
        with self._lock:  # re-entrant under the public methods' hold
            if self._window_s is None:
                return
            now = self._clock()
            elapsed = now - self._epoch_start
            if elapsed < self._window_s:
                return
            fresh = [0] * (len(self._bounds) + 1)
            if elapsed < 2 * self._window_s:
                # Current window ages into "previous"; observations
                # older than that fall out.
                self._prev_counts = self._counts
                self._prev_total = self._total
                self._prev_sum = self._sum
            else:
                # Idle for 2+ windows: everything has aged out.
                self._prev_counts = list(fresh)
                self._prev_total = 0
                self._prev_sum = 0.0
            self._counts = fresh
            self._total = 0
            self._sum = 0.0
            self._epoch_start = now

    def observe(self, value: float) -> None:
        v = float(value)
        i = 0
        for i, b in enumerate(self._bounds):  # noqa: B007
            if v <= b:
                break
        else:
            i = len(self._bounds)
        with self._lock:
            self._roll_locked()
            self._counts[i] += 1
            self._total += 1
            self._sum += v

    @property
    def count(self) -> int:
        with self._lock:
            self._roll_locked()
            return self._total + self._prev_total

    def mean(self) -> float:
        with self._lock:
            self._roll_locked()
            total = self._total + self._prev_total
            return (self._sum + self._prev_sum) / total if total else 0.0

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket containing the p-quantile (p in
        [0, 1]) over the covered span (lifetime, or the last 1-2
        windows when ``window_s`` is set).  Values past the last bound
        report that bound — the histogram saturates rather than
        guessing at the tail."""
        with self._lock:
            self._roll_locked()
            total = self._total + self._prev_total
            if not total:
                return 0.0
            rank = p * total
            seen = 0
            for i in range(len(self._counts)):
                c = self._counts[i] + self._prev_counts[i]
                seen += c
                if seen >= rank and c:
                    return self._bounds[min(i, len(self._bounds) - 1)]
            return self._bounds[-1]

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": float(self.count),
            "mean": round(self.mean(), 3),
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }

    def state(self) -> Dict[str, object]:
        """Mergeable wire form (ISSUE 9): bucket bounds + the counts
        covering the live window span (current + previous window,
        rolled first so aged-out observations are excluded).  This is
        what one gateway of a sharded tier ships in its stats snapshot
        — percentiles themselves are NOT mergeable (averaging two p95s
        whipsaws the autoscaler); bucket counts are."""
        with self._lock:
            self._roll_locked()
            return {
                "bounds": list(self._bounds),
                "counts": [
                    c + p for c, p in
                    zip(self._counts, self._prev_counts)
                ],
                "total": self._total + self._prev_total,
                "sum": self._sum + self._prev_sum,
            }

    def merge(self, other) -> None:
        """Fold another histogram (or a :meth:`state` dict) into this
        one, bucket-wise.  Window-aware on both sides: ``other``'s
        state covers only its live windows, and the merged counts land
        in THIS histogram's current window (so they age out on this
        instance's clock).  Bounds must match exactly — merging
        differently-bucketed histograms would silently misbin.

        The tier aggregator builds a FRESH histogram per pass and
        merges every gateway's state into it, so counts are never
        double-folded across passes."""
        st = other.state() if isinstance(other, Histogram) else other
        if list(st.get("bounds", [])) != list(self._bounds):
            raise ValueError(
                f"histogram bounds mismatch: {st.get('bounds')} != "
                f"{list(self._bounds)}"
            )
        counts = st["counts"]
        if len(counts) != len(self._counts):
            raise ValueError(
                f"histogram count vector length {len(counts)} != "
                f"{len(self._counts)}"
            )
        with self._lock:
            self._roll_locked()
            for i, c in enumerate(counts):
                self._counts[i] += int(c)
            self._total += int(st["total"])
            self._sum += float(st["sum"])

    @classmethod
    def merged(cls, states, buckets=None) -> "Histogram":
        """A fresh (windowless) histogram holding the bucket-wise sum
        of ``states`` (:meth:`state` dicts and/or Histograms); empty
        input yields an empty histogram over the default buckets."""
        states = list(states)
        if buckets is None:
            for st in states:
                src = st.state() if isinstance(st, Histogram) else st
                if src.get("bounds"):
                    buckets = tuple(src["bounds"])
                    break
            else:
                buckets = cls.DEFAULT_BUCKETS_MS
        agg = cls(buckets=buckets)
        for st in states:
            agg.merge(st)
        return agg

    def register_gauges(self, registry: "MetricsRegistry",
                        name: str) -> None:
        """Expose count/p50/p95/p99 as ``<name>_*`` gauges."""
        registry.gauge(f"{name}_count", lambda: float(self.count))
        for q, label in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
            registry.gauge(
                f"{name}_{label}_ms",
                lambda q=q: self.percentile(q),
            )


class MetricsRegistry:
    """Name -> callable returning a float (sampled at scrape time)."""

    #: Consecutive scrape failures before a gauge's callback failure is
    #: promoted from per-scrape debug to a once-per-gauge WARNING: one
    #: blip during startup is noise, a gauge that never answers is a
    #: blind spot an operator believes is being watched.
    FAIL_PROMOTE_AFTER = 3

    def __init__(self):
        self._lock = threading.Lock()
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._overwrite_warned: set = set()
        self._fail_streak: Dict[str, int] = {}
        self._fail_warned: set = set()

    def gauge(self, name: str, fn: Callable[[], float]) -> None:
        with self._lock:
            if name in self._gauges and \
                    name not in self._overwrite_warned:
                # Registering over an existing name silently replaced
                # it before ISSUE 12: two subsystems exporting the same
                # name means one of them is unknowingly dark.  Warn
                # ONCE per name (re-registration is also a legitimate
                # restart idiom — it must not spam every relaunch).
                self._overwrite_warned.add(name)
                logger.warning(
                    "metrics: gauge %r re-registered; previous "
                    "callback replaced (this warning fires once per "
                    "name)", name,
                )
            self._gauges[name] = fn

    def set(self, name: str, value: float) -> None:
        """Pin a constant value.  Repeated sets UPDATE by design (the
        last-value idiom) — no overwrite warning."""
        with self._lock:
            self._gauges[name] = lambda v=value: v

    def render(self) -> str:
        lines = []
        with self._lock:
            items = list(self._gauges.items())
        for name, fn in items:
            try:
                val = float(fn())
            except Exception as e:  # noqa: BLE001
                # A broken gauge callback should not kill the scrape;
                # one that fails PERSISTENTLY is promoted to a
                # once-per-gauge warning (a debug line per scrape is
                # exactly how a dead gauge hides for weeks).
                with self._lock:
                    streak = self._fail_streak.get(name, 0) + 1
                    self._fail_streak[name] = streak
                    promote = (
                        streak >= self.FAIL_PROMOTE_AFTER
                        and name not in self._fail_warned
                    )
                    if promote:
                        self._fail_warned.add(name)
                if promote:
                    logger.warning(
                        "metrics: gauge %s has failed %d consecutive "
                        "scrapes (%s) — it is exporting NOTHING",
                        name, streak, e,
                    )
                else:
                    logger.debug("metrics: gauge %s failed: %s",
                                 name, e)
                continue
            with self._lock:
                if self._fail_streak.pop(name, None):
                    # Recovered: a later relapse deserves a new warning.
                    self._fail_warned.discard(name)
            lines.append(f"# TYPE {PREFIX}_{name} gauge")
            lines.append(f"{PREFIX}_{name} {val}")
        return "\n".join(lines) + "\n"


class MetricsServer:
    def __init__(self, registry: MetricsRegistry, port: int = 0):
        self.registry = registry
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = reg.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request logs
                pass

        self._httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="metrics-http",
                daemon=True,
            )
            self._thread.start()
            logger.info("metrics endpoint on :%d/metrics", self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
