"""Pre-flight node health check: paired matmul+psum benchmark.

Parity with reference ``NodeCheckElasticAgent`` (``training.py:1241``,
payloads ``trainer/torch/node_check/nvidia_gpu.py:39``) on TPU terms: nodes
rendezvous in the *network-check* service, are paired into 2-node sub-worlds
(round 0: adjacent; round 1: fastest-with-slowest), and each pair runs a
small ``jit`` matmul + ``psum`` benchmark over its own JAX world.  Elapsed
times feed the master's fault/straggler detection
(``NetworkCheckRendezvousManager``).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.rpc import find_free_port, local_ip

# The check payload runs in a subprocess so a wedged TPU runtime cannot hang
# the agent (reference runs it via the elastic agent's worker spawner).
_PAYLOAD = r"""
import os, sys, time
from dlrover_tpu.common.jax_env import ensure_platform
import jax
ensure_platform()
coord = os.environ.get("DLROVER_TPU_CHECK_COORD", "")
nproc = int(os.environ.get("DLROVER_TPU_CHECK_NPROC", "1"))
pid = int(os.environ.get("DLROVER_TPU_CHECK_PID", "0"))
if coord and nproc > 1:
    jax.distributed.initialize(coord, num_processes=nproc, process_id=pid)
import jax.numpy as jnp
# Payload must be big enough to discriminate a sick chip from dispatch
# noise (reference uses a large matmul + a 16M-element allreduce): on an
# accelerator, 8 x 4096^3 matmuls ~ 1.1 TFLOP and the allreduce moves
# 64 MB; on CPU (tests) the small sizes keep the check sub-second.
on_cpu = jax.default_backend() == "cpu"
n = int(os.environ.get(
    "DLROVER_TPU_CHECK_MATMUL_N", "512" if on_cpu else "4096"))
x = jnp.ones((n, n), jnp.bfloat16)
f = jax.jit(lambda a: a @ a)
f(x).block_until_ready()  # compile outside the timed region
t0 = time.perf_counter()
for _ in range(8):
    x = f(x)
x.block_until_ready()
# Fault injection for tests: a "slow node" pays a fixed tax inside the
# timed region so straggler detection has something to catch.
time.sleep(float(os.environ.get("DLROVER_TPU_CHECK_DELAY_S", "0")))
matmul_t = time.perf_counter() - t0
if coord and nproc > 1:
    from jax.sharding import Mesh, PartitionSpec as P, NamedSharding
    import numpy as np
    mesh = Mesh(np.array(jax.devices()), ("x",))
    sharding = NamedSharding(mesh, P("x"))
    g = jax.jit(lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P()))
    m = int(os.environ.get(
        "DLROVER_TPU_CHECK_ALLREDUCE_M",
        "1048576" if on_cpu else "16777216"))
    per = m // max(1, jax.device_count())
    arr = jax.make_array_from_process_local_data(
        sharding, np.ones((per * jax.local_device_count(),), np.float32))
    g(arr).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(4):
        g(arr).block_until_ready()
    comm_t = time.perf_counter() - t0
    if os.environ.get("DLROVER_TPU_COMM_PERF", "") == "1":
        # Bandwidth sweep (reference --comm-perftest): allreduce bus
        # bandwidth at growing payloads, algbw = 2*(n-1)/n * bytes / t.
        nd = jax.device_count()
        for m_sweep in (1 << 20, 1 << 22, 1 << 24):
            per_s = m_sweep // nd
            a = jax.make_array_from_process_local_data(
                sharding,
                np.ones((per_s * jax.local_device_count(),), np.float32))
            g(a).block_until_ready()
            t1 = time.perf_counter()
            reps = 4
            for _ in range(reps):
                g(a).block_until_ready()
            el = (time.perf_counter() - t1) / reps
            busbw = 2.0 * (nd - 1) / nd * (m_sweep * 4) / el / 1e9
            print(f"COMM_PERF bytes={m_sweep * 4} time_s={el:.6f} "
                  f"busbw_gbps={busbw:.3f}", flush=True)
else:
    comm_t = 0.0
print(f"NODE_CHECK_RESULT {matmul_t + comm_t:.6f}", flush=True)
"""


def _run_check_payload(
    coord: str, nproc: int, pid: int, timeout: float = 300.0,
    comm_perf: bool = False,
) -> Optional[float]:
    env = dict(os.environ)
    env["DLROVER_TPU_CHECK_COORD"] = coord
    env["DLROVER_TPU_CHECK_NPROC"] = str(nproc)
    env["DLROVER_TPU_CHECK_PID"] = str(pid)
    if comm_perf:
        env["DLROVER_TPU_COMM_PERF"] = "1"
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PAYLOAD],
            env=env,
            capture_output=True,
            timeout=timeout,
            text=True,
        )
    except subprocess.TimeoutExpired:
        logger.error("node check payload timed out")
        return None
    result = None
    for line in out.stdout.splitlines():
        if line.startswith("COMM_PERF"):
            logger.info("comm perf: %s", line[len("COMM_PERF "):])
        if line.startswith("NODE_CHECK_RESULT"):
            result = float(line.split()[1])
    if result is not None:
        return result
    logger.error(
        "node check payload failed rc=%d stderr=%s",
        out.returncode, out.stderr[-2000:],
    )
    return None


def node_health_check(
    config, master_addr: str, client: MasterClient, rounds: int = 2
) -> bool:
    """Run ``rounds`` of the paired benchmark; returns False if the master
    declares this node faulty (reference ``node_health_check :1460``).
    With ``config.comm_perf_test`` the final round also sweeps allreduce
    payload sizes and logs bus bandwidth (reference ``--comm-perftest``)."""
    host = local_ip()
    comm_perf = bool(getattr(config, "comm_perf_test", False))
    for r in range(rounds):
        port = find_free_port()
        client.register_node(
            node_rank=config.node_rank,
            host=host,
            agent_port=port,
            local_world_size=1,
            slice_id=config.slice_id,
        )
        client.join_rendezvous(
            config.node_rank, 1, rdzv_name=RendezvousName.NETWORK_CHECK
        )
        world, coord, my_pid, nproc = {}, "", 0, 1
        deadline = time.time() + 120
        while time.time() < deadline:
            _, _, world, coord = client.get_comm_world(
                RendezvousName.NETWORK_CHECK
            )
            if world:
                break
            time.sleep(0.5)
        if world:
            nproc = len(world)
            for rank, meta in world.items():
                if meta["node_id"] == config.node_id:
                    my_pid = int(rank)
        elapsed = _run_check_payload(
            coord if nproc > 1 else "", nproc, my_pid,
            comm_perf=comm_perf and r == rounds - 1,
        )
        succeeded = elapsed is not None
        client.report_network_check(
            succeeded, elapsed if elapsed else 0.0, round_=r
        )
        logger.info(
            "node check round %d: ok=%s elapsed=%s", r, succeeded, elapsed
        )
        if r + 1 < rounds:
            # Advance the master's pairing round.
            from dlrover_tpu.common import messages as m

            # Round advance is master-driven in the dist master; standalone
            # agents simply re-join and report with the next round index.
            time.sleep(1.0)
    # Peers may still be reporting their final round; the verdict is only
    # final once the master has every participant's result (the `complete`
    # flag) — a stability heuristic would false-settle exactly when a peer
    # is the straggler being waited on.
    deadline = time.time() + 30.0
    while time.time() < deadline:
        _, _, complete = client.get_stragglers_full()
        if complete:
            break
        time.sleep(0.75)
    faults, _ = client.get_fault_nodes()
    if config.node_id in faults:
        return False
    stragglers, times = client.get_stragglers()
    if config.node_id in stragglers:
        logger.warning(
            "node %d flagged as straggler (times=%s)", config.node_id, times
        )
    return True
