"""Agent-side async checkpoint saver daemon.

Parity with reference ``elastic_agent/torch/ckpt_saver.py``
(``AsyncCheckpointSaver :353``, ``_sync_shm_to_storage :536``,
``save_shm_to_storage :701``, ``commit_checkpoint :822``): runs inside the
*agent* process, so persistence survives worker crashes; consumes save
events from a SharedQueue, copies each local rank's shm arena to storage
under the fencing lock, votes with done files, and (on the leader node)
advances the tracker after the master's cross-node step barrier.

Breakpoint-save: when the agent is about to stop workers (failure or
membership change) it calls :meth:`save_shm_to_storage` to persist whatever
steps are staged but not yet persisted — the "checkpoint-at-breakpoint" that
makes kill-and-rejoin cheap.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional

from dlrover_tpu import chaos
from dlrover_tpu.agent.metrics import integrity_counters, perf_stats
from dlrover_tpu.checkpoint import shard_file, slicer
from dlrover_tpu.checkpoint.engine import (
    ckpt_lock_name,
    ckpt_queue_name,
    ckpt_stat_name,
)
from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedQueue,
)
from dlrover_tpu.common.shm import SharedMemoryArena, arena_name
from dlrover_tpu.common.storage import PosixDiskStorage
from dlrover_tpu.obs import journal


class AsyncCheckpointSaver:
    def __init__(
        self,
        job_name: str,
        nproc_per_node: int,
        *,
        master_client=None,
        storage=None,
    ):
        self.job_name = job_name
        self.nproc = nproc_per_node
        self.client = master_client
        self.storage = storage or PosixDiskStorage()
        self._ctx = get_context()
        # Server side of the worker-facing primitives.
        self._queue = SharedQueue(ckpt_queue_name(job_name), create=True)
        self._locks = [
            SharedLock(ckpt_lock_name(job_name, lr), create=True)
            for lr in range(nproc_per_node)
        ]
        self._stat = SharedDict(ckpt_stat_name(job_name), create=True)
        # In-process mutex per rank: the replica thread, the save-event
        # thread and breakpoint saves share one cached arena object, and
        # reopen() munmaps the mapping — concurrent reopen()/read_state()
        # on the same instance is a use-after-munmap.  Always taken
        # *inside* the cross-process fencing lock (never around it).
        # Pre-populated for every rank so lazy init can't race either.
        self._arenas: Dict[int, SharedMemoryArena] = {
            lr: SharedMemoryArena(arena_name(job_name, lr))
            for lr in range(nproc_per_node)
        }
        self._arena_mus: Dict[int, threading.Lock] = {
            lr: threading.Lock() for lr in range(nproc_per_node)
        }
        self._persisted: Dict[int, int] = {}  # local_rank -> step
        # Dirty-fence memory per local rank (incremental saves), keyed
        # by the (ckpt_dir, process_id, world) scope it was built for —
        # an elastic re-rendezvous that re-identifies the rank resets it
        # (the next save is then full, never wrong).
        self._dirty: Dict[int, slicer.DirtyTracker] = {}
        self._dirty_scope: Dict[int, tuple] = {}
        self._perf_cache: tuple = (0.0, {})  # (fetched_at, stat snapshot)
        # TTL-cache clock seam: tests age the cache by stepping a fake
        # clock instead of sleeping (or back-dating with the WRONG
        # clock family — the old wall-stamp aging never expired a
        # monotonic-compared cache).
        self._perf_clock: Callable[[], float] = time.monotonic
        self._last_event: Dict[int, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._replica_thread: Optional[threading.Thread] = None
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, self._ctx.ckpt_shard_io_workers),
            thread_name_prefix="ckpt-io",
        )
        # Cross-node in-memory replicas (reference replica.py; opt-in via
        # DLROVER_TPU_CKPT_REPLICA=1 — costs DCN bandwidth per save).
        self.replica = None
        if self._ctx.ckpt_replica and master_client is not None:
            try:
                from dlrover_tpu.checkpoint.replica import (
                    CkptReplicaManager,
                )

                self.replica = CkptReplicaManager(master_client)
            except Exception:  # noqa: BLE001
                logger.exception("replica manager unavailable")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._event_loop, name="async-ckpt-saver", daemon=True
            )
            self._thread.start()
            logger.info(
                "async checkpoint saver up (job=%s nproc=%d)",
                self.job_name, self.nproc,
            )
        if self.replica is not None and self._replica_thread is None:
            # Memory-only saves never enqueue events; replicate by
            # watching the arenas' staged steps directly.
            self._replica_thread = threading.Thread(
                target=self._replica_loop, name="ckpt-replica", daemon=True
            )
            self._replica_thread.start()

    def _replica_loop(self) -> None:
        interval = max(5.0, self.replica.push_interval / 2)
        pushed: Dict[int, int] = {}
        while not self._stop.wait(interval):
            for lr in range(self.nproc):
                try:
                    arena = self._arena(lr)
                    with self._arena_mu(lr):
                        arena.reopen()
                        # Cheap metadata peek first: copying the full state
                        # every poll just to compare steps would hold the
                        # fencing lock for a multi-GB memcpy.
                        meta = arena.metadata()
                    if meta is None or int(
                        meta.get("extra", {}).get("step", -1)
                    ) <= pushed.get(lr, -1):
                        continue
                    lock = self._locks[lr] if lr < len(self._locks) else None
                    if lock is not None and not lock.acquire(timeout=5.0):
                        continue
                    try:
                        with self._arena_mu(lr):
                            read = arena.read_state(copy=True)
                    finally:
                        if lock is not None:
                            lock.release()
                    if read is None:
                        continue
                    tensors, extra = read
                    step = int(extra.get("step", -1))
                    if step <= pushed.get(lr, -1):
                        continue
                    pid = int(extra.get("process_id", lr))
                    if self.replica.backup_shard(pid, step, tensors, extra):
                        pushed[lr] = step
                except FileNotFoundError:
                    continue  # no staged state yet on this rank
                except Exception:  # noqa: BLE001
                    logger.exception("replica push for rank %d failed", lr)

    def update_world(self, node_rank: int, world_size: int) -> None:
        """Refresh replica ring neighbours after a rendezvous round."""
        if self.replica is not None:
            self.replica.update_world(node_rank, world_size)

    def seed_from_replicas(
        self, process_ids: Dict[int, int], num_processes: int
    ) -> int:
        """Seed empty/stale local arenas from peer replicas before workers
        start (reference FullCkptReplicaManager gather-on-restart).

        ``process_ids``: local_rank -> global process_id for the coming
        round.  Returns how many arenas were seeded."""
        if self.replica is None:
            return 0
        seeded = 0
        for lr, pid in process_ids.items():
            arena = self._arena(lr)
            cur_step = -1
            try:
                with self._arena_mu(lr):
                    arena.reopen()
                    meta = arena.metadata()
                if meta is not None:
                    cur_step = int(meta.get("extra", {}).get("step", -1))
            except Exception as e:  # noqa: BLE001
                # No local arena yet is normal on a fresh node; the
                # fetch below then pulls the full replica (min_step=0).
                logger.debug(
                    "replica restore: arena peek failed for rank %d: "
                    "%s", lr, e,
                )
            got = self.replica.fetch_replica(pid, min_step=cur_step + 1)
            if got is None:
                continue
            step, tensors, extra = got
            if extra.get("num_processes") != num_processes:
                continue  # world changed: resharding goes through storage
            lock = self._locks[lr] if lr < len(self._locks) else None
            if lock is not None and not lock.acquire(timeout=30.0):
                continue
            try:
                with self._arena_mu(lr):
                    arena.write_state(tensors, extra=extra)
                seeded += 1
                logger.info(
                    "replica: seeded local arena %d with step %d", lr, step
                )
            finally:
                if lock is not None:
                    lock.release()
        return seeded

    def stop(self) -> None:
        self._stop.set()
        if self.replica is not None:
            self.replica.stop()
        self._pool.shutdown(wait=False)
        self._queue.close()
        for lock in self._locks:
            lock.close()
        self._stat.close()
        for arena in self._arenas.values():
            arena.close()

    def _arena(self, local_rank: int) -> SharedMemoryArena:
        return self._arenas[local_rank]

    def _arena_mu(self, local_rank: int) -> threading.Lock:
        return self._arena_mus[local_rank]

    # -- event loop (reference _sync_shm_to_storage :536) -------------------
    def _event_loop(self) -> None:
        while not self._stop.is_set():
            try:
                event = self._queue.get(timeout=2.0)
            except TimeoutError:
                continue
            except Exception:  # noqa: BLE001
                if not self._stop.is_set():
                    logger.exception("ckpt saver queue error")
                    time.sleep(1.0)
                continue
            if not isinstance(event, dict) or event.get("event") != "save":
                continue
            self._last_event[event.get("local_rank", 0)] = event
            try:
                self._handle_save(event)
            except Exception:  # noqa: BLE001
                logger.exception("ckpt save event failed: %s", event)

    def _handle_save(self, event: dict) -> None:
        lr = int(event.get("local_rank", 0))
        step = int(event.get("step", 0))
        pid = int(event.get("process_id", lr))
        nproc_global = int(event.get("num_processes", self.nproc))
        ckpt_dir = event["ckpt_dir"]
        keep_last = shard_file.resolve_keep_last(event.get("max_to_keep"))
        lock = self._locks[lr] if lr < len(self._locks) else None
        if lock is not None and not lock.acquire(timeout=60.0):
            logger.warning("saver: lock for rank %d busy; skipping", lr)
            return
        # Zero-copy fast path: stream the arena's mapped bytes straight to
        # storage, holding the fencing lock + arena mutex for the whole
        # persist (the views' lifetime contract — see
        # SharedMemoryArena.read_state).  A worker staging its next step
        # waits on the lock for the persist duration, exactly like the
        # reference saver; the bench measures that stall.  Copy mode —
        # one full state copy under the lock, persist from the copy with
        # the lock released (the old bounded stall) — is kept for every
        # consumer that outlives the lock: the replica-ring push, and
        # operators on slow storage who set ckpt_zero_copy=False.
        copy_mode = self.replica is not None or not self._ctx.ckpt_zero_copy
        tensors = extra = None
        stats = None
        try:
            arena = self._arena(lr)
            with self._arena_mu(lr):
                arena.reopen()
                read = arena.read_state(copy=copy_mode)
                if read is None:
                    logger.warning("saver: arena for rank %d empty", lr)
                    return
                tensors, extra = read
                staged_step = int(extra.get("step", -1))
                if staged_step != step:
                    logger.info(
                        "saver: arena holds step %d (event wanted %d) — "
                        "persisting the staged one", staged_step, step,
                    )
                    step = staged_step
                # The arena's CRC covers the meta blob only; validate the
                # staged state's own layout metadata before it becomes a
                # durable shard — a torn/mismatched stage must never be
                # persisted (and later trusted) under this event's
                # identity.
                reason = shard_file.validate_staged_state(
                    tensors, extra,
                    expect_process_id=pid,
                    expect_num_processes=nproc_global,
                )
                if reason is not None:
                    integrity_counters.inc("ckpt_staged_rejected")
                    logger.error(
                        "saver: rank %d staged state rejected, NOT "
                        "persisted (%s)", lr, reason,
                    )
                    return
                if not copy_mode:
                    stats = self._persist(
                        ckpt_dir, step, pid, tensors, extra, lr=lr,
                        sliced=not event.get("breakpoint"),
                        world=nproc_global,
                    )
        finally:
            if lock is not None:
                lock.release()
        if copy_mode:
            # Stable copies: persist outside the locks, then push.
            stats = self._persist(
                ckpt_dir, step, pid, tensors, extra, lr=lr,
                sliced=not event.get("breakpoint"), world=nproc_global,
            )
            if self.replica is not None:
                self._pool.submit(
                    self.replica.backup_shard, pid, step, tensors, extra
                )
        self._report_persist_perf(step, stats["mbps"])
        self._persisted[lr] = step
        # One round trip for the whole rank row: the persisted-step ack
        # plus the per-rank gauges the agg scrape sums.
        self._stat.update(
            {
                f"persisted_{lr}": step,
                f"persist_mbps_{lr}": round(stats["mbps"], 1),
                f"tensors_skipped_{lr}": stats.get("skipped", 0),
            }
        )
        logger.info(
            "saver: persisted rank %d step %d in %.2fs (%.0f MB/s, "
            "%d tensors ref'd unchanged)",
            lr, step, stats["seconds"], stats["mbps"],
            stats.get("skipped", 0),
        )
        if pid == 0:
            # Commit waits for the OTHER ranks' shards — never block the
            # event loop on it (they may be persisted by this same loop).
            self._pool.submit(
                self._commit, ckpt_dir, step, nproc_global, keep_last
            )

    def _tracker(
        self, lr: int, ckpt_dir: str, pid: int, world: int
    ) -> slicer.DirtyTracker:
        scope = (ckpt_dir, pid, world)
        if self._dirty_scope.get(lr) != scope:
            self._dirty[lr] = slicer.DirtyTracker()
            self._dirty_scope[lr] = scope
        return self._dirty[lr]

    def _persist(
        self, ckpt_dir: str, step: int, pid: int, tensors, extra,
        *, lr: int = 0, sliced: bool = True, world: Optional[int] = None,
    ) -> dict:
        """One streamed shard write + throughput stats/gauges.

        The rank writes only its disjoint slice of replicated tensors
        (``sliced=False`` on breakpoint saves: a dying partial world must
        leave restorable FULL shards, not orphan slices) and refs
        tensors whose dirty fence has not tripped since their holder
        step."""
        t0 = time.perf_counter()
        chaos.inject("ckpt.slow_storage", step=step, rank=pid)
        world = int(world or extra.get("num_processes") or self.nproc)
        plan = slicer.plan_persist(
            tensors, extra,
            process_id=pid, num_processes=world,
            sliced=sliced and self._ctx.ckpt_sliced_persist,
            tracker=(
                self._tracker(lr, ckpt_dir, pid, world)
                if self._ctx.ckpt_incremental else None
            ),
            holder_exists=lambda s: self.storage.exists(
                shard_file.shard_path(ckpt_dir, s, pid)
            ),
        )
        stats = shard_file.write_shard_from_views(
            self.storage, ckpt_dir, step, pid, plan.tensors, plan.extra,
            workers=self._ctx.ckpt_persist_workers,
            meta_extra=plan.meta_extra,
        )
        self._tracker(lr, ckpt_dir, pid, world).note_plan(
            plan, step, stats.get("crcs", {})
        )
        stats["seconds"] = max(1e-9, time.perf_counter() - t0)
        stats["mbps"] = stats["total_bytes"] / stats["seconds"] / (1 << 20)
        stats["skipped"] = plan.skipped
        perf_stats.set("ckpt_persist_mbps", stats["mbps"])
        return stats

    def _report_persist_perf(self, step: int, mbps: float) -> None:
        """Throughput-only CkptPerf to the master (stall_ms=0 touches no
        stall bookkeeping) including the node's AGGREGATE persist rate
        and skipped-tensor count for the goodput/diagnosis log.  Called
        AFTER the fencing lock/arena mutex are released — a slow master
        must never stretch the lock hold the trainer's next save waits
        on.  Best-effort, short budget."""
        if self.client is None:
            return
        try:
            self.client.report_ckpt_perf(
                step=step, stall_ms=0.0, persist_mbps=mbps,
                agg_persist_mbps=self.agg_persist_mbps(),
                tensors_skipped=self.tensors_skipped_total(),
            )
        except Exception as e:  # noqa: BLE001
            logger.debug("persist perf report failed: %s", e)

    def agg_persist_mbps(self) -> float:
        """Sum of every local rank's last persist throughput — the
        node-level aggregate bandwidth the sliced persist exists to
        scale; rides the same one-round-trip stat snapshot as the other
        gauges."""
        snap = self.worker_perf()
        return sum(
            float(v) for k, v in snap.items()
            if k.startswith("persist_mbps_") and v is not None
        )

    def tensors_skipped_total(self) -> int:
        """Sum of every local rank's last dirty-fence skip count (the
        ``ckpt_tensors_skipped`` gauge)."""
        snap = self.worker_perf()
        return int(sum(
            int(v) for k, v in snap.items()
            if k.startswith("tensors_skipped_") and v is not None
        ))

    def worker_perf(self) -> Dict[str, float]:
        """One snapshot of the workers' reported perf stats — a single
        short-budget round trip, because this runs inside a Prometheus
        scrape handler (per-rank gets would cost nproc x timeout against
        a sick stat server and black out the whole endpoint).  A 1s TTL
        cache collapses the multiple gauges sampled by one scrape into
        ONE round trip (and one bounded wait against a hung server)."""
        ts, snap = self._perf_cache
        if self._perf_clock() - ts < 1.0:
            return snap
        try:
            snap = self._stat.to_dict(timeout=2.0) or {}
        except Exception as e:  # noqa: BLE001
            logger.debug("perf stat snapshot failed: %s", e)
            snap = {}
        self._perf_cache = (self._perf_clock(), snap)
        return snap

    def last_stall_ms(self) -> float:
        """Worst save_to_memory blocking time across local ranks, as the
        engines report it into the shared stat dict — the agent-side
        gauge behind ``ckpt_stall_ms_last``."""
        snap = self.worker_perf()
        return max(
            (float(v) for k, v in snap.items()
             if k.startswith("stall_ms_") and v is not None),
            default=0.0,
        )

    def staged_mbps(self) -> float:
        """Slowest rank's worker->shm staging throughput (the staging
        bottleneck) — the gauge behind ``ckpt_staged_mbps``."""
        snap = self.worker_perf()
        return min(
            (float(v) for k, v in snap.items()
             if k.startswith("staged_mbps_") and v is not None),
            default=0.0,
        )

    def _commit(self, ckpt_dir: str, step: int, world: int,
                keep_last: int = 3, timeout: float = 600.0) -> None:
        deadline = time.time() + timeout
        if not shard_file.wait_sync_barrier(
            self.client, step, min(60.0, timeout / 4), self._stop
        ) and not self._stop.is_set():
            logger.warning(
                "saver: step-%d sync barrier did not open; "
                "committing on done files alone", step,
            )
        while time.time() < deadline:
            if shard_file.all_shards_done(self.storage, ckpt_dir, step, world):
                # Votes in hand, writes finished: an unprovable slice
                # cover is terminal for this step (the previous
                # committed step stays the restore point).
                if self._ctx.ckpt_commit_coverage and not slicer.commit_gate(
                    self.storage, ckpt_dir, step
                ):
                    journal("ckpt.commit", step=step, ok=False,
                            verdict="coverage_blocked")
                    return
                shard_file.commit(
                    self.storage, ckpt_dir, step, keep_last=keep_last
                )
                journal("ckpt.commit", step=step, ok=True,
                        verdict="coverage_proven"
                        if self._ctx.ckpt_commit_coverage
                        else "ungated")
                return
            if self._stop.is_set():
                # Saver shutdown while shards are still missing: these
                # pool threads are non-daemon and would otherwise pin the
                # dying agent process for the rest of the timeout.  (A
                # ready commit is still taken — the check above runs
                # first.)
                logger.info("saver: commit of step %d aborted (stop)", step)
                return
            time.sleep(0.5)
        logger.warning("saver: commit of step %d timed out", step)

    # -- breakpoint save (reference save_shm_to_storage :701) ---------------
    def save_shm_to_storage(self, reason: str = "") -> None:
        """Persist every staged-but-unpersisted arena now (called by the
        agent right before stopping workers)."""
        for lr in range(self.nproc):
            try:
                arena = self._arena(lr)
                # Take the fencing lock so an in-flight worker write
                # finishes first — an unlocked peek mid-write reads the
                # dirty flag and would silently skip this rank's state.
                lock = self._locks[lr] if lr < len(self._locks) else None
                if lock is not None and not lock.acquire(timeout=60.0):
                    logger.warning(
                        "breakpoint save: rank %d lock busy; skipping", lr
                    )
                    continue
                try:
                    with self._arena_mu(lr):
                        arena.reopen()
                        meta = arena.metadata()
                finally:
                    if lock is not None:
                        lock.release()
            except Exception as e:  # noqa: BLE001
                # Skipping a rank's state here silently loses it on the
                # next hard kill — this must be loud.
                logger.warning(
                    "breakpoint save: arena peek failed for rank %d "
                    "(state NOT persisted): %s", lr, e,
                )
                continue
            if meta is None:
                continue
            extra = meta.get("extra", {})
            step = int(extra.get("step", -1))
            ckpt_dir = extra.get("ckpt_dir", "")
            if step < 0 or not ckpt_dir:
                continue
            if self._persisted.get(lr, -1) >= step:
                continue
            logger.info(
                "breakpoint save (%s): persisting rank %d step %d",
                reason, lr, step,
            )
            self._handle_save(
                {
                    "event": "save",
                    "step": step,
                    "local_rank": lr,
                    "process_id": extra.get("process_id", lr),
                    "num_processes": extra.get("num_processes", self.nproc),
                    "ckpt_dir": ckpt_dir,
                    # A breakpoint save may be the last write a dying
                    # world ever makes: write FULL shards — orphan slices
                    # from a partial world would be unrestorable, where a
                    # full replicated shard from any one rank is.
                    "breakpoint": True,
                }
            )
