"""Agent-side monitors: node resource usage and training step metrics.

Parity with reference ``elastic_agent/monitor/resource.py:86``
(``ResourceMonitor``: psutil + pynvml -> ``report_used_resource``) and
``monitor/training.py:77`` (``TorchTrainingMonitor``).  TPU notes: chip
utilisation comes from the jax runtime when available (device memory stats)
rather than NVML; the heartbeat itself lives in the training agent.
"""

from __future__ import annotations


import threading

from typing import Optional

from dlrover_tpu.common.log import logger


def _psutil():
    try:
        import psutil  # type: ignore

        return psutil
    except ImportError:  # pragma: no cover
        return None


def current_usage() -> dict:
    """Snapshot of host CPU/memory usage (+ TPU device memory if a live
    backend exposes it)."""
    out = {"cpu_percent": 0.0, "memory_mb": 0.0, "device_memory_mb": 0.0}
    ps = _psutil()
    if ps is not None:
        out["cpu_percent"] = ps.cpu_percent(interval=None)
        out["memory_mb"] = ps.virtual_memory().used / (1 << 20)
    try:  # device stats only when jax is already imported and live
        import sys

        jax = sys.modules.get("jax")
        if jax is not None:
            stats = jax.local_devices()[0].memory_stats() or {}
            out["device_memory_mb"] = stats.get("bytes_in_use", 0) / (1 << 20)
    # graftcheck: disable=CC104 -- device stats are optional telemetry:
    # no live jax backend is an expected state and the report simply
    # omits the field
    except Exception:  # noqa: BLE001
        pass
    return out


class ResourceMonitor:
    """Periodic used-resource reports to the master
    (reference ``resource.py:86``)."""

    def __init__(self, master_client, interval_s: float = 15.0):
        self._client = master_client
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="resource-monitor", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                usage = current_usage()
                self._client.report_used_resource(
                    cpu_percent=usage["cpu_percent"],
                    memory_mb=usage["memory_mb"],
                )
            except Exception as e:  # noqa: BLE001
                logger.debug("resource report failed: %s", e)


# Worker step metrics flow to the master's diagnosis store from
# ElasticContext.report_step (bootstrap.py) — the worker already holds the
# step counter, so no agent-side relay thread is needed.
