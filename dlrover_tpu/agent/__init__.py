"""L4 elastic agent: per-node supervisor.

Master-driven rendezvous, worker process lifecycle, async checkpoint saver,
resource/training monitors, sharding client, diagnosis agent (SURVEY.md §1
L4, reference ``dlrover/python/elastic_agent/``).
"""
