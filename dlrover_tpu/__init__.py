"""dlrover_tpu — a TPU-native elastic/fault-tolerant distributed training framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capabilities of DLRover
(reference: intelligent-machine-learning/dlrover): a per-job master that forms
and re-forms TPU worker groups (rendezvous, health checks, straggler detection,
auto-scaling), a per-host elastic agent supervising training processes, Flash
Checkpoint (async shared-memory pytree save/restore), dynamic data sharding,
and an ``accelerate()`` layer expressing DP/FSDP/TP/SP/EP/PP strategies as
GSPMD shardings over an ICI/DCN device mesh with Pallas kernels.

Top-level convenience re-exports keep the public API surface shallow::

    import dlrover_tpu as dt
    strategy = dt.accelerate(model_def, mesh_spec="auto")
    ckpt = dt.FlashCheckpointer(dirpath)
"""

__version__ = "0.1.0"

# Lazy re-exports: importing the package must stay cheap (no jax import at
# top level — agents/masters run on hosts that may not have devices).
_LAZY = {
    # acceleration
    "accelerate": "dlrover_tpu.parallel.accelerate",
    "Strategy": "dlrover_tpu.parallel.accelerate",
    "MeshSpec": "dlrover_tpu.parallel.mesh",
    "build_mesh": "dlrover_tpu.parallel.mesh",
    "build_hybrid_mesh": "dlrover_tpu.parallel.mesh",
    "plan_layout": "dlrover_tpu.parallel.layout_planner",
    "LocalSGDSync": "dlrover_tpu.parallel.local_sgd",
    # checkpointing
    "FlashCheckpointer": "dlrover_tpu.checkpoint.checkpointer",
    "CheckpointEngine": "dlrover_tpu.checkpoint.engine",
    # live resharding (restart-free elasticity)
    "build_plan": "dlrover_tpu.reshard.plan",
    "ReshardPlan": "dlrover_tpu.reshard.plan",
    "reshard_state": "dlrover_tpu.reshard.coordinator",
    "ReshardError": "dlrover_tpu.reshard.coordinator",
    # trainer SDK
    "Trainer": "dlrover_tpu.trainer.trainer",
    "TrainingArgs": "dlrover_tpu.trainer.trainer",
    "ElasticTrainer": "dlrover_tpu.trainer.elastic",
    "ElasticSampler": "dlrover_tpu.trainer.sampler",
    # data
    "DevicePrefetcher": "dlrover_tpu.data.prefetch",
    "pack_sequences": "dlrover_tpu.data.packing",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name])
        return getattr(mod, name)
    raise AttributeError(f"module 'dlrover_tpu' has no attribute {name!r}")
