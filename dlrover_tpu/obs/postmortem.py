"""Crash postmortem CLI: reconstruct a killed fleet's last seconds.

::

    python -m dlrover_tpu.obs.postmortem DUMP_DIR [--out trace.json]

Reads every per-process flight-recorder dump under ``DUMP_DIR`` and
answers the three questions an operator asks after a kill:

- **who died** — each process's dump reason (clean exit / SIGTERM /
  chaos crash, naming the injected site) and its last recorded instant;
- **what it held** — requests a dead process had in flight (spans in
  its ring with no terminal of its own) and its final journal events;
- **where work went** — traces whose spans appear in more than one
  process's dump, with the process that recorded the effective
  terminal (the failover/replay destination).

``--out`` additionally writes the merged Perfetto-loadable chrome
trace (:func:`dlrover_tpu.obs.collect.build_chrome_trace`).
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Dict, List

from dlrover_tpu.obs.collect import (
    load_dir,
    spans_by_trace,
    validate_trace,
    write_chrome_trace,
)


def _fmt_ts(us: float) -> str:
    return f"{us / 1e6:.3f}s"


def analyze(dump_dir: str) -> Dict[str, Any]:
    """The postmortem as data (the CLI renders it; tests assert on it)."""
    dumps = load_dir(dump_dir)
    traces = spans_by_trace(dumps)
    processes: List[Dict[str, Any]] = []
    for dump in dumps:
        meta = dump["meta"]
        evs = dump["events"]
        last_ts = max(
            (float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
             for e in evs), default=0.0,
        )
        proc = {
            "process": str(meta.get("process", "")),
            "pid": int(meta.get("pid", 0)),
            "reason": str(meta.get("reason", "")),
            "chaos_site": str(meta.get("chaos_site", "")),
            "events": len(evs),
            "dropped": int(meta.get("dropped", 0)),
            "last_ts_us": last_ts,
            "journal_tail": [
                {k: v for k, v in e.items() if k not in ("k", "seq")}
                for e in evs if e.get("k") == "ev"
            ][-5:],
        }
        # In-flight at death: traces this process touched but never
        # CLOSED from its own point of view.  Closure is role-shaped:
        # a gateway closes with the terminal span, a replica with the
        # decode-completion or a journal replay (a replica never
        # records terminals, so "no terminal" alone would damn every
        # request it ever finished).
        held = []
        closed = set()
        touched = {}
        for e in evs:
            if e.get("k") != "span" or not e.get("tid"):
                continue
            args = e.get("args") or {}
            rid = args.get("rid") or args.get("req_id") or ""
            touched.setdefault(e["tid"], rid)
            if args.get("terminal") or e.get("name") in (
                "rep.decode", "rep.journal_replay", "rep.kv_export",
            ):
                closed.add(e["tid"])
        for tid_key, rid in touched.items():
            if tid_key not in closed:
                held.append(rid or tid_key)
        proc["held_in_flight"] = sorted(held)
        processes.append(proc)
    # Where orphaned work went: traces spanning >1 process.
    rerouted = []
    for tid_key, spans in traces.items():
        procs = sorted({s.get("_proc", "") for s in spans})
        if len(procs) < 2:
            continue
        rep = validate_trace(spans)
        rid = next(
            (str((s.get("args") or {}).get("rid") or "")
             for s in spans if (s.get("args") or {}).get("rid")), "",
        )
        rerouted.append({
            "trace_id": tid_key,
            "req_id": rid,
            "processes": procs,
            "terminal_process": rep.get("terminal_process", ""),
            "state": rep.get("state", ""),
            "superseded_terminals": rep.get("superseded_terminals", 0),
        })
    rerouted.sort(key=lambda r: r["trace_id"])
    crashed = [p for p in processes if p["reason"] == "chaos"]
    return {
        "dump_dir": dump_dir,
        "processes": processes,
        "crashed": [p["process"] for p in crashed],
        "chaos_sites": sorted(
            {p["chaos_site"] for p in crashed if p["chaos_site"]}
        ),
        "traces": len(traces),
        "rerouted": rerouted,
    }


def render(report: Dict[str, Any]) -> str:
    lines = [f"fleet postmortem: {report['dump_dir']}"]
    lines.append(
        f"  {len(report['processes'])} process dump(s), "
        f"{report['traces']} trace(s)"
    )
    lines.append("who died:")
    for proc in report["processes"]:
        tag = proc["reason"]
        if proc["chaos_site"]:
            tag += f" [{proc['chaos_site']}]"
        lines.append(
            f"  {proc['process']:<16} pid={proc['pid']:<7} "
            f"reason={tag:<28} events={proc['events']} "
            f"dropped={proc['dropped']} "
            f"last={_fmt_ts(proc['last_ts_us'])}"
        )
        if proc["reason"] == "chaos":
            held = proc["held_in_flight"]
            lines.append(
                f"    held in flight at death: "
                f"{', '.join(held) if held else '(nothing)'}"
            )
            for ev in proc["journal_tail"]:
                lines.append(f"    last journal: {json.dumps(ev)}")
    if report["rerouted"]:
        lines.append("requests that crossed processes:")
        for r in report["rerouted"]:
            extra = (
                f" ({r['superseded_terminals']} superseded terminal)"
                if r["superseded_terminals"] else ""
            )
            lines.append(
                f"  {r['req_id'] or r['trace_id']:<12} "
                f"{' -> '.join(r['processes'])} "
                f"finished at {r['terminal_process'] or '?'} "
                f"state={r['state']}{extra}"
            )
    else:
        lines.append("no request crossed processes")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dlrover_tpu.obs.postmortem",
        description="reconstruct a killed fleet's last seconds from "
                    "flight-recorder dumps",
    )
    ap.add_argument("dump_dir", help="directory of flight-*.jsonl dumps")
    ap.add_argument("--out", default="",
                    help="also write the merged chrome trace here")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    args = ap.parse_args(argv)
    report = analyze(args.dump_dir)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    if args.out:
        write_chrome_trace(args.dump_dir, args.out)
        print(f"merged chrome trace: {args.out}")
    return 0 if report["processes"] else 1


if __name__ == "__main__":  # pragma: no cover - thin CLI shell
    raise SystemExit(main())
