"""Trace identity and clock anchoring for the fleet flight recorder.

Deliberately tiny and jax-free: these helpers run on the serving hot
path (one call per traced request per phase), in every process of the
fleet (gateways, replicas, drafts, masters, clients).

Two design decisions carry the whole cross-process story:

- **trace_id is a pure function of the request id.**  A failover
  resubmit, a journal replay, or a re-dispatched grant lands in the
  SAME trace without any process shipping state to any other; sampling
  (head-based at the gateway) is equally deterministic, so every
  gateway of a sharded tier makes the identical keep/drop decision for
  a given request.
- **durations are monotonic, timelines are anchored.**  Spans measure
  with ``time.monotonic`` (wall-clock steps under NTP must never bend
  a duration — the OB301 rule enforces this repo-wide); each process
  pins ``wall - monotonic`` ONCE at import (:data:`EPOCH_ANCHOR`) and
  dump/merge converts monotonic instants to an absolute microsecond
  timeline, so per-process traces line up to clock-sync precision when
  merged.
"""

from __future__ import annotations

import hashlib
import os
import time

#: Per-process epoch anchor, pinned once at import: wall-clock seconds
#: at this process's monotonic zero.  Dumps carry it so the collector
#: can reason about residual skew between processes.
# graftcheck: disable=OB301 -- the anchor IS the one sanctioned
# wall-minus-monotonic: it converts monotonic instants to an absolute
# timeline at dump time; it is never used as a duration
EPOCH_ANCHOR: float = time.time() - time.monotonic()


def anchored_us(mono_s: float) -> float:
    """A monotonic instant as absolute microseconds on this process's
    anchored timeline (the chrome-trace ``ts`` unit)."""
    return (EPOCH_ANCHOR + mono_s) * 1e6


def trace_id_for(req_id: str) -> str:
    """The trace id of a request — derived, never allocated, so every
    process (and every incarnation across failovers) agrees on it."""
    return hashlib.sha1(req_id.encode()).hexdigest()[:16]


def new_span_id() -> str:
    """A fresh span id.  Random, not derived: the same request may be
    admitted twice (failover resubmit) and each admission's spans must
    stay distinct within the shared trace."""
    return os.urandom(8).hex()
