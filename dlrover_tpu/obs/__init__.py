"""Fleet flight recorder (ISSUE 12): distributed request tracing, a
control-plane event journal, and crash postmortems.

Three layers, all jax-free:

- :mod:`~dlrover_tpu.obs.span` — trace identity (trace_id derived from
  the request id, so a failover resubmit joins the SAME trace with no
  wire coordination) and monotonic-clock spans with per-process epoch
  anchoring (each process pins ``wall - monotonic`` once at import, so
  merged timelines align across processes to clock-sync precision
  without ever measuring durations on the wall clock).
- :mod:`~dlrover_tpu.obs.recorder` — the per-process
  :class:`FlightRecorder`: a bounded ring of structured events (spans +
  control-plane journal entries) spilled as fsync'd JSONL on exit,
  SIGTERM, and chaos crashes (``chaos.on_crash``), and scrapeable live
  over the repo RPC idiom (``ObsScrapeRequest``).  Every ring drop is
  counted, never silent.
- :mod:`~dlrover_tpu.obs.collect` / :mod:`~dlrover_tpu.obs.postmortem`
  — merge per-process dumps by trace_id into one Perfetto-loadable
  chrome trace (``utils/trace_analysis.py`` consumes it for rollups),
  validate span trees, and reconstruct a killed fleet's last seconds.

Enabled by ``DLROVER_TPU_OBS_DIR`` (dump directory; unset = ring-only,
still live-scrapeable).  ``DLROVER_TPU_OBS_PROCESS`` names the process
in dumps and merged traces.
"""

from dlrover_tpu.obs.recorder import (  # noqa: F401
    FlightRecorder,
    configure,
    get_recorder,
    journal,
    record_span,
    reset,
    set_process,
)
from dlrover_tpu.obs.span import (  # noqa: F401
    anchored_us,
    new_span_id,
    trace_id_for,
)
