"""The per-process flight recorder (ISSUE 12).

A bounded ring of structured events — request spans and control-plane
journal entries — that SURVIVES the process's death:

- normal exit: an ``atexit`` hook spills the ring as fsync'd JSONL;
- SIGTERM: a handler (installed only when the process had no handler of
  its own — embedders' handlers are never displaced) spills, restores
  the default disposition, and re-raises;
- chaos crash: :func:`dlrover_tpu.chaos.on_crash` fires the spill
  BEFORE ``os._exit``, naming the injected site in the dump header —
  a chaos kill simulates SIGKILL for every OTHER subsystem (no atexit,
  no finally), but the flight recorder is exactly the black box that
  must survive the crash, so it gets the one pre-exit callback;
- live: any process holding the repo RPC idiom can answer
  ``ObsScrapeRequest`` from :meth:`FlightRecorder.snapshot`.

The ring is bounded (``capacity`` events) because a flight recorder's
job is the LAST seconds, not an archive; every eviction is counted in
``dropped`` and exported — a drop is never silent.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from dlrover_tpu.common.log import logger
from dlrover_tpu.obs.span import EPOCH_ANCHOR, anchored_us, new_span_id

ENV_DIR = "DLROVER_TPU_OBS_DIR"
ENV_PROCESS = "DLROVER_TPU_OBS_PROCESS"
ENV_CAPACITY = "DLROVER_TPU_OBS_CAPACITY"


class FlightRecorder:
    """Bounded, thread-safe ring of span/journal events.

    All public methods are cheap enough for the serving data plane's
    per-request rate (a dict build + deque append under one lock); the
    decision whether a request is traced at all is the gateway's
    head-based sampling, not this class's concern."""

    def __init__(self, capacity: int = 4096, process: str = "",
                 out_dir: Optional[str] = None,
                 clock=time.monotonic):
        self.capacity = int(capacity)
        self.process = process or f"pid{os.getpid()}"
        self.out_dir = out_dir
        self._clock = clock
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self.dropped = 0
        self.spans = 0
        self.events = 0
        self._dumped_reason: Optional[str] = None

    # -- recording --------------------------------------------------------

    def _append_locked(self, rec: Dict[str, Any]) -> None:
        self._seq += 1
        rec["seq"] = self._seq
        if len(self._ring) == self.capacity:
            self.dropped += 1
        self._ring.append(rec)

    def span(self, name: str, cat: str, start_s: float, end_s: float,
             trace_id: str = "", span_id: Optional[str] = None,
             parent: str = "", args: Optional[dict] = None) -> str:
        """Record one completed span (monotonic instants in, anchored
        microseconds stored).  Returns the span id."""
        sid = span_id or new_span_id()
        rec: Dict[str, Any] = {
            "k": "span", "name": name, "cat": cat,
            "ts": round(anchored_us(start_s), 1),
            "dur": round(max(0.0, end_s - start_s) * 1e6, 1),
            "tid": trace_id, "sid": sid,
        }
        if parent:
            rec["psid"] = parent
        if args:
            rec["args"] = args
        with self._mu:
            self.spans += 1
            self._append_locked(rec)
        return sid

    def event(self, kind: str, **fields: Any) -> None:
        """Record one control-plane journal event (reshard transition,
        checkpoint commit verdict, reconcile decision, chaos firing,
        ...).  ``fields`` must be JSON/msgpack-safe scalars/containers."""
        rec: Dict[str, Any] = {
            "k": "ev", "kind": kind,
            "ts": round(anchored_us(self._clock()), 1),
        }
        rec.update(fields)
        with self._mu:
            self.events += 1
            self._append_locked(rec)

    # -- reading ----------------------------------------------------------

    def snapshot(self, since_seq: int = 0
                 ) -> Tuple[List[Dict[str, Any]], int, int]:
        """(events newer than ``since_seq``, lifetime drop count, next
        cursor) — the live-scrape read."""
        with self._mu:
            evs = [dict(r) for r in self._ring
                   if r["seq"] > since_seq]
            return evs, self.dropped, self._seq

    def stats(self) -> Dict[str, int]:
        with self._mu:
            return {"spans": self.spans, "events": self.events,
                    "dropped": self.dropped, "ring": len(self._ring)}

    # -- spilling ---------------------------------------------------------

    def dump_path(self) -> Optional[str]:
        if not self.out_dir:
            return None
        return os.path.join(
            self.out_dir,
            f"flight-{self.process}-{os.getpid()}.jsonl",
        )

    def dump(self, path: Optional[str] = None, reason: str = "exit",
             chaos_site: str = "") -> Optional[str]:
        """Spill the ring as fsync'd JSONL (atomic tmp+rename): a meta
        header line, then every retained event.  Safe to call multiple
        times (each dump rewrites with the current ring — the LAST one
        wins, which is the crash semantics a flight recorder wants).
        Returns the path, or None when no target is configured."""
        path = path or self.dump_path()
        if path is None:
            return None
        with self._mu:
            evs = list(self._ring)
            meta = {
                "k": "meta", "process": self.process,
                "pid": os.getpid(), "anchor": EPOCH_ANCHOR,
                "reason": reason, "chaos_site": chaos_site,
                "dumped_at": round(anchored_us(self._clock()), 1),
                "dropped": self.dropped, "events": len(evs),
            }
            self._dumped_reason = reason
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                f.write(json.dumps(meta) + "\n")
                for rec in evs:
                    f.write(json.dumps(rec) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("flight recorder dump to %s failed: %s",
                           path, e)
            return None
        return path


# ---------------------------------------------------------------------------
# The process-global recorder
# ---------------------------------------------------------------------------

_mu = threading.Lock()
_RECORDER: Optional[FlightRecorder] = None
_hooks_installed = False


def _install_hooks(rec: FlightRecorder) -> None:
    """Exit/crash spill hooks, once per process.  Only when a dump
    directory exists — a ring-only recorder has nothing to spill."""
    global _hooks_installed
    if _hooks_installed or not rec.out_dir:
        return
    _hooks_installed = True

    def _atexit_dump() -> None:
        r = _RECORDER
        if r is not None and r._dumped_reason is None:
            r.dump(reason="exit")

    atexit.register(_atexit_dump)

    from dlrover_tpu import chaos

    def _chaos_dump(site: str, ctx: dict) -> None:
        r = _RECORDER
        if r is not None:
            r.event("chaos.crash", site=site,
                    ctx={k: v for k, v in ctx.items()
                         if isinstance(v, (str, int, float, bool))})
            r.dump(reason="chaos", chaos_site=site)

    chaos.on_crash(_chaos_dump)

    # SIGTERM: spill, then die with the default disposition.  Installed
    # ONLY when the process has no handler (embedders that set their
    # own — the fleet example's clean-stop path — reach the atexit
    # spill instead; displacing their handler would break their
    # shutdown).  Never from a non-main thread (signal.signal raises).
    try:
        if (threading.current_thread() is threading.main_thread()
                and signal.getsignal(signal.SIGTERM)
                == signal.SIG_DFL):
            def _term(signum, frame):
                r = _RECORDER
                if r is not None:
                    r.dump(reason="sigterm")
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _term)
    except (ValueError, OSError) as e:
        logger.debug("obs: SIGTERM hook not installed: %s", e)


def get_recorder() -> FlightRecorder:
    """The process recorder, created on first use from the environment
    (``DLROVER_TPU_OBS_DIR`` / ``_PROCESS`` / ``_CAPACITY``)."""
    global _RECORDER
    rec = _RECORDER
    if rec is not None:
        return rec
    with _mu:
        if _RECORDER is None:
            out_dir = os.environ.get(ENV_DIR) or None
            try:
                cap = int(os.environ.get(ENV_CAPACITY, "") or 4096)
            except ValueError:
                cap = 4096
            _RECORDER = FlightRecorder(
                capacity=cap,
                process=os.environ.get(ENV_PROCESS, ""),
                out_dir=out_dir,
            )
            _install_hooks(_RECORDER)
        return _RECORDER


def configure(out_dir: Optional[str] = None, process: str = "",
              capacity: int = 4096) -> FlightRecorder:
    """Install a fresh process recorder explicitly (tests, embedders).
    Replaces any existing one; the exit hooks always act on the
    CURRENT recorder, so replacement never dangles a hook."""
    global _RECORDER
    with _mu:
        _RECORDER = FlightRecorder(
            capacity=capacity, process=process, out_dir=out_dir,
        )
        _install_hooks(_RECORDER)
        return _RECORDER


def reset() -> None:
    """Drop the process recorder (tests).  The next use re-reads env."""
    global _RECORDER
    with _mu:
        _RECORDER = None


def set_process(name: str) -> None:
    """Name this process in dumps/merged traces (``gw-g0``, ``rep-r1``)
    — later configuration wins, env stays the default."""
    if name:
        get_recorder().process = name


def journal(kind: str, **fields: Any) -> None:
    """Record one control-plane event on the process recorder — the
    one-liner the fleet/reshard/checkpoint/autoscale layers call."""
    get_recorder().event(kind, **fields)


def record_span(name: str, cat: str, start_s: float, end_s: float,
                trace_id: str = "", span_id: Optional[str] = None,
                parent: str = "", args: Optional[dict] = None) -> str:
    """Record one span on the process recorder (hot-path one-liner)."""
    return get_recorder().span(
        name, cat, start_s, end_s, trace_id=trace_id,
        span_id=span_id, parent=parent, args=args,
    )
