"""Merge per-process flight-recorder dumps into one fleet trace.

The collector is pure host code over the JSONL dumps
(:meth:`FlightRecorder.dump`) — run it after an e2e, a chaos run, or a
production incident:

- :func:`build_chrome_trace` emits a Perfetto-loadable chrome trace:
  every span becomes a complete ``"X"`` event (pid = the producing
  process, one lane per trace id, so a request's cross-process path
  reads as one aligned row group), journal events become instants, and
  process-name metadata labels the lanes.
  ``utils/trace_analysis.TraceAnalysis`` consumes the same file for
  busy/hotspot/critical-path rollups.
- :func:`validate_traces` checks each trace's structural law: at least
  one span, exactly one EFFECTIVE terminal (a failover replay may
  legitimately produce a superseded terminal at the dead gateway — the
  collector keeps the last and verifies the duplicates AGREE, which is
  exactly-once evidence, not a violation), and gateway phase spans that
  tile the terminal to within tolerance.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple


def load_dump(path: str) -> Optional[Dict[str, Any]]:
    """One dump file -> {"meta": header dict, "events": [...]}; a torn
    tail line (crash mid-write never happens — dumps are atomic — but
    foreign files might) is skipped, an unreadable file returns None."""
    meta: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("k") == "meta":
                    meta = rec
                else:
                    events.append(rec)
    except OSError:
        return None
    if not meta:
        meta = {"process": os.path.basename(path), "pid": 0}
    return {"meta": meta, "events": events, "path": path}


def load_dir(dump_dir: str) -> List[Dict[str, Any]]:
    """Every ``flight-*.jsonl`` dump under ``dump_dir``."""
    out = []
    for path in sorted(glob.glob(
            os.path.join(dump_dir, "flight-*.jsonl"))):
        d = load_dump(path)
        if d is not None:
            out.append(d)
    return out


def _lane(trace_id: str) -> int:
    """Stable per-trace thread lane (chrome tid) — groups one request's
    spans into one row; 0 is the process-level lane (rounds, events)."""
    if not trace_id:
        return 0
    try:
        h = int(trace_id[:8], 16)
    except ValueError:  # foreign/synthetic trace ids need a lane too
        import zlib

        h = zlib.crc32(trace_id.encode())
    return (h % 100000) + 1


def build_chrome_trace(dumps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Perfetto-loadable chrome trace dict from loaded dumps."""
    events: List[Dict[str, Any]] = []
    for dump in dumps:
        meta = dump["meta"]
        pid = int(meta.get("pid", 0))
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": str(meta.get("process", pid))},
        })
        for rec in dump["events"]:
            if rec.get("k") == "span":
                args = dict(rec.get("args") or {})
                for key in ("tid", "sid", "psid"):
                    if rec.get(key):
                        args[f"trace_{key}" if key == "tid"
                             else key] = rec[key]
                events.append({
                    "ph": "X", "name": rec.get("name", ""),
                    "cat": rec.get("cat", ""),
                    "ts": float(rec.get("ts", 0.0)),
                    "dur": float(rec.get("dur", 0.0)),
                    "pid": pid, "tid": _lane(rec.get("tid", "")),
                    "args": args,
                })
            elif rec.get("k") == "ev":
                events.append({
                    "ph": "i", "s": "p",
                    "name": rec.get("kind", "event"),
                    "ts": float(rec.get("ts", 0.0)),
                    "pid": pid, "tid": 0,
                    "args": {k: v for k, v in rec.items()
                             if k not in ("k", "ts", "seq")},
                })
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(dump_dir: str, out_path: str) -> str:
    """Merge every dump under ``dump_dir`` into a chrome-trace file."""
    with open(out_path, "w") as f:
        json.dump(build_chrome_trace(load_dir(dump_dir)), f)
    return out_path


# ---------------------------------------------------------------------------
# Structural validation
# ---------------------------------------------------------------------------


def spans_by_trace(dumps: List[Dict[str, Any]]
                   ) -> Dict[str, List[Dict[str, Any]]]:
    """trace_id -> its spans across every dump, each annotated with the
    producing process/pid under ``_proc``/``_pid``."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for dump in dumps:
        meta = dump["meta"]
        for rec in dump["events"]:
            if rec.get("k") != "span" or not rec.get("tid"):
                continue
            rec = dict(rec)
            rec["_proc"] = str(meta.get("process", ""))
            rec["_pid"] = int(meta.get("pid", 0))
            out.setdefault(rec["tid"], []).append(rec)
    for spans in out.values():
        spans.sort(key=lambda s: (s.get("ts", 0.0), s.get("seq", 0)))
    return out


def validate_trace(spans: List[Dict[str, Any]]
                   ) -> Dict[str, Any]:
    """Structural report for one trace's merged spans (see module
    docstring for the law being checked)."""
    terminals = [
        s for s in spans
        if (s.get("args") or {}).get("terminal")
    ]
    report: Dict[str, Any] = {
        "spans": len(spans),
        "terminal_spans": len(terminals),
        "complete": False,
        "duplicates_agree": True,
        "superseded_terminals": max(0, len(terminals) - 1),
    }
    if not terminals:
        return report
    # Effective terminal = the last one recorded: an earlier terminal
    # only exists when a kill orphaned an already-answered completion
    # and the journal replay re-recorded it at the adopting gateway.
    terminals.sort(
        key=lambda s: s.get("ts", 0.0) + s.get("dur", 0.0)
    )
    term = terminals[-1]
    targs = term.get("args") or {}
    report["state"] = targs.get("state", "")
    report["terminal_process"] = term.get("_proc", "")
    for a, b in zip(terminals, terminals[1:]):
        aa, ba = a.get("args") or {}, b.get("args") or {}
        if (aa.get("state"), aa.get("tokens")) != \
                (ba.get("state"), ba.get("tokens")):
            report["duplicates_agree"] = False
    # Phase tiling: the gateway's phase spans are contiguous marks on
    # ONE clock, so within the terminal's own process they must sum to
    # the terminal's duration (and the pre-TTFT subset to the measured
    # TTFT) exactly — the merged-trace check allows small float slack.
    pid = term.get("_pid", 0)
    phases = [s for s in spans
              if s.get("cat") == "phase" and s.get("_pid") == pid]
    report["phase_spans"] = len(phases)
    report["phase_sum_us"] = round(
        sum(float(s.get("dur", 0.0)) for s in phases), 1
    )
    report["ttft_phase_sum_us"] = round(
        sum(float(s.get("dur", 0.0)) for s in phases
            if (s.get("args") or {}).get("pre_ttft")), 1
    )
    report["latency_us"] = round(float(term.get("dur", 0.0)), 1)
    ttft_ms = targs.get("ttft_ms")
    if ttft_ms is not None:
        report["ttft_us"] = round(float(ttft_ms) * 1000.0, 1)
    report["complete"] = bool(spans) and report["duplicates_agree"]
    return report


def validate_traces(dumps: List[Dict[str, Any]],
                    tolerance: float = 0.05) -> Dict[str, Any]:
    """Per-trace structural reports plus a fleet summary.  A trace
    passes when it has exactly one effective terminal, agreeing
    duplicates, and phase spans summing to the terminal's measured
    latency (and TTFT) within ``tolerance``."""
    traces = spans_by_trace(dumps)
    reports: Dict[str, Any] = {}
    ok = 0
    for tid_key, spans in traces.items():
        rep = validate_trace(spans)
        rep["phase_sum_ok"] = _within(
            rep.get("phase_sum_us"), rep.get("latency_us"), tolerance
        )
        rep["ttft_sum_ok"] = _within(
            rep.get("ttft_phase_sum_us"), rep.get("ttft_us"),
            tolerance,
        ) if "ttft_us" in rep else True
        rep["ok"] = bool(
            rep["complete"] and rep["terminal_spans"] >= 1
            and rep["phase_sum_ok"] and rep["ttft_sum_ok"]
        )
        ok += rep["ok"]
        reports[tid_key] = rep
    return {
        "traces": reports,
        "total": len(reports),
        "ok": ok,
    }


def _within(a: Optional[float], b: Optional[float],
            tol: float) -> bool:
    if a is None or b is None:
        return False
    if b <= 0:
        return a <= 0
    # Absolute floor: sub-millisecond phases against a sub-millisecond
    # terminal are all float noise — 5% of nothing proves nothing.
    return abs(a - b) <= max(tol * b, 500.0)


def trace_ids_for(req_ids) -> Dict[str, str]:
    """req_id -> trace_id convenience for test assertions."""
    from dlrover_tpu.obs.span import trace_id_for

    return {rid: trace_id_for(rid) for rid in req_ids}
