"""Cross-role policies: decisions no single role can make.

The first one is the ROADMAP item-5 flagship: a sustained serving
queue spike BORROWS a chip from the co-scheduled training role.  Both
directions are drain-first —

- borrow: the TRAINING role drains first (two-phase resize; the PR-6
  live-reshard path moves the leaving ranks' state mesh-to-mesh when
  eligible, the restart ladder otherwise) and the serving role grows
  only after the lender's drain completed — the chip is genuinely free
  before anything new is scheduled onto it;
- hand-back: the SERVING role drains first (the gateway two-phase: the
  borrowed replica stops being granted work, finishes in flight,
  deregisters) and training reclaims only after the drain completed.

Spike/decay detection is hysteretic (patience counters, the
``autoscale.decide`` shape) so a bursty queue cannot flap chips back
and forth, and a cooldown separates consecutive borrows.

:class:`ChipBorrowArbiter` is a registered sim-bound pure policy
(graftcheck DET70x, ISSUE 16): every decision is a function of the
adapters' observed signals and the scripted pass sequence — no
ambient clock, randomness, or I/O reachable from ``step``
(``tests/test_determinism.py`` pins the double-run law).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.fleet.role import RoleAdapter
from dlrover_tpu.obs import journal

IDLE = "idle"
LENDING = "lending"          # lender draining (training reshard/restart)
BORROWED = "borrowed"        # chip moved; serving grew
RECLAIMING = "reclaiming"    # borrower draining (gateway two-phase)


@dataclasses.dataclass
class BorrowPolicy:
    #: Spike: borrower queue depth per alive member above this ...
    queue_high_per_member: float = 8.0
    #: ... for this many consecutive passes.
    spike_patience: int = 3
    #: Decay: queue per member below this ...
    queue_low_per_member: float = 1.0
    #: ... for this many consecutive passes hands the chip back.
    decay_patience: int = 5
    #: Units on loan at once (drains are serialized anyway).
    max_borrow: int = 1
    #: Passes to sit idle after a full borrow/hand-back cycle.
    cooldown_passes: int = 3
    #: GAIN mode (ISSUE 11, draft-vs-target arbitration): when the
    #: arbiter is built with a ``gain_fn`` (a measured earned-value
    #: signal — accepted tokens/round for a draft pool), the borrow
    #: trigger is the signal EXCEEDING ``gain_high`` (the pool is
    #: earning more than a chip costs; typically break-even + margin)
    #: and the hand-back trigger is a MEASURED signal below
    #: ``gain_low`` (typically break-even: below it the chips decode
    #: faster as plain capacity).  An unmeasured signal (0) holds —
    #: silence must not flap chips.
    gain_high: float = 0.0
    gain_low: float = 0.0


class ChipBorrowArbiter:
    """Lender/borrower state machine over the uniform role surface.

    ``signal_fn`` returns the borrower's load view (defaults to the
    borrower's observed signals): needs ``queue_depth`` and the alive
    member count.  ``step`` runs once per fleet pass (wired via
    :meth:`FleetManager.add_cross_policy`)."""

    def __init__(
        self,
        lender: RoleAdapter,
        borrower: RoleAdapter,
        policy: Optional[BorrowPolicy] = None,
        signal_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        gain_fn: Optional[Callable[[], float]] = None,
        scope: str = "",
        hold_fn: Optional[Callable[[], bool]] = None,
    ):
        self.lender = lender
        self.borrower = borrower
        self.policy = policy or BorrowPolicy()
        self._signal_fn = signal_fn
        #: Fleet-level freeze (ISSUE 17): while ``hold_fn`` returns
        #: True no NEW borrow begins (in-flight phases still pump to
        #: completion).  A federation wires this to its blackout view —
        #: a surviving cell absorbing a dead sibling's spillover must
        #: not simultaneously lend its serving chips away.
        self._hold_fn = hold_fn
        #: Cell scope (ISSUE 15): which cell this arbiter actuates in.
        #: A cell-aware loan path wires ``signal_fn`` to the federation
        #: (``FederationTier.borrow_signal_fn``) so the DECISION sees
        #: fleet-wide pressure, while lend/grow/reclaim stay inside
        #: this cell — zero cross-cell coordination on the loan path.
        self.scope = scope
        #: GAIN mode (ISSUE 11): arbitrate by a measured earned-value
        #: signal instead of queue depth — the draft-vs-target split
        #: follows measured tokens/round, not hardware identity (the
        #: VirtualFlow decoupling argument).
        self._gain_fn = gain_fn
        self.phase = IDLE
        self.borrowed = 0
        self._spike_streak = 0
        self._decay_streak = 0
        self._cooldown = 0
        #: Audit trail: (phase_from, phase_to, reason) transitions.
        self.events: List[tuple] = []

    # -- signals ------------------------------------------------------------

    def _held(self) -> bool:
        if self._hold_fn is None:
            return False
        try:
            return bool(self._hold_fn())
        except Exception:  # noqa: BLE001 - a broken freeze signal must
            # fail SAFE (hold): lending into an unknown fleet state is
            # the risky direction.
            return True

    def _signals(self) -> Dict[str, Any]:
        if self._signal_fn is not None:
            return self._signal_fn()
        status = self.borrower.observe()
        sig = dict(status.signals)
        sig.setdefault("members_alive", len(status.members))
        return sig

    def _queue_per_member(self) -> float:
        sig = self._signals()
        alive = max(
            1,
            int(sig.get("members_alive")
                or len(self.borrower.observe().members) or 1),
        )
        return float(sig.get("queue_depth", 0)) / alive

    # -- the pass ------------------------------------------------------------

    def step(self, fleet=None) -> str:
        if self._gain_fn is not None:
            # GAIN mode: spike = the borrower's measured earned value
            # EXCEEDS gain_high (it deserves another chip); decay = a
            # MEASURED value below gain_low (below break-even the chip
            # is worth more back at the lender).  Unmeasured (0) holds
            # every streak — silence must not move chips.
            qpm = float(self._gain_fn() or 0.0)
            metric = "tokens/round"
            high, low = self.policy.gain_high, self.policy.gain_low
            spike = high > 0 and qpm > high
            decay = 0 < qpm < low
        else:
            qpm = self._queue_per_member()
            metric = "queue/member"
            high = self.policy.queue_high_per_member
            low = self.policy.queue_low_per_member
            spike = qpm > high
            decay = qpm < low
        if spike:
            self._spike_streak += 1
            self._decay_streak = 0
        elif decay:
            self._decay_streak += 1
            self._spike_streak = 0
        else:
            self._spike_streak = 0
            self._decay_streak = 0

        if self.phase == IDLE:
            if self._held():
                pass  # frozen: a sibling-cell emergency outranks loans
            elif self._cooldown > 0:
                self._cooldown -= 1
            elif (
                self._spike_streak >= self.policy.spike_patience
                and self.borrowed < self.policy.max_borrow
                # The borrower must have HEADROOM before the lender
                # drains anything: a chip released toward a role
                # already at max_count would be pure waste.
                and self.borrower.spec.desired
                < self.borrower.spec.max_count
                and self.lender.can_lend()
            ):
                if self.lender.lend_one():
                    self._move(
                        LENDING,
                        f"{metric} {qpm:.1f} > {high} for "
                        f"{self._spike_streak} passes",
                    )
                    self._spike_streak = 0
        elif self.phase == LENDING:
            if not self.lender.lend_pending():
                # The lender's drain protocol completed: the chip is
                # free.  Only NOW does the borrower grow onto it.
                if not self.borrower.grow_one():
                    # Headroom vanished while the lender drained (a
                    # concurrent policy grow): don't strand the chip —
                    # hand it straight back.
                    logger.warning(
                        "fleet borrow: borrower %s refused the grow "
                        "(at max?); reclaiming the lent chip",
                        self.borrower.name,
                    )
                    self.lender.reclaim_one()
                    if not getattr(self.lender, "preemptible", False):
                        self._cooldown = self.policy.cooldown_passes
                    self._move(IDLE, "borrower grow refused; reclaimed")
                    return self.phase
                self.borrowed += 1
                self._move(BORROWED, "lender drain complete")
        elif self.phase == BORROWED:
            if self._decay_streak >= self.policy.decay_patience:
                # Hand-back begins with the BORROWER's drain protocol.
                if self.borrower.shrink_one():
                    self._move(
                        RECLAIMING,
                        f"{metric} {qpm:.1f} < {low} for "
                        f"{self._decay_streak} passes",
                    )
                    self._decay_streak = 0
        elif self.phase == RECLAIMING:
            if not self.borrower.drain_pending():
                self.lender.reclaim_one()
                self.borrowed -= 1
                # Cooldown exists to damp loan CHURN — a chip bouncing
                # between two SLO roles.  Reclaiming from a PREEMPTIBLE
                # lender (the offline tier) is not churn: taking back a
                # free chip must never make an online role wait out a
                # cooldown to evict batch work (ISSUE 20 small fix).
                if not getattr(self.lender, "preemptible", False):
                    self._cooldown = self.policy.cooldown_passes
                self._move(IDLE, "borrower drain complete; reclaimed")
        return self.phase

    def _move(self, phase: str, reason: str) -> None:
        logger.info(
            "fleet borrow [%s->%s] %s -> %s: %s",
            self.lender.name, self.borrower.name, self.phase, phase,
            reason,
        )
        self.events.append((self.phase, phase, reason))
        # Loans are the decisions operators second-guess first: every
        # transition is a flight-recorder entry (ISSUE 12).
        journal("fleet.borrow", lender=self.lender.name,
                borrower=self.borrower.name, phase_from=self.phase,
                phase_to=phase, reason=reason,
                borrowed=self.borrowed, cell=self.scope)
        self.phase = phase

    def describe(self) -> Dict[str, Any]:
        return {
            "policy": "chip_borrow",
            "mode": "gain" if self._gain_fn is not None else "queue",
            "lender": self.lender.name,
            "borrower": self.borrower.name,
            "phase": self.phase,
            "borrowed": self.borrowed,
            "cell": self.scope,
            "held": self._held(),
        }


# -- cross-cell chip MOVES (ISSUE 17) ---------------------------------------

MOVE_IDLE = "idle"
MOVE_DRAINING = "draining"   # source cell draining (reshard epoch)


@dataclasses.dataclass
class MovePolicy:
    #: Passes a source drain may take before the move is ABORTED to
    #: the restart ladder (a stuck reshard must not wedge the fleet).
    drain_budget_passes: int = 20
    #: Passes to sit idle after a completed or laddered move —
    #: consecutive moves stay serialized and spaced (the ElasWave
    #: bounded-disruption argument: one reshard wave at a time).
    cooldown_passes: int = 2
    #: Total moves this mover may actuate (0 = unbounded).
    max_moves: int = 0


class CrossCellMover:
    """Actuates federation cross-cell MOVE orders — the PR-15
    remainder: a ``place_roles`` decision finally moves workers
    BETWEEN cells instead of only describing where they should be.

    ``orders_fn`` returns the current move orders (``[(role, src_cell,
    dst_cell, n)]`` — ``FederationTier.plan_cell_moves``); ``cells``
    maps cell_id -> {role: RoleAdapter} (each cell's own adapters,
    pumped by that cell's FleetManager).  One move is in flight at a
    time, drain-first BOTH ways:

    - the SOURCE cell drains first (``lend_one`` — for training this
      is the PR-6/10 two-phase resize through a reshard epoch; for
      serving, the gateway drain protocol), so the chip is genuinely
      free before anything crosses the boundary;
    - only after the source drain completes does the DESTINATION cell
      grow (``grow_one`` — itself confirmed by the destination role's
      own reconcile/spawn-grace machinery).

    Any mid-move failure — the source drain stuck past
    ``drain_budget_passes``, the destination refusing the grow — falls
    back to the RESTART LADDER: ``reclaim_one`` at the source
    re-establishes the pre-move placement through the proven
    checkpoint-restart path, and the event is journaled with
    ``ladder=True``.  Like :class:`ChipBorrowArbiter`, every decision
    is a function of the adapters' observed signals and the scripted
    pass sequence — no ambient clock, randomness, or I/O reachable
    from ``step`` (sim-bound, graftcheck DET70x)."""

    def __init__(
        self,
        orders_fn: Callable[[], List[tuple]],
        cells: Dict[str, Dict[str, RoleAdapter]],
        policy: Optional[MovePolicy] = None,
    ):
        self._orders_fn = orders_fn
        self._cells = cells
        self.policy = policy or MovePolicy()
        self.phase = MOVE_IDLE
        #: The in-flight order, (role, src_cell, dst_cell).
        self.current: Optional[tuple] = None
        self._drain_passes = 0
        self._cooldown = 0
        self.moved = 0
        self.laddered = 0
        #: Audit trail: (phase_from, phase_to, reason) transitions.
        self.events: List[tuple] = []

    def _adapter(self, cell: str, role: str) -> Optional[RoleAdapter]:
        return (self._cells.get(cell) or {}).get(role)

    # -- the pass ------------------------------------------------------------

    def step(self, fleet=None) -> str:
        if self.phase == MOVE_IDLE:
            if self._cooldown > 0:
                self._cooldown -= 1
                return self.phase
            if self.policy.max_moves and self.moved >= self.policy.max_moves:
                return self.phase
            try:
                orders = list(self._orders_fn() or [])
            except Exception as e:  # noqa: BLE001 - federation read may
                # race a dying cell; a missed pass beats a wedged mover
                logger.warning("fleet move: orders fetch failed: %s", e)
                return self.phase
            for role, src, dst, n in orders:
                src_a = self._adapter(src, role)
                dst_a = self._adapter(dst, role)
                if src_a is None or dst_a is None:
                    continue
                if dst_a.spec.desired >= dst_a.spec.max_count:
                    continue
                if not src_a.can_lend():
                    continue
                if src_a.lend_one():
                    self.current = (role, src, dst)
                    self._drain_passes = 0
                    self._move(
                        MOVE_DRAINING,
                        f"order {role}: {src} -> {dst} (want {n}); "
                        f"source draining",
                    )
                    break
            return self.phase
        # MOVE_DRAINING: one order in flight.
        role, src, dst = self.current
        src_a = self._adapter(src, role)
        dst_a = self._adapter(dst, role)
        if src_a is None or dst_a is None:
            # A cell vanished mid-move (blackout): nothing to reclaim
            # against — the restart ladder inside the surviving cell's
            # own reconciler recovers its membership.
            self.laddered += 1
            self._cooldown = self.policy.cooldown_passes
            self._finish(f"cell vanished mid-move ({src} -> {dst})",
                         ladder=True)
            return self.phase
        src_a.pump_drain()
        self._drain_passes += 1
        if src_a.lend_pending():
            if self._drain_passes > self.policy.drain_budget_passes:
                # Stuck reshard/drain: ABORT to the restart ladder —
                # reclaim the unit at the source; its proven
                # checkpoint-restart path re-establishes the pre-move
                # placement.
                src_a.reclaim_one()
                self.laddered += 1
                self._cooldown = self.policy.cooldown_passes
                self._finish(
                    f"source drain stuck after {self._drain_passes} "
                    f"passes; restart ladder reclaimed at {src}",
                    ladder=True,
                )
            return self.phase
        # The source drain completed: the chip is free — only NOW does
        # the destination cell grow onto it.
        if not dst_a.grow_one():
            src_a.reclaim_one()
            self.laddered += 1
            self._cooldown = self.policy.cooldown_passes
            self._finish(
                f"destination {dst} refused the grow (at max?); "
                f"restart ladder reclaimed at {src}",
                ladder=True,
            )
            return self.phase
        # The unit left the source cell for GOOD: release its on-loan
        # hold so the source's ordinary policy resumes post-move.
        src_a.confirm_departure()
        self.moved += 1
        self._cooldown = self.policy.cooldown_passes
        self._finish(f"move complete: one {role} unit {src} -> {dst}")
        return self.phase

    def _finish(self, reason: str, ladder: bool = False) -> None:
        self._move(MOVE_IDLE, reason, ladder=ladder)
        self.current = None
        self._drain_passes = 0

    def _move(self, phase: str, reason: str,
              ladder: bool = False) -> None:
        role, src, dst = self.current or ("", "", "")
        logger.info(
            "fleet move [%s: %s->%s] %s -> %s: %s",
            role, src, dst, self.phase, phase, reason,
        )
        self.events.append((self.phase, phase, reason))
        # Cross-cell moves are the most operator-visible decisions the
        # federation makes: every transition is a flight-recorder
        # entry, ladder fallbacks flagged.
        journal("fleet.move", role=role, src=src, dst=dst,
                phase_from=self.phase, phase_to=phase, reason=reason,
                moved=self.moved, ladder=ladder)
        self.phase = phase

    def describe(self) -> Dict[str, Any]:
        role, src, dst = self.current or ("", "", "")
        return {
            "policy": "cross_cell_move",
            "phase": self.phase,
            "role": role, "src": src, "dst": dst,
            "moved": self.moved,
            "laddered": self.laddered,
        }
