"""One role/fleet control plane for training, serving, gateway and
embedding roles (ISSUE 10 / ROADMAP item 5).

- :mod:`dlrover_tpu.fleet.role` — the contract: :class:`RoleSpec`
  (desired count, floors/ceilings, relaunch budget),
  :class:`RoleStatus` (one observation), :class:`RoleAdapter` (spawn /
  observe / drain-first shrink / borrow surface).
- :mod:`dlrover_tpu.fleet.roles` — the four families migrated onto
  it: training workers (the allreduce scaler's optimizer walk +
  live-reshard hold run unchanged), serving replicas (single-gateway
  or merged multi-gateway tier view, per-role sub-pools), gateways as
  a SUPERVISED role (registry-leased health, relaunch under the same
  id re-adopts the dead ring ranges), embedding stores.
- :mod:`dlrover_tpu.fleet.manager` — :class:`FleetManager`, the
  reconciler pumping every role once per pass, then the cross-role
  policies; :func:`build_job_fleet` composes one for a mixed
  ElasticJob.
- :mod:`dlrover_tpu.fleet.policy` — :class:`ChipBorrowArbiter`: a
  sustained serving-queue spike borrows a chip from training,
  drain-first in both directions.
- :mod:`dlrover_tpu.fleet.registry` — role-family factories: how
  ``distribution_strategy`` resolves to a scaler.

Everything here is jax-free pure control plane.
"""

from dlrover_tpu.fleet.manager import (  # noqa: F401
    FleetManager,
    build_job_fleet,
)
from dlrover_tpu.fleet.policy import (  # noqa: F401
    BorrowPolicy,
    ChipBorrowArbiter,
    CrossCellMover,
    MovePolicy,
)
from dlrover_tpu.fleet.registry import (  # noqa: F401
    register_role_family,
    resolve_job_scaler,
    role_families,
)
from dlrover_tpu.fleet.role import (  # noqa: F401
    RoleAdapter,
    RoleSpec,
    RoleStatus,
)
from dlrover_tpu.fleet.roles import (  # noqa: F401
    DraftRole,
    EmbeddingRole,
    GatewayRole,
    ServingReplicaRole,
    TrainingRole,
)
