"""The four role families, migrated onto the fleet contract.

Each adapter WRAPS its family's existing machinery rather than
re-deriving it — the training scaler's optimizer walk, live-reshard
hold and shrink-only live gating; the serving drain two-phase; the
embedding group resize; the gateway registry lease — so every behavior
those components already prove in their own test suites flows through
the fleet layer unchanged.

- :class:`TrainingRole` — wraps :class:`AllreduceTrainingAutoScaler`.
  Its reconcile IS the scaler's pass; lending a chip goes through the
  scaler's two-phase resize (live-reshard shrink when eligible, the
  restart ladder otherwise) so a borrow can never bypass the epoch
  protocol.
- :class:`ServingReplicaRole` — replicas behind a gateway-shaped
  actuator (a single ``GatewayCore`` or the tier-wide
  :class:`~dlrover_tpu.serving.tier.TierActuator` over the MERGED
  snapshot).  Shrink is the drain-first two-phase; per-role sub-pools
  (prefill/decode) ride ``decide_pools``.
- :class:`GatewayRole` — gateways as a SUPERVISED role (ROADMAP 4a):
  membership is the leased registry, a dead gateway is relaunched
  UNDER ITS OWN ID so the replacement re-adopts exactly the dead hash
  ranges, and graceful shrink deregisters before stopping.
- :class:`EmbeddingRole` — the host-side embedding-store group; resize
  rebalances shards via the embedding router's consistent hashing, so
  drain is the count drop itself (watched to completion).
- :class:`OfflineRole` — the preemptible offline tier (ISSUE 20): the
  first NON-SLO family, virtual capacity (zero borrow bid), drain =
  the runner's instant-reclaim contract (one decode round, preempt
  youngest, chunk requeued).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.fleet.role import RoleAdapter, RoleSpec, RoleStatus


class TrainingRole(RoleAdapter):
    """Training workers as a fleet role.

    ``scaler`` is an :class:`AllreduceTrainingAutoScaler` (already
    wired to the job manager, speed monitor, optimizer and reshard
    manager); its ``scale_once`` pass — backfill, optimizer walk,
    live-reshard two-phase hold — runs unchanged as this role's
    reconcile.  While chips are LENT to another role the ordinary
    policy is held (the optimizer would fight the borrow by re-growing)
    and only the in-flight resize is pumped."""

    def __init__(self, spec: RoleSpec, scaler, job_manager):
        super().__init__(spec)
        self._scaler = scaler
        self._job_manager = job_manager
        self._drain_target: Optional[int] = None
        self.lent = 0

    def observe(self) -> RoleStatus:
        alive = tuple(
            f"w{n.rank_index}" for n in self._job_manager.alive_workers()
        )
        pending = tuple(
            f"w{n.rank_index}" for n in self._job_manager.pending_workers()
        )
        signals: Dict[str, Any] = {"lent": self.lent}
        speed = getattr(self._scaler, "_speed_monitor", None)
        if speed is not None:
            signals["speed"] = speed.running_speed()
        return RoleStatus(members=alive, pending=pending, signals=signals)

    def reconcile(self) -> int:
        if self.lent > 0:
            # Chips on loan: hold the ordinary grow/shrink policy and
            # only pump the in-flight two-phase resize (epoch DONE ->
            # release surplus workers; ABORT -> restart ladder).
            return self._scaler.pump()
        return self._scaler.scale_once()

    # -- drain / borrow surface --------------------------------------------

    def spawn(self, n: int) -> int:
        status = self.observe()
        return self._job_manager.scale_workers_to(status.live + n)

    def begin_drain(self) -> Optional[str]:
        status = self.observe()
        unit = self._scaler.node_unit
        target = status.live - unit
        if target < self.spec.min_count:
            return None
        if not self._scaler.request_resize(target):
            return None
        self._drain_target = target
        return f"resize->{target}"

    def drain_pending(self) -> bool:
        if self._drain_target is None:
            return False
        if self._scaler.resize_pending:
            return True
        if self.observe().live > self._drain_target:
            return True
        self._drain_target = None
        return False

    def pump_drain(self) -> None:
        self._scaler.pump()

    def can_lend(self) -> bool:
        return (
            not self.drain_pending()
            and self.observe().live - self._scaler.node_unit
            >= self.spec.min_count
        )

    def lend_one(self) -> bool:
        """Drain-first chip release: the two-phase resize (live-reshard
        when eligible — survivors move the leaving ranks' state
        mesh-to-mesh before any process dies).  One "unit" is the
        job's node_unit (TPU slices are all-or-nothing)."""
        if self.begin_drain() is None:
            return False
        self.spec.desired = max(
            self.spec.min_count,
            self.spec.desired - self._scaler.node_unit,
        )
        self.lent += 1
        return True

    def reclaim_one(self) -> bool:
        if self.lent <= 0:
            return False
        self.lent -= 1
        self.spec.desired = self.spec.clamp(
            self.spec.desired + self._scaler.node_unit
        )
        self._job_manager.scale_workers_to(self.spec.desired)
        return True

    def confirm_departure(self) -> None:
        """The lent unit moved to another CELL for good (ISSUE 17):
        drop the on-loan hold so :meth:`reconcile` resumes the
        ordinary policy at the post-move size — a permanent move must
        not freeze the source cell's autoscaling forever."""
        if self.lent > 0:
            self.lent -= 1


class ServingReplicaRole(RoleAdapter):
    """Serving replicas as a fleet role.

    ``actuator`` is gateway-shaped — ``stats_snapshot`` /
    ``pick_drain_victim`` / ``drain`` — which a single
    :class:`GatewayCore` satisfies directly and the tier-wide
    :class:`~dlrover_tpu.serving.tier.TierActuator` satisfies over the
    MERGED multi-gateway view (ROADMAP 4b: provisioning decisions read
    the whole tier, drains broadcast to every gateway).

    ``spawn_fn(n, role=None)`` provisions replicas (the job manager in
    a supervised fleet, a thread/subprocess spawner in benches and
    e2e); ``release_fn(victim)`` runs after a drained victim fully
    deregistered (phase B bookkeeping — e.g. lowering the worker
    target, which by then kills nobody live)."""

    def __init__(
        self,
        spec: RoleSpec,
        actuator,
        spawn_fn: Callable[..., Any],
        policy=None,
        pool_policies: Optional[Dict[str, Any]] = None,
        release_fn: Optional[Callable[[str], Any]] = None,
    ):
        super().__init__(spec)
        from dlrover_tpu.serving.autoscale import ScalePolicy, ScaleState

        self._actuator = actuator
        self._spawn_fn = spawn_fn
        self._release_fn = release_fn
        self._policy = policy or ScalePolicy(
            min_replicas=max(1, spec.min_count),
            max_replicas=max(1, spec.max_count),
        )
        self._state = ScaleState()
        self._pool_policies = dict(pool_policies or {})
        self._pool_states: Dict[str, Any] = {}
        self._drain_victim: Optional[str] = None
        #: Spawns not yet visible as registered replicas, with a
        #: deadline after which the spawn is presumed lost.
        self._expected: list = []
        #: One tier fan-out per reconcile pass: drain_pending, observe
        #: and policy_target all read the SAME snapshot (an actuator
        #: over a registry pays one RPC per live gateway per fetch).
        #: The snapshot is kept until the NEXT pass refreshes it, so
        #: cross-role policies running after the roles (the borrow
        #: arbiter's observe/grow calls) reuse this pass's fan-out too
        #: — at most one pass of staleness, by construction.
        self._pass_snap: Optional[Dict[str, Any]] = None

    def reconcile(self) -> int:
        self._pass_snap = self._actuator.stats_snapshot()
        return super().reconcile()

    def _snapshot(self) -> Dict[str, Any]:
        if self._pass_snap is not None:
            return self._pass_snap
        return self._actuator.stats_snapshot()

    # -- observation --------------------------------------------------------

    def observe(self) -> RoleStatus:
        snap = self._snapshot()
        replicas = snap.get("replicas", {})
        members = tuple(
            rid for rid, r in replicas.items() if not r.get("draining")
        )
        draining = tuple(
            rid for rid, r in replicas.items() if r.get("draining")
        )
        now = time.monotonic()
        # Under the role lock: status() (the servicer's FleetStats
        # read) calls observe concurrently with the fleet thread's
        # spawn — an unguarded rebuild could drop fresh spawn
        # deadlines and over-provision on the next pass.
        with self._mu:
            self._expected = [
                d for d in self._expected if d > now
            ][: max(0, self.spec.desired - len(members))]
            pending = tuple(
                f"pending-{i}" for i in range(len(self._expected))
            )
        return RoleStatus(
            members=members,
            pending=pending,
            draining=draining,
            signals={
                "queue_depth": snap.get("queue_depth", 0),
                "occupancy": snap.get("occupancy", 0.0),
                "ttft_p95_ms": snap.get("ttft_p95_ms", 0.0),
                "pools": snap.get("pools", {}),
                "gateways": snap.get("gateways", 1),
            },
        )

    def policy_target(self, status: RoleStatus) -> Optional[int]:
        from dlrover_tpu.serving.autoscale import decide, decide_pools

        snap = self._snapshot()
        if self._pool_policies:
            # Per-role sub-pools (the PoolAutoScaler arithmetic): each
            # pool gets its own decision; the ROLE target is their sum
            # and pool-level grow/drain is actuated here directly.
            targets = decide_pools(
                snap, self._pool_policies, self._pool_states
            )
            pools = snap.get("pools", {})
            for role, target in targets.items():
                alive = int(pools.get(role, {}).get("alive", 0))
                if target > alive:
                    self._spawn_fn(target - alive, role=role)
                elif target < alive and not self.drain_pending():
                    victim = self._actuator.pick_drain_victim(role=role)
                    if victim is not None:
                        self._actuator.drain(victim)
                        self._drain_victim = victim
            return None  # pool path actuates itself
        return decide(snap, self._policy, self._state)

    # -- actuation ----------------------------------------------------------

    def spawn(self, n: int) -> int:
        deadline = time.monotonic() + self.spec.spawn_grace_s
        with self._mu:
            self._expected.extend([deadline] * n)
        self._spawn_fn(n)
        return n

    def begin_drain(self) -> Optional[str]:
        if self._drain_victim is not None:
            return None
        victim = self._actuator.pick_drain_victim()
        if victim is None:
            return None
        self._actuator.drain(victim)
        self._drain_victim = victim
        logger.info("fleet[%s]: draining replica %s", self.name, victim)
        return victim

    def drain_pending(self) -> bool:
        if self._drain_victim is None:
            return False
        snap = self._snapshot()
        if self._drain_victim in snap.get("replicas", {}):
            return True
        victim, self._drain_victim = self._drain_victim, None
        if self._release_fn is not None:
            try:
                self._release_fn(victim)
            except Exception:
                logger.exception(
                    "fleet[%s]: release of %s failed", self.name, victim
                )
        logger.info(
            "fleet[%s]: drain of %s complete", self.name, victim
        )
        return False

    def pump_drain(self) -> None:
        self.drain_pending()


class DraftRole(RoleAdapter):
    """Draft replicas as the FIFTH role family (ISSUE 11): small
    speculation proposal servers (``serving.draft``) behind the same
    gateway-shaped actuator the serving role uses.

    The role's own policy is the EARNED-VALUE signal: the acceptance
    its proposals win at the spec targets (the gateway snapshot's
    ``pools["draft"]["tokens_per_round"]``, measured at the CONSUMERS).
    A MEASURED value below ``break_even`` sustained ``low_patience``
    passes shrinks the pool toward its floor — below break-even a
    draft chip decodes more tokens as plain target capacity, so the
    role hands it back (the :class:`~dlrover_tpu.fleet.policy.
    ChipBorrowArbiter` in gain mode drives the cross-role half).
    Growth is driven from outside (the arbiter's reclaim/borrow, or an
    operator raising ``desired``) — an unmeasured signal never grows a
    pool speculatively.  Shrink is the serving drain two-phase: the
    draft deregisters, spec targets detach on their next poll and
    degrade to plain decode mid-request (speculation is an
    optimization, never a dependency)."""

    def __init__(
        self,
        spec: RoleSpec,
        actuator,
        spawn_fn: Callable[..., Any],
        break_even: float = 3.3,
        low_patience: int = 3,
        release_fn: Optional[Callable[[str], Any]] = None,
    ):
        super().__init__(spec)
        self._actuator = actuator
        self._spawn_fn = spawn_fn
        self.break_even = float(break_even)
        self.low_patience = max(1, int(low_patience))
        self._release_fn = release_fn
        self._low_streak = 0
        self._drain_victim: Optional[str] = None
        self._expected: list = []
        self._pass_snap: Optional[Dict[str, Any]] = None

    def reconcile(self) -> int:
        self._pass_snap = self._actuator.stats_snapshot()
        return super().reconcile()

    def _snapshot(self) -> Dict[str, Any]:
        if self._pass_snap is not None:
            return self._pass_snap
        return self._actuator.stats_snapshot()

    def observe(self) -> RoleStatus:
        snap = self._snapshot()
        replicas = snap.get("replicas", {})
        members = tuple(
            rid for rid, r in replicas.items()
            if r.get("role") == "draft" and not r.get("draining")
        )
        draining = tuple(
            rid for rid, r in replicas.items()
            if r.get("role") == "draft" and r.get("draining")
        )
        now = time.monotonic()
        with self._mu:
            self._expected = [
                d for d in self._expected if d > now
            ][: max(0, self.spec.desired - len(members))]
            pending = tuple(
                f"pending-{i}" for i in range(len(self._expected))
            )
        pool = snap.get("pools", {}).get("draft", {})
        counters = snap.get("counters", {})
        return RoleStatus(
            members=members,
            pending=pending,
            draining=draining,
            signals={
                "tokens_per_round": pool.get("tokens_per_round", 0.0),
                "spec_fallbacks": counters.get("spec_fallbacks", 0),
                "spec_rounds": counters.get("spec_rounds", 0),
            },
        )

    def policy_target(self, status: RoleStatus) -> Optional[int]:
        tpr = float(status.signals.get("tokens_per_round", 0.0))
        if 0 < tpr < self.break_even and status.members:
            self._low_streak += 1
            if self._low_streak >= self.low_patience:
                self._low_streak = 0
                return self.spec.desired - 1
        else:
            self._low_streak = 0
        return None

    def spawn(self, n: int) -> int:
        deadline = time.monotonic() + self.spec.spawn_grace_s
        with self._mu:
            self._expected.extend([deadline] * n)
        self._spawn_fn(n, role="draft")
        return n

    def begin_drain(self) -> Optional[str]:
        if self._drain_victim is not None:
            return None
        victim = self._actuator.pick_drain_victim(role="draft")
        if victim is None:
            return None
        self._actuator.drain(victim)
        self._drain_victim = victim
        logger.info("fleet[%s]: draining draft %s", self.name, victim)
        return victim

    def drain_pending(self) -> bool:
        if self._drain_victim is None:
            return False
        snap = self._snapshot()
        if self._drain_victim in snap.get("replicas", {}):
            return True
        victim, self._drain_victim = self._drain_victim, None
        if self._release_fn is not None:
            try:
                self._release_fn(victim)
            except Exception:
                logger.exception(
                    "fleet[%s]: release of %s failed", self.name, victim
                )
        logger.info(
            "fleet[%s]: drain of draft %s complete", self.name, victim
        )
        return False

    def pump_drain(self) -> None:
        self.drain_pending()


class GatewayRole(RoleAdapter):
    """Gateways as a SUPERVISED role (ROADMAP 4a).

    Membership is the leased ``ServeRegistry``: a gateway that stops
    heartbeating ages out of the registry and the reconciler replaces
    it — under the SAME gateway id, so the replacement's virtual nodes
    land exactly on the dead gateway's hash ranges and the ring heals
    to its pre-death shape (clients and replicas re-route within one
    lease either way).

    ``spawn_fn(gid)`` launches one gateway process (job manager node,
    subprocess, or thread); ``stop_fn(gid)`` gracefully stops one for
    scale-down (deregister first — the registry entry vanishing IS the
    drain completion signal, after which no client routes to it)."""

    def __init__(
        self,
        spec: RoleSpec,
        registry,
        spawn_fn: Callable[[str], Any],
        stop_fn: Optional[Callable[[str], Any]] = None,
        id_prefix: str = "gw",
    ):
        super().__init__(spec)
        self.registry = registry
        self._spawn_fn = spawn_fn
        self._stop_fn = stop_fn
        self._id_prefix = id_prefix
        #: Every id this role ever launched (dead ones are relaunch
        #: candidates; ids, not processes, are the stable identity).
        self._known: list = []
        #: gid -> spawn deadline while the announce is awaited.
        self._spawning: Dict[str, float] = {}
        self._drain_gid: Optional[str] = None
        self._drain_deadline = 0.0
        #: Seconds for a graceful stop to take effect (entry gone from
        #: the registry) before the drain is ABANDONED — a stop_fn that
        #: cannot actually stop the process (or the default
        #: registry-only removal racing a live heartbeat) must not
        #: wedge the whole role's reconciliation forever.
        self.drain_timeout_s = 30.0

    def observe(self) -> RoleStatus:
        live = self.registry.gateways()
        now = time.monotonic()
        # Under the role lock: the servicer's status() observe races
        # the fleet thread's spawn bookkeeping on _known/_spawning.
        with self._mu:
            # Adopted members (announced by someone else) become
            # relaunch candidates too: identity is the id, not who
            # launched it.
            for gid in live:
                if gid not in self._known:
                    self._known.append(gid)
            for gid in list(self._spawning):
                if gid in live or self._spawning[gid] <= now:
                    self._spawning.pop(gid, None)
            pending = tuple(self._spawning)
        draining = (
            (self._drain_gid,)
            if self._drain_gid is not None and self._drain_gid in live
            else ()
        )
        members = tuple(g for g in live if g not in draining)
        return RoleStatus(
            members=members,
            pending=pending,
            draining=draining,
            signals={"addrs": dict(live)},
        )

    def spawn(self, n: int) -> int:
        live = set(self.registry.gateways())
        launched = 0
        for _ in range(n):
            with self._mu:
                live |= set(self._spawning)
                gid = self._pick_id(live)
                live.add(gid)
                if gid not in self._known:
                    self._known.append(gid)
                self._spawning[gid] = (
                    time.monotonic() + self.spec.spawn_grace_s
                )
            logger.info("fleet[%s]: launching gateway %s", self.name, gid)
            try:
                self._spawn_fn(gid)
                launched += 1
            except Exception:
                logger.exception(
                    "fleet[%s]: gateway %s spawn failed", self.name, gid
                )
                with self._mu:
                    self._spawning.pop(gid, None)
        return launched

    def _pick_id(self, live) -> str:
        # Dead known ids first: the replacement re-adopts the dead
        # gateway's ring ranges (same id = same vnodes).  Budget-
        # blocked ids are never picked — relaunching the crash-looper
        # would defeat the budget AND starve a healthy slot's
        # replacement.
        for gid in self._known:
            if gid not in live and gid not in self._blocked:
                return gid
        k = len(self._known)
        while f"{self._id_prefix}{k}" in live \
                or f"{self._id_prefix}{k}" in self._blocked:
            k += 1
        return f"{self._id_prefix}{k}"

    def begin_drain(self) -> Optional[str]:
        status = self.observe()
        if not status.members or self._drain_gid is not None:
            return None
        gid = sorted(status.members)[-1]
        self._drain_gid = gid
        self._drain_deadline = time.monotonic() + self.drain_timeout_s
        try:
            if self._stop_fn is not None:
                self._stop_fn(gid)
            else:
                # Best-effort without a stop hook: deregister so
                # clients re-route.  A LIVE gateway will re-announce on
                # its next heartbeat — the drain then times out below
                # rather than wedging the role (provide a stop_fn for
                # a real graceful shrink).
                self.registry.remove_gateway(gid)
        except Exception:
            logger.exception(
                "fleet[%s]: gateway %s stop failed", self.name, gid
            )
        return gid

    def drain_pending(self) -> bool:
        if self._drain_gid is None:
            return False
        if self._drain_gid in self.registry.gateways():
            if time.monotonic() > self._drain_deadline:
                logger.error(
                    "fleet[%s]: gateway %s still announcing %.0fs "
                    "after its drain began (stop_fn missing or "
                    "ineffective); ABANDONING the drain so the role "
                    "keeps reconciling",
                    self.name, self._drain_gid, self.drain_timeout_s,
                )
                self._drain_gid = None
                return False
            return True
        self._drain_gid = None
        return False

    def pump_drain(self) -> None:
        self.drain_pending()


class EmbeddingRole(RoleAdapter):
    """Host-side embedding-store servers as a fleet role.  The store
    group rebalances shards by consistent hashing on ANY resize, so
    the drain protocol is the resize itself, watched to completion."""

    def __init__(self, spec: RoleSpec, job_manager,
                 node_type: str = NodeType.EMBEDDING):
        super().__init__(spec)
        self._job_manager = job_manager
        self._node_type = node_type
        self._drain_target: Optional[int] = None

    def observe(self) -> RoleStatus:
        alive = tuple(
            f"e{n.rank_index}"
            for n in self._job_manager.alive_nodes_of(self._node_type)
        )
        pending = tuple(
            f"e{n.rank_index}"
            for n in self._job_manager.pending_nodes_of(self._node_type)
        )
        return RoleStatus(members=alive, pending=pending)

    def spawn(self, n: int) -> int:
        status = self.observe()
        return self._job_manager.scale_role_to(
            self._node_type, status.live + n
        )

    def begin_drain(self) -> Optional[str]:
        status = self.observe()
        target = status.live - 1
        if target < self.spec.min_count:
            return None
        self._job_manager.scale_role_to(self._node_type, target)
        self._drain_target = target
        return f"resize->{target}"

    def drain_pending(self) -> bool:
        if self._drain_target is None:
            return False
        if self.observe().live > self._drain_target:
            return True
        self._drain_target = None
        return False


class OfflineRole(RoleAdapter):
    """The preemptible offline tier as a fleet role (ISSUE 20).

    The sixth family and the first NON-SLO one.  Its capacity is
    *virtual*: ``observe`` always reports ``queue_depth: 0`` (the real
    backlog rides a separate ``offline_backlog`` signal the borrow
    arbiter never reads), so an arbiter with this role as the borrower
    can never spike a loan on batch pressure — every chip it holds was
    idle by construction.  ``preemptible = True`` is what exempts
    reclaims FROM this role from the arbiter's cooldown.

    ``workers_fn()`` returns the live worker handles in SPAWN ORDER
    (worker_id -> handle with the :class:`OfflineRunner` surface:
    ``running``, ``busy``, ``request_reclaim()``); ``spawn_fn(n)``
    launches ``n`` more workers.  The drain protocol IS the runner's
    instant-reclaim contract: ``begin_drain`` preempts the YOUNGEST
    worker (least sunk chunk cost, mirroring the paged arena's
    admission law) via ``request_reclaim()``, and the drain is
    complete when that worker's loop has exited — at most one decode
    round later, the hard bound the tier-1 loopback test clocks."""

    preemptible = True

    def __init__(
        self,
        spec: RoleSpec,
        workers_fn: Callable[[], Dict[str, Any]],
        spawn_fn: Callable[[int], int],
        queue=None,
        policy=None,
        idle_chips_fn: Optional[Callable[[], int]] = None,
        speed_weight: float = 1.0,
    ):
        super().__init__(spec)
        self._workers_fn = workers_fn
        self._spawn_fn = spawn_fn
        self._queue = queue
        self._policy = policy
        self._idle_chips_fn = idle_chips_fn
        self.speed_weight = float(speed_weight)
        self._drain_wid: Optional[str] = None

    def observe(self) -> RoleStatus:
        workers = self._workers_fn()
        members = tuple(
            wid for wid, w in workers.items()
            if getattr(w, "running", True)
        )
        backlog = self._queue.backlog() if self._queue is not None else 0
        busy = sum(
            1 for wid in members if getattr(workers[wid], "busy", False)
        )
        return RoleStatus(
            members=members,
            draining=(
                (self._drain_wid,)
                if self._drain_wid is not None
                and self._drain_wid in members else ()
            ),
            signals={
                # Zero bid, ALWAYS: batch backlog is not pressure and
                # must never pull a chip from an SLO-bearing role.
                "queue_depth": (
                    self._policy.borrow_bid()
                    if self._policy is not None else 0
                ),
                "offline_backlog": backlog,
                "busy_workers": busy,
            },
        )

    def spawn(self, n: int) -> int:
        try:
            return int(self._spawn_fn(n))
        except Exception:
            logger.exception(
                "fleet[%s]: offline worker spawn failed", self.name
            )
            return 0

    def begin_drain(self) -> Optional[str]:
        if self._drain_wid is not None:
            return None
        workers = self._workers_fn()
        running = [
            wid for wid, w in workers.items()
            if getattr(w, "running", True)
        ]
        if not running:
            return None
        # Preempt-youngest: the newest worker holds the chunk with the
        # least sunk decode cost (its abandoned chunk requeues intact).
        wid = running[-1]
        workers[wid].request_reclaim()
        self._drain_wid = wid
        return wid

    def drain_pending(self) -> bool:
        if self._drain_wid is None:
            return False
        workers = self._workers_fn()
        w = workers.get(self._drain_wid)
        if w is not None and getattr(w, "running", False):
            return True
        self._drain_wid = None
        return False

    def pump_drain(self) -> None:
        self.drain_pending()

    def can_lend(self) -> bool:
        """ALWAYS willing while anything runs: a preemptible role has
        no floor worth defending against an SLO-bearing claimant."""
        return self.drain_pending() is False and bool(
            self.observe().members
        )

    def policy_target(self, status: RoleStatus) -> Optional[int]:
        if self._policy is None or self._idle_chips_fn is None:
            return None
        # Idle supply EXCLUDES chips this role already holds: the
        # target is sized against what the online roles left over.
        idle = int(self._idle_chips_fn())
        return self._policy.target_workers(
            idle_chips=idle + len(status.members),
            backlog_chunks=int(
                status.signals.get("offline_backlog", 0)
            ),
            online_pressure=self._drain_wid is not None,
            speed_weight=self.speed_weight,
        )
