"""FleetManager: one reconciler supervising N heterogeneous roles.

The fleet pass is deliberately boring — that is the point.  Each role
adapter owns its family's machinery (the training scaler's optimizer
walk and live-reshard hold, the serving drain two-phase, the gateway
registry lease); the manager just pumps every role once per interval
and then runs the cross-role policies (the borrow arbiter) over the
uniform surface.  Nothing here knows what a worker, replica or gateway
*is* — which is exactly what lets a single ElasticJob run all of them.

The manager also duck-types the :class:`JobAutoScaler` interface
(``start_auto_scaling`` / ``stop_auto_scaling``) so the master can slot
it where a single-role scaler goes today.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.log import logger
from dlrover_tpu.fleet.role import RoleAdapter
from dlrover_tpu.obs import journal


class FleetManager:
    def __init__(self, interval: Optional[float] = None):
        self._roles: Dict[str, RoleAdapter] = {}
        self._policies: List[Any] = []  # objects with .step(fleet)
        self._interval = interval or get_context().scale_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        #: Audit trail of reconcile outcomes: (pass_no, role, delta).
        self.events: List[tuple] = []
        self._passes = 0

    # -- composition --------------------------------------------------------

    def add_role(self, adapter: RoleAdapter) -> RoleAdapter:
        with self._mu:
            if adapter.name in self._roles:
                raise ValueError(f"role {adapter.name!r} already added")
            self._roles[adapter.name] = adapter
        logger.info(
            "fleet: role %s added (desired=%d, [%d, %d])",
            adapter.name, adapter.spec.desired,
            adapter.spec.min_count, adapter.spec.max_count,
        )
        return adapter

    def role(self, name: str) -> RoleAdapter:
        with self._mu:
            return self._roles[name]

    def roles(self) -> Dict[str, RoleAdapter]:
        with self._mu:
            return dict(self._roles)

    def add_cross_policy(self, policy) -> Any:
        """A cross-role policy: ``step(fleet)`` once per pass, AFTER
        every role reconciled (it sees a current view and its
        desired-count movements take effect next pass)."""
        with self._mu:
            self._policies.append(policy)
        return policy

    # -- the pass ------------------------------------------------------------

    def reconcile_once(self) -> Dict[str, int]:
        """One fleet pass; returns role -> applied delta."""
        deltas: Dict[str, int] = {}
        with self._mu:
            roles = list(self._roles.items())
            policies = list(self._policies)
            self._passes += 1
            n = self._passes
        for name, adapter in roles:
            try:
                delta = int(adapter.reconcile() or 0)
            except Exception:
                logger.exception("fleet: role %s reconcile failed", name)
                delta = 0
            deltas[name] = delta
            if delta:
                with self._mu:
                    self.events.append((n, name, delta))
                # Every applied reconcile decision lands in the flight
                # recorder (ISSUE 12): a postmortem must show WHY the
                # fleet moved, next to what it did to requests.
                journal("fleet.reconcile", role=name, delta=delta,
                        reconcile_pass=n,
                        desired=adapter.spec.desired)
        for policy in policies:
            try:
                policy.step(self)
            except Exception:
                logger.exception(
                    "fleet: cross-role policy %s failed",
                    type(policy).__name__,
                )
        return deltas

    # -- views ---------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """Fleet summary (the servicer's ``FleetStatsRequest`` body)."""
        out: Dict[str, Any] = {"roles": {}, "policies": []}
        for name, adapter in self.roles().items():
            try:
                out["roles"][name] = adapter.summary()
            except Exception as e:  # noqa: BLE001 - a sick role must not
                # blind the whole fleet view
                out["roles"][name] = {"error": str(e)}
        with self._mu:
            for policy in self._policies:
                desc = getattr(policy, "describe", None)
                out["policies"].append(
                    desc() if callable(desc) else type(policy).__name__
                )
        return out

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="fleet-manager", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # JobAutoScaler duck surface: the master can treat the fleet
    # manager exactly like a single-role scaler.
    start_auto_scaling = start
    stop_auto_scaling = stop

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.reconcile_once()
            except Exception:
                logger.exception("fleet reconcile pass failed")


def build_job_fleet(
    job_args,
    job_manager,
    auto_scaler,
    kv_store=None,
    gateway_spawn_fn=None,
) -> Optional[FleetManager]:
    """Compose a FleetManager for a MIXED ElasticJob (a ``gateway``
    node group beside the workers, or an embedding fleet riding a
    training job).  Returns ``None`` for plain single-role jobs — the
    master then runs the resolved scaler directly, exactly as before
    this layer existed.

    The training role wraps the already-built ``auto_scaler`` (the
    same object, so starting the fleet INSTEAD of the scaler thread
    never double-actuates); the gateway role rides the serve registry
    in the master's own KV store (``serve/{job}/gw/...`` — where tier
    gateways already announce), spawning via ``gateway_spawn_fn`` or
    the job manager's gateway node group."""
    from dlrover_tpu.common.constants import NodeType
    from dlrover_tpu.fleet.roles import GatewayRole, TrainingRole
    from dlrover_tpu.master.job_auto_scaler import (
        AllreduceTrainingAutoScaler,
    )
    from dlrover_tpu.fleet.role import RoleSpec

    gw_group = job_args.node_groups.get(NodeType.GATEWAY)
    if gw_group is None or gw_group.count <= 0 or kv_store is None:
        return None
    fleet = FleetManager()
    if isinstance(auto_scaler, AllreduceTrainingAutoScaler):
        workers = job_args.workers
        fleet.add_role(TrainingRole(
            RoleSpec(
                name="training",
                desired=workers.count,
                min_count=workers.min_count,
                max_count=workers.max_count,
            ),
            auto_scaler, job_manager,
        ))
    from dlrover_tpu.serving.tier import ServeRegistry

    registry = ServeRegistry(kv_store, job=job_args.job_name)
    gw_role = GatewayRole(
        RoleSpec(
            name="gateway",
            desired=gw_group.count,
            min_count=gw_group.min_count,
            max_count=gw_group.max_count,
            relaunch_limit=gw_group.restart_count,
        ),
        registry, gateway_spawn_fn or (lambda gid: None),
        id_prefix="gw",
    )
    if gateway_spawn_fn is None:
        # Platform spawn is COUNT-idempotent: ask the job manager for
        # the role's desired node count (the process-level relaunch
        # ladder owns per-node replacement; the registry lease owns
        # announce-level health).  A per-gid spawn here would grow
        # platform nodes unboundedly while a sick gateway process
        # never announces.
        def _spawn(gid, _jm=job_manager, _role=gw_role):
            _jm.scale_role_to(NodeType.GATEWAY, _role.spec.desired)

        # Graceful shrink actually STOPS a process: drop the platform
        # node count by one (highest rank — matching the role's pick
        # of the highest-sorted gid); registry-only removal would race
        # the live gateway's heartbeat and time the drain out.
        def _stop(gid, _jm=job_manager):
            live = len(_jm.alive_nodes_of(NodeType.GATEWAY)) + len(
                _jm.pending_nodes_of(NodeType.GATEWAY)
            )
            _jm.scale_role_to(NodeType.GATEWAY, max(0, live - 1))

        gw_role._spawn_fn = _spawn
        gw_role._stop_fn = _stop
    fleet.add_role(gw_role)
    return fleet
