"""Role model: the one contract every fleet member family implements.

ISSUE 10 / ROADMAP item 5: the master/agent tree special-cased training
workers (``dist_job_manager`` filtering on ``NodeType.WORKER``) versus
serving replicas (``ServingFleetAutoScaler`` bolted beside
``JobAutoScaler``) versus embedding servers — so no single ElasticJob
could run a mixed fleet and nothing could reason across roles.  This
module is the decoupling VirtualFlow (2009.09523) argues for: a *role*
is what runs (training worker, serving replica, gateway, embedding
store), the hardware beneath is fungible, and every family exposes the
SAME lifecycle to the reconciler:

    spawn -> observe (health) -> drain (role's own protocol) ->
    release -> relaunch

The surface is deliberately small and synchronous — adapters are
polled by the :class:`~dlrover_tpu.fleet.manager.FleetManager` pass
(the shape every scaler in this repo already uses: signals in, one
decision out, actuation elsewhere) — and every resize, in ANY role, is
a first-class drain-aware event (ElasWave 2510.00606): growth spawns,
shrink ALWAYS goes through :meth:`RoleAdapter.begin_drain` /
:meth:`RoleAdapter.drain_pending` so no role's in-flight work observes
the change.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional, Tuple

from dlrover_tpu.common.log import logger


@dataclasses.dataclass
class RoleSpec:
    """Desired shape of one role inside the fleet.

    ``desired`` is the reconciler's set-point: supervision restores the
    observed member count to it, per-role autoscale policies and the
    cross-role borrow arbiter MOVE it (always within
    ``[min_count, max_count]``).  ``relaunch_limit`` bounds supervised
    replacements per member id — a member that keeps dying stops being
    respawned (and is logged), exactly like the node relaunch budget in
    the job manager."""

    name: str
    desired: int = 1
    min_count: int = 0
    max_count: int = 64
    relaunch_limit: int = 3
    #: Seconds a spawned member may stay unobserved before the
    #: reconciler treats the spawn as lost and tries again.
    spawn_grace_s: float = 30.0
    #: Consecutive passes a member deficit must persist before
    #: supervision spawns a replacement.  1 = react immediately; roles
    #: whose membership view can FLICKER (a serving replica's gateway
    #: lease lapsing for one poll during tier churn) set 2-3 so a
    #: transient blip does not add real capacity.
    spawn_confirm_passes: int = 1

    def clamp(self, n: int) -> int:
        return max(self.min_count, min(self.max_count, int(n)))


@dataclasses.dataclass
class RoleStatus:
    """One observation of a role: who is alive, who is still coming up,
    who is on the way out, plus the role's load signals (queue depth,
    occupancy, speed — whatever its policy consumes)."""

    members: Tuple[str, ...] = ()
    pending: Tuple[str, ...] = ()
    draining: Tuple[str, ...] = ()
    signals: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def live(self) -> int:
        """Members counted against ``desired``: alive + on their way
        up.  Draining members are already spoken for (they leave when
        their drain completes) and never count as capacity."""
        return len(self.members) + len(self.pending)


class RoleAdapter:
    """Base adapter: the lifecycle primitives plus a generic
    reconcile pass built from them.

    Subclasses implement :meth:`observe`, :meth:`spawn` and the drain
    trio; families with richer native machinery (the training scaler's
    optimizer walk + live-reshard hold) override :meth:`reconcile`
    wholesale and keep their exact semantics — the uniform model is the
    *contract*, not a rewrite of every policy.

    The borrow surface (:meth:`can_lend` / :meth:`lend_one` /
    :meth:`lend_pending` / :meth:`reclaim_one`) is what cross-role
    policies drive; the defaults ride the same drain path so a borrow
    can never bypass a role's drain protocol."""

    #: Priority class (ISSUE 20).  ``True`` marks a NON-SLO role whose
    #: capacity is virtual: it bids zero for chips, drains within one
    #: decode round when reclaimed, and taking chips BACK from it costs
    #: the borrow arbiter no cooldown (evicting batch work is not loan
    #: churn).  SLO-bearing roles stay ``False``.
    preemptible = False

    def __init__(self, spec: RoleSpec):
        self.spec = spec
        self._mu = threading.Lock()
        #: member id -> supervised relaunch count (budget enforcement).
        self._relaunches: Dict[str, int] = {}
        #: member ids whose relaunch budget is spent: while such an id
        #: stays dead the role runs degraded instead of thrashing.
        self._blocked: set = set()
        self._last_seen: Tuple[str, ...] = ()
        self._deficit_streak = 0
        #: Members observed gone while a deficit is still being
        #: CONFIRMED (spawn_confirm_passes > 1): the budget is charged
        #: on the pass that actually spawns, not the pass that first
        #: noticed — and a blip that heals on its own charges nobody.
        self._pending_gone: list = []

    @property
    def name(self) -> str:
        return self.spec.name

    # -- primitives every role implements ---------------------------------

    def observe(self) -> RoleStatus:
        raise NotImplementedError

    def spawn(self, n: int) -> int:
        """Ask for ``n`` more members; returns how many were actually
        requested (budget / platform limits may bite)."""
        raise NotImplementedError

    def begin_drain(self) -> Optional[str]:
        """Start the role's drain protocol on ONE member (or one
        resize unit).  Returns a token identifying the drain (usually
        the member id) or ``None`` when nothing is eligible.  Shrinks
        are serialized: one drain in flight per role."""
        raise NotImplementedError

    def drain_pending(self) -> bool:
        """A previously begun drain has not completed yet.  While true
        the reconciler holds every other decision for this role (the
        two-phase pattern the serving scaler pioneered)."""
        return False

    def pump_drain(self) -> None:
        """Advance an in-flight drain (poll completion, release the
        freed resources).  Called once per reconcile pass while
        :meth:`drain_pending`."""

    # -- borrow surface (cross-role policies) ------------------------------

    def can_lend(self) -> bool:
        """One unit could leave without violating the floor."""
        return self.observe().live - 1 >= self.spec.min_count

    def lend_one(self) -> bool:
        """Begin a drain-first release of one unit for another role's
        benefit.  Default: the ordinary shrink path."""
        return self.shrink_one()

    def lend_pending(self) -> bool:
        return self.drain_pending()

    def reclaim_one(self) -> bool:
        """Take a previously lent unit back (the hand-back direction)."""
        return self.grow_one()

    def confirm_departure(self) -> None:
        """A lent unit left PERMANENTLY (a cross-cell move, ISSUE 17):
        unlike a loan there is no hand-back to wait for — the role
        stops treating the unit as on-loan and its ordinary policy
        resumes at the new, smaller desired count.  Default: no-op
        (roles without loan bookkeeping have nothing to release)."""

    # -- desired-count movements ------------------------------------------

    def grow_one(self) -> bool:
        target = self.spec.clamp(self.spec.desired + 1)
        if target == self.spec.desired:
            return False
        self.spec.desired = target
        status = self.observe()
        if status.live < target:
            self.spawn(target - status.live)
        return True

    def shrink_one(self) -> bool:
        target = self.spec.clamp(self.spec.desired - 1)
        if target == self.spec.desired or self.drain_pending():
            return False
        if self.begin_drain() is None:
            return False
        self.spec.desired = target
        return True

    # -- per-role autoscale policy ----------------------------------------

    def policy_target(self, status: RoleStatus) -> Optional[int]:
        """This role's own autoscale opinion for the pass (None = no
        opinion).  The generic reconcile moves ``desired`` toward it."""
        return None

    # -- the generic pass --------------------------------------------------

    def reconcile(self) -> int:
        """One supervision + policy pass; returns the applied member
        delta (0 while holding)."""
        if self.drain_pending():
            self.pump_drain()
            return 0
        status = self.observe()
        gone = self._note_seen(status)
        # 1) Supervision: dead members are replaced toward desired
        # (drain removals already lowered desired, so this never
        # resurrects a drained member).
        if status.live < self.spec.desired:
            self._deficit_streak += 1
            self._pending_gone.extend(
                m for m in gone if m not in self._pending_gone
            )
            if self._deficit_streak < self.spec.spawn_confirm_passes:
                return 0
            want = self.spec.desired - status.live
            charged, self._pending_gone = tuple(self._pending_gone), []
            allowed = self._budgeted(charged, status, want)
            if allowed > 0:
                self._deficit_streak = 0
                logger.info(
                    "fleet[%s]: %d live < %d desired; spawning %d",
                    self.name, status.live, self.spec.desired, allowed,
                )
                return self.spawn(allowed)
            return 0
        self._deficit_streak = 0
        self._pending_gone.clear()  # the blip healed; nobody charged
        # 2) Per-role policy.
        target = self.policy_target(status)
        if target is None:
            return 0
        target = self.spec.clamp(target)
        if target > self.spec.desired:
            self.spec.desired = target
            if status.live < target:
                return self.spawn(target - status.live)
        elif target < self.spec.desired:
            self.shrink_one()  # serialized, drain-first
        return 0

    # -- relaunch budget ---------------------------------------------------

    def _note_seen(self, status: RoleStatus) -> Tuple[str, ...]:
        """Track live membership across passes; returns the members
        that vanished (not via a drain) since the last observation —
        the ones a supervision spawn would be replacing."""
        with self._mu:
            gone = tuple(
                m for m in self._last_seen
                if m not in status.members and m not in status.draining
            )
            self._last_seen = status.members
            return gone

    def _budgeted(self, gone: Tuple[str, ...], status: RoleStatus,
                  want: int) -> int:
        """Charge supervised replacements against the per-member
        relaunch budget.  A member id over budget is BLOCKED: while it
        stays dead the role runs degraded (one fewer spawn) rather
        than thrashing a relaunch loop — only meaningful for id-stable
        roles (gateways relaunch under their own id); id-fresh roles
        never re-kill a blocked id, so nothing accumulates."""
        with self._mu:
            for member in gone:
                count = self._relaunches.get(member, 0) + 1
                self._relaunches[member] = count
                if (
                    count > self.spec.relaunch_limit
                    and member not in self._blocked
                ):
                    logger.error(
                        "fleet[%s]: member %s exceeded relaunch budget "
                        "(%d); not replacing it",
                        self.name, member, self.spec.relaunch_limit,
                    )
                    self._blocked.add(member)
            dead_blocked = sum(
                1 for m in self._blocked
                if m not in status.members and m not in status.draining
            )
            return max(0, want - dead_blocked)

    # -- views --------------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        status = self.observe()
        return {
            "desired": self.spec.desired,
            "members": sorted(status.members),
            "pending": len(status.pending),
            "draining": sorted(status.draining),
            "signals": dict(status.signals),
            "drain_pending": self.drain_pending(),
        }
