"""Role-family registry: how a job's strategy resolves to a scaler.

``new_job_auto_scaler`` used to be a hard-coded if-chain over
``distribution_strategy`` — adding a role family meant editing the
master.  Factories now register here (the built-in four at
``master.job_auto_scaler`` import time) and resolution is a lookup,
so an out-of-tree role family plugs in the same way a chaos site or a
bench subcommand does.

A factory is ``f(job_args, job_manager, speed_monitor, *,
resource_optimizer=None, serving_gateway=None, reshard_manager=None)
-> JobAutoScaler``.  Unknown strategies fall back to the default
(training) family with a loud log — a typo'd strategy must not crash
a master at boot, same contract as the gatewayless serving fallback.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from dlrover_tpu.common.log import logger

DEFAULT_FAMILY = "allreduce"

_FAMILIES: Dict[str, Callable] = {}


def register_role_family(strategy: str, factory: Callable,
                         replace: bool = False) -> None:
    """Register ``factory`` for ``distribution_strategy == strategy``.
    Re-registering without ``replace=True`` raises — two families
    silently fighting over a strategy is exactly the bug this registry
    exists to prevent."""
    if not replace and strategy in _FAMILIES \
            and _FAMILIES[strategy] is not factory:
        raise ValueError(
            f"role family {strategy!r} already registered"
        )
    _FAMILIES[strategy] = factory


def role_families() -> Tuple[str, ...]:
    _ensure_builtin()
    return tuple(sorted(_FAMILIES))


def resolve_job_scaler(job_args, job_manager, speed_monitor, **kw):
    """Resolve ``job_args.distribution_strategy`` through the registry
    and build the scaler."""
    _ensure_builtin()
    strategy = getattr(job_args, "distribution_strategy", DEFAULT_FAMILY)
    factory = _FAMILIES.get(strategy)
    if factory is None:
        logger.error(
            "unknown distribution_strategy %r (registered: %s); "
            "falling back to the %r role family",
            strategy, sorted(_FAMILIES), DEFAULT_FAMILY,
        )
        factory = _FAMILIES[DEFAULT_FAMILY]
    return factory(job_args, job_manager, speed_monitor, **kw)


def _ensure_builtin() -> None:
    """The built-in families register when ``master.job_auto_scaler``
    imports; pull it in if resolution runs first."""
    if DEFAULT_FAMILY not in _FAMILIES:
        from dlrover_tpu.master import job_auto_scaler  # noqa: F401
