"""Elastic samplers for SPMD training.

Parity with reference ``trainer/torch/elastic/sampler.py:25``
(``ElasticDistributedSampler``): a deterministic index partition over the
*current* world that (a) re-partitions transparently when the world is
re-formed after a membership change and (b) checkpoints its position so a
restore continues exactly where training stopped — no sample is seen twice
or skipped within an epoch.

SPMD note (why this exists alongside the dynamic ``IndexShardingClient``):
under ``jit`` every process must step in lockstep, so the per-step data
partition must be *statically balanced* across processes.  The dynamic task
manager is the right tool for independent-worker input (recommendation/PS
style); this sampler is the right tool for the collective data plane.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional

import numpy as np


@dataclasses.dataclass
class SamplerState:
    epoch: int
    completed_steps: int  # steps completed in this epoch


class ElasticSampler:
    """Deterministic, shardable, checkpointable index sampler.

    Each epoch shuffles ``dataset_size`` indices with ``seed + epoch`` (same
    on every process), pads to a multiple of the *global* batch, then yields
    this process's slice of each global batch: process ``p`` of ``P`` with
    per-process batch ``b`` owns columns ``[p*b, (p+1)*b)`` of every global
    batch.  Re-sharding after elasticity = constructing a new sampler with
    the new (num_processes, process_id) and the restored state.
    """

    def __init__(
        self,
        dataset_size: int,
        *,
        batch_size_per_process: int,
        num_processes: int = 1,
        process_id: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ):
        self.dataset_size = dataset_size
        self.batch_size_per_process = batch_size_per_process
        self.num_processes = num_processes
        self.process_id = process_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.completed_steps = 0

    @property
    def global_batch_size(self) -> int:
        return self.batch_size_per_process * self.num_processes

    def steps_per_epoch(self) -> int:
        if self.drop_last:
            return self.dataset_size // self.global_batch_size
        return -(-self.dataset_size // self.global_batch_size)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        order = np.arange(self.dataset_size, dtype=np.int64)
        if self.shuffle:
            np.random.RandomState(self.seed + epoch).shuffle(order)
        if not self.drop_last:
            pad = (-len(order)) % self.global_batch_size
            if pad:
                order = np.concatenate([order, order[:pad]])
        return order

    def __iter__(self) -> Iterator[np.ndarray]:
        """Yield this process's index slice of each remaining global batch in
        the current epoch."""
        order = self._epoch_order(self.epoch)
        gb = self.global_batch_size
        b = self.batch_size_per_process
        start = self.completed_steps
        for step in range(start, self.steps_per_epoch()):
            gbatch = order[step * gb : (step + 1) * gb]
            if len(gbatch) < gb and self.drop_last:
                break
            lo = self.process_id * b
            # Position advances when the batch is handed out, so a
            # state_dict() taken after the consumer finishes the step
            # includes it (checkpoint-after-step semantics); crash recovery
            # restores from the checkpointed state, not this live counter.
            self.completed_steps = step + 1
            yield gbatch[lo : lo + b]
        self.epoch += 1
        self.completed_steps = 0

    # -- checkpoint (reference sampler state_dict/load_state_dict) ----------
    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "completed_steps": self.completed_steps,
            "seed": self.seed,
            "dataset_size": self.dataset_size,
        }

    def load_state_dict(self, state: dict) -> None:
        self.epoch = int(state.get("epoch", 0))
        self.completed_steps = int(state.get("completed_steps", 0))
        self.seed = int(state.get("seed", self.seed))

    def reshard(self, num_processes: int, process_id: int) -> "ElasticSampler":
        """New sampler over the re-formed world, preserving position.

        The epoch order is world-independent, so the resume point is exact
        as long as the *global* batch size is preserved — adjust
        ``batch_size_per_process`` accordingly (the ``ElasticTrainer`` keeps
        global batch fixed via grad accumulation instead, reference
        ``trainer.py:181``)."""
        s = ElasticSampler(
            self.dataset_size,
            batch_size_per_process=self.global_batch_size // num_processes,
            num_processes=num_processes,
            process_id=process_id,
            shuffle=self.shuffle,
            seed=self.seed,
            drop_last=self.drop_last,
        )
        s.epoch = self.epoch
        s.completed_steps = self.completed_steps
        return s
