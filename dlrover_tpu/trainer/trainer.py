"""Full training-loop SDK: eval, LR schedules, callbacks, checkpoint cadence.

Parity with the reference ``AtorchTrainer``
(``atorch/trainer/atorch_trainer.py:142``: train loop + evaluate + LR
scheduler resume + callback dispatch + save cadence, and its
``TrainingArgs``/``TrainerState``/``TrainerCallback`` surface modeled on
the HF trainer).  TPU-native shape: the step itself is the pjit'd
function built by :class:`~dlrover_tpu.trainer.elastic.ElasticTrainer`
(global batch preserved under elasticity); the LR schedule is an optax
step-indexed schedule living *inside* the optimizer state, so restoring
the flash checkpoint resumes the schedule exactly; eval is a second jit
over the same sharded params.  Kill-and-restore goes through the flash
checkpoint engine: params/opt-state from shm or storage, sampler position
and trainer counters from the checkpoint's meta.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from dlrover_tpu.common.log import logger
from dlrover_tpu.trainer.elastic import ElasticTrainer, TrainerConfig


# ---------------------------------------------------------------------------
# Arguments / state / control
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TrainingArgs:
    """The knobs of the loop (reference ``AtorchTrainingArgs``)."""

    # batch & elasticity
    global_batch_size: int = 32
    max_micro_batch_per_proc: int = 32
    # duration: max_steps wins if > 0, else num_epochs
    max_steps: int = 0
    num_epochs: int = 1
    # optimizer / schedule
    learning_rate: float = 3e-4
    lr_schedule: str = "cosine"  # cosine | linear | constant
    warmup_steps: int = 0
    min_lr_ratio: float = 0.1
    weight_decay: float = 0.0
    max_grad_norm: float = 0.0  # 0 = no clipping
    # cadences (steps; 0 disables)
    logging_steps: int = 10
    eval_steps: int = 0
    save_steps: int = 0
    # checkpointing
    ckpt_dir: str = ""
    job_name: str = ""  # shm-arena namespace; derived from ckpt_dir if ""
    persist_every_n_saves: int = 1  # 1 = every save goes to storage
    # eval micro batch (defaults to the train micro batch)
    eval_batch_per_proc: int = 0
    # misc
    seed: int = 0
    early_stopping_patience: int = 0  # evals w/o improvement; 0 = off
    greater_is_better: bool = False  # for the eval metric
    # Parameter layouts from the cost-model planner (axis->dim search,
    # parallel/layout_planner.py) instead of the ZeRO-3 heuristic.
    layout_planner: bool = False
    # Per-op runtime metrics (utils/op_metrics.py, the xpu-timer
    # analogue): capture a jax-profiler trace of one step every N steps
    # and feed step percentiles + op-class fractions to the master's
    # diagnosis chain. 0 = off.
    op_metrics_every: int = 0


@dataclasses.dataclass
class TrainerState:
    """Loop counters + history (reference ``TrainerState``); checkpointed
    via the flash-ckpt meta so restores resume cadences correctly."""

    step: int = 0
    epoch: int = 0
    samples_seen: int = 0
    best_metric: Optional[float] = None
    evals_since_best: int = 0
    saves: int = 0
    log_history: List[dict] = dataclasses.field(default_factory=list)

    def to_meta(self) -> dict:
        return {
            "step": self.step,
            "epoch": self.epoch,
            "samples_seen": self.samples_seen,
            "best_metric": self.best_metric,
            "evals_since_best": self.evals_since_best,
            "saves": self.saves,
        }

    def load_meta(self, meta: dict) -> None:
        self.step = int(meta.get("step", 0))
        self.epoch = int(meta.get("epoch", 0))
        self.samples_seen = int(meta.get("samples_seen", 0))
        bm = meta.get("best_metric")
        self.best_metric = None if bm is None else float(bm)
        self.evals_since_best = int(meta.get("evals_since_best", 0))
        self.saves = int(meta.get("saves", 0))


@dataclasses.dataclass
class TrainerControl:
    should_stop: bool = False
    should_save: bool = False
    should_evaluate: bool = False


class TrainerCallback:
    """Hook surface (reference ``TrainerCallback`` dispatch in
    ``atorch_trainer.py``).  Every hook may mutate ``control``."""

    def on_train_begin(self, args, state, control) -> None: ...

    def on_step_end(self, args, state, control, metrics: dict) -> None: ...

    def on_log(self, args, state, control, logs: dict) -> None: ...

    def on_evaluate(self, args, state, control, metrics: dict) -> None: ...

    def on_save(self, args, state, control) -> None: ...

    def on_epoch_end(self, args, state, control) -> None: ...

    def on_train_end(self, args, state, control) -> None: ...


class LoggingCallback(TrainerCallback):
    def on_log(self, args, state, control, logs) -> None:
        logger.info(
            "step %d | %s",
            state.step,
            " ".join(
                f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in logs.items()
            ),
        )


class EarlyStoppingCallback(TrainerCallback):
    """Stop after ``args.early_stopping_patience`` evals w/o improvement."""

    def on_evaluate(self, args, state, control, metrics) -> None:
        if args.early_stopping_patience <= 0:
            return
        if state.evals_since_best >= args.early_stopping_patience:
            logger.info(
                "early stop: no improvement in %d evals",
                state.evals_since_best,
            )
            control.should_stop = True


# ---------------------------------------------------------------------------
# Optimizer / schedule factory
# ---------------------------------------------------------------------------


def build_lr_schedule(args: TrainingArgs, total_steps: int):
    """Warmup + decay as an optax step-indexed schedule.  Because the
    schedule is a pure function of the optimizer's internal count, a
    restored checkpoint resumes it exactly (reference: the LR-scheduler
    state_dict save/load dance in ``atorch_trainer.py``)."""
    import optax

    peak = args.learning_rate
    floor = peak * args.min_lr_ratio
    decay_steps = max(1, total_steps - args.warmup_steps)
    if args.lr_schedule == "constant":
        decay = optax.constant_schedule(peak)
    elif args.lr_schedule == "linear":
        decay = optax.linear_schedule(peak, floor, decay_steps)
    elif args.lr_schedule == "cosine":
        decay = optax.cosine_decay_schedule(
            peak, decay_steps, alpha=args.min_lr_ratio
        )
    else:
        raise ValueError(f"unknown lr_schedule {args.lr_schedule!r}")
    if args.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, peak, args.warmup_steps)
        return optax.join_schedules([warmup, decay], [args.warmup_steps])
    return decay


def build_optimizer(args: TrainingArgs, total_steps: int):
    """AdamW + schedule (+ optional global-norm clipping)."""
    import optax

    schedule = build_lr_schedule(args, total_steps)
    tx = optax.adamw(
        learning_rate=schedule, weight_decay=args.weight_decay
    )
    if args.max_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(args.max_grad_norm), tx)
    return tx, schedule


# ---------------------------------------------------------------------------
# The trainer
# ---------------------------------------------------------------------------


class Trainer:
    """The full loop over the elastic core.

    ``fetch_batch(indices) -> batch pytree`` feeds training;
    ``eval_fetch`` (same contract) feeds :meth:`evaluate`.  The optimizer
    defaults to AdamW with the scheduled LR; pass ``optimizer_fn``
    (schedule -> optax tx) to customize while keeping schedule resume.
    """

    def __init__(
        self,
        *,
        loss_fn: Callable,
        init_fn: Callable,
        args: TrainingArgs,
        fetch_batch: Callable[[np.ndarray], Any],
        dataset_size: int,
        eval_fetch: Optional[Callable[[np.ndarray], Any]] = None,
        eval_dataset_size: int = 0,
        optimizer_fn: Optional[Callable[[Any], Any]] = None,
        strategy: Any = None,
        callbacks: Sequence[TrainerCallback] = (),
        master_client=None,
        step_reporter: Optional[Callable[[int], None]] = None,
        devices=None,
        num_processes: int = 1,
        process_id: int = 0,
        frozen: Any = None,
    ):
        self.args = args
        self.loss_fn = loss_fn
        self.init_fn = init_fn
        # Non-trained base tree (LoRA): rides the train state, reaches
        # loss_fn as ``frozen=``; excluded from checkpoints (saving a 7B
        # base per factor-save would defeat flash checkpointing) and
        # re-attached from the live state on restore.
        self.frozen = frozen
        self.eval_fetch = eval_fetch
        self.eval_dataset_size = eval_dataset_size
        self.client = master_client
        self.step_reporter = step_reporter
        self.state = TrainerState()
        self.control = TrainerControl()
        self.callbacks: List[TrainerCallback] = [LoggingCallback()]
        self.callbacks += list(callbacks)
        if args.early_stopping_patience > 0:
            self.callbacks.append(EarlyStoppingCallback())
        if args.op_metrics_every > 0:
            from dlrover_tpu.utils.op_metrics import OpMetricsCallback

            self.callbacks.append(
                OpMetricsCallback(
                    capture_every=args.op_metrics_every,
                    report_every=args.op_metrics_every,
                    master_client=master_client,
                )
            )

        total = self.total_steps(dataset_size)
        if optimizer_fn is not None:
            self.schedule = build_lr_schedule(args, total)
            tx = optimizer_fn(self.schedule)
        else:
            tx, self.schedule = build_optimizer(args, total)
        self.optimizer = tx

        strategy_cache = None
        if master_client is not None:
            # Persist winning strategies with the master: a worker
            # relaunched on a fresh host skips the search mid-recovery.
            from dlrover_tpu.parallel.strategy_search import (
                MasterStrategyCache,
            )

            strategy_cache = MasterStrategyCache(master_client)
        self.core = ElasticTrainer(
            TrainerConfig(
                global_batch_size=args.global_batch_size,
                max_micro_batch_per_proc=args.max_micro_batch_per_proc,
            ),
            loss_fn=loss_fn,
            init_fn=init_fn,
            optimizer=tx,
            fetch_batch=fetch_batch,
            dataset_size=dataset_size,
            strategy=strategy,
            sampler_seed=args.seed,
            devices=devices,
            strategy_cache=strategy_cache,
            param_specs="planner" if args.layout_planner else None,
            frozen=frozen,
        )
        self._num_processes = num_processes
        self._process_id = process_id
        self._ckpt = None
        self._eval_step = None
        self._eval_step_job = None
        self._sampler_restored = False
        if args.ckpt_dir:
            import hashlib

            from dlrover_tpu.checkpoint.checkpointer import (
                FlashCheckpointer,
            )

            # Namespace the shm staging arena by the checkpoint dir, so
            # two jobs (or two tests) on one host never share state.
            job = args.job_name or "t" + hashlib.sha1(
                args.ckpt_dir.encode()
            ).hexdigest()[:10]
            self._ckpt = FlashCheckpointer(
                args.ckpt_dir, job_name=job, master_client=master_client
            )

    # -- sizing --------------------------------------------------------------
    def total_steps(self, dataset_size: int) -> int:
        if self.args.max_steps > 0:
            return self.args.max_steps
        per_epoch = max(1, dataset_size // self.args.global_batch_size)
        return per_epoch * max(1, self.args.num_epochs)

    @property
    def steps_per_epoch(self) -> int:
        return max(
            1, self.core.dataset_size // self.args.global_batch_size
        )

    # -- checkpoint ----------------------------------------------------------
    def _restore(self) -> bool:
        self._sampler_restored = False
        if self._ckpt is None:
            return False
        live_frozen = (
            self.core.state.pop("frozen", None)
            if self.frozen is not None else None
        )
        restored = self._ckpt.load(target=self.core.state)
        if live_frozen is not None:
            self.core.state["frozen"] = live_frozen
        if restored is None:
            return False
        ckpt_state, meta = restored
        if live_frozen is not None:
            # Checkpoints hold the factor tree only; the frozen base
            # stays the live (device-resident) copy.
            ckpt_state = dict(ckpt_state, frozen=live_frozen)
        self.core.state = ckpt_state
        self.state.load_meta(meta.get("trainer", {}))
        if meta.get("sampler") and self.core.sampler is not None:
            self.core.sampler.load_state_dict(meta["sampler"])
            self._sampler_restored = True
        logger.info(
            "trainer: restored step %d (epoch %d)",
            self.state.step, self.state.epoch,
        )
        return True

    def save(self, storage: Optional[bool] = None) -> None:
        if self._ckpt is None:
            return
        self.state.saves += 1
        if storage is None:
            storage = (
                self.args.persist_every_n_saves <= 1
                or self.state.saves % self.args.persist_every_n_saves == 0
            )
        meta = {
            "step": self.state.step,
            "trainer": self.state.to_meta(),
            "sampler": (
                self.core.sampler.state_dict() if self.core.sampler else {}
            ),
        }
        to_save = self.core.state
        if self.frozen is not None:
            # Factor-tree checkpoints: the frozen base is config, not
            # training progress — a LoRA save must cost KBs, not the 7B
            # base per save.
            to_save = {
                k: v for k, v in to_save.items() if k != "frozen"
            }
        self._ckpt.save(to_save, meta=meta, storage=storage)
        for cb in self.callbacks:
            cb.on_save(self.args, self.state, self.control)

    # -- eval ----------------------------------------------------------------
    def _build_eval_step(self):
        import jax

        job = self.core.job
        # Rebuilt whenever the elastic core re-forms the mesh — a cached
        # jit pinned to the old shardings would reject (or reference
        # departed devices of) the new world's batches.
        if self._eval_step is not None and self._eval_step_job is job:
            return
        self._eval_step_job = job

        has_frozen = self.frozen is not None

        def eval_loss(state, batch):
            if has_frozen:
                return self.loss_fn(
                    state["params"], batch, frozen=state["frozen"]
                )
            return self.loss_fn(state["params"], batch)

        self._eval_step = jax.jit(
            eval_loss,
            in_shardings=(job.state_sharding, job.batch_sharding),
        )

    def evaluate(self) -> Dict[str, float]:
        """Mean loss over the eval dataset (reference ``evaluate`` +
        ``prediction_loop``)."""
        if self.eval_fetch is None or self.eval_dataset_size <= 0:
            return {}
        import jax

        self._build_eval_step()
        per_proc = (
            self.args.eval_batch_per_proc
            or self.core.micro_batch * self.core.grad_accum
        )
        global_bs = per_proc * max(1, self._num_processes)
        n_batches = max(1, self.eval_dataset_size // global_bs)
        losses = []
        for b in range(n_batches):
            lo = b * global_bs + self._process_id * per_proc
            indices = np.arange(lo, lo + per_proc, dtype=np.int64)
            indices %= self.eval_dataset_size
            batch_np = self.eval_fetch(indices)
            batch = jax.tree_util.tree_map(
                lambda x, s: jax.make_array_from_process_local_data(
                    s, np.asarray(x)
                ),
                batch_np,
                self.core.job.batch_sharding,
            )
            losses.append(float(self._eval_step(self.core.state, batch)))
        metrics = {"eval_loss": float(np.mean(losses))}
        metric = metrics["eval_loss"]
        better = (
            self.state.best_metric is None
            or (metric > self.state.best_metric
                if self.args.greater_is_better
                else metric < self.state.best_metric)
        )
        if better:
            self.state.best_metric = metric
            self.state.evals_since_best = 0
        else:
            self.state.evals_since_best += 1
        for cb in self.callbacks:
            cb.on_evaluate(self.args, self.state, self.control, metrics)
        self._log(metrics)
        return metrics

    # -- logging -------------------------------------------------------------
    def current_lr(self) -> float:
        return float(self.schedule(self.state.step))

    def _log(self, logs: dict) -> None:
        logs = dict(logs)
        logs.setdefault("lr", self.current_lr())
        logs.setdefault("epoch", self.state.epoch)
        self.state.log_history.append({"step": self.state.step, **logs})
        for cb in self.callbacks:
            cb.on_log(self.args, self.state, self.control, logs)

    # -- the loop ------------------------------------------------------------
    def train(self, resume: bool = True) -> TrainerState:
        args = self.args
        self.core.build(self._num_processes, self._process_id)
        total = self.total_steps(self.core.dataset_size)
        restored = self._restore() if resume else False
        # Fast-forward the sampler ONLY when the checkpoint carried no
        # sampler state (e.g. a checkpoint written outside this trainer);
        # the restored position is authoritative — a boundary checkpoint
        # (step % steps_per_epoch == 0) would otherwise replay the whole
        # epoch under the wrong shuffle.
        if (
            restored
            and not self._sampler_restored
            and self.core.sampler is not None
        ):
            self.core.sampler.completed_steps = (
                self.state.step % self.steps_per_epoch
            )
        for cb in self.callbacks:
            cb.on_train_begin(args, self.state, self.control)

        window: List[float] = []
        t_last = time.perf_counter()
        empty_passes = 0
        while self.state.step < total and not self.control.should_stop:
            made_progress = False
            for metrics in self.core.epoch():
                made_progress = True
                self.state.step += 1
                self.state.samples_seen += args.global_batch_size
                # Defer the host transfer: float() here would sync every
                # step and serialize the async-dispatch pipeline (device
                # idles while python rounds the loss); losses are forced
                # in a batch at the logging boundary instead.  With
                # logging disabled there is no boundary to drain at, so
                # skip accumulating (live device buffers would otherwise
                # pile up for the whole run).
                if args.logging_steps > 0:
                    window.append(metrics["loss"])
                if self.step_reporter is not None:
                    try:
                        self.step_reporter(self.state.step)
                    except Exception as e:  # noqa: BLE001
                        # The reporter feeds the hang detector; losing
                        # it silently mimics the hang it should catch.
                        logger.debug("step reporter failed: %s", e)
                for cb in self.callbacks:
                    cb.on_step_end(
                        args, self.state, self.control, metrics
                    )

                if (
                    args.logging_steps > 0
                    and self.state.step % args.logging_steps == 0
                ):
                    dt = time.perf_counter() - t_last
                    self._log(
                        {
                            "loss": float(
                                np.mean([float(x) for x in window])
                            ),
                            "steps_per_s": len(window) / max(dt, 1e-9),
                        }
                    )
                    window.clear()
                    t_last = time.perf_counter()
                if (
                    args.eval_steps > 0
                    and self.state.step % args.eval_steps == 0
                ) or self.control.should_evaluate:
                    self.control.should_evaluate = False
                    self.evaluate()
                if (
                    args.save_steps > 0
                    and self.state.step % args.save_steps == 0
                ) or self.control.should_save:
                    self.control.should_save = False
                    self.save()
                if (
                    self.state.step >= total
                    or self.control.should_stop
                ):
                    break
            self.state.epoch += 1
            for cb in self.callbacks:
                cb.on_epoch_end(args, self.state, self.control)
            # A pass that yields nothing is normal exactly once after a
            # boundary restore (the exhausted epoch rolls the sampler to
            # the next one); twice in a row means a truly empty partition.
            empty_passes = 0 if made_progress else empty_passes + 1
            if empty_passes >= 2:
                break

        if self._ckpt is not None:
            self.save(storage=True)
            self._ckpt.wait()
        for cb in self.callbacks:
            cb.on_train_end(args, self.state, self.control)
        return self.state
