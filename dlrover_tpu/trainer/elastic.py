"""ElasticTrainer: fixed-global-batch elastic training.

Capability parity with reference ``trainer/torch/elastic/trainer.py:181``
(``ElasticTrainer``) and ``dataloader.py:26`` (``ElasticDataLoader``): the
user fixes a GLOBAL batch size once; when the world is re-formed with a
different process count the trainer preserves it by adjusting gradient
accumulation, so the optimization trajectory (effective batch, LR schedule)
is invariant to elasticity.

TPU-native design: instead of wrapping a torch module and hooking
``optimizer.step``, the trainer owns a pjit'd step built by
``parallel.accelerate`` and re-builds it (new mesh + new grad-accum) on
``reshard``.  Checkpointable trainer state (step, sampler position) rides
the same flash-checkpoint pytree as params.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Optional

import numpy as np

from dlrover_tpu.common.log import logger
from dlrover_tpu.trainer.sampler import ElasticSampler


def resolve_grad_accum(
    global_batch_size: int, num_processes: int, max_micro_per_proc: int
) -> tuple[int, int]:
    """-> (micro_batch_per_proc, grad_accum) with
    micro*accum*num_processes == global_batch_size (reference
    ``ElasticTrainer._get_gradient_accumulation`` behaviour: accum grows as
    the world shrinks).  Raises if the global batch cannot be preserved."""
    if global_batch_size % num_processes:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by "
            f"{num_processes} processes"
        )
    per_proc = global_batch_size // num_processes
    accum = -(-per_proc // max_micro_per_proc)  # ceil
    while per_proc % accum:
        accum += 1
    return per_proc // accum, accum


@dataclasses.dataclass
class TrainerConfig:
    global_batch_size: int
    max_micro_batch_per_proc: int  # memory ceiling per process
    seq_len: int = 0


class ElasticTrainer:
    """Owns the sharded train step + sampler; survives re-formed worlds.

    Usage (inside a worker, after ``trainer_sdk.init()``)::

        trainer = ElasticTrainer(
            cfg, loss_fn=..., init_fn=..., optimizer=...,
            fetch_batch=lambda idx: {...np arrays...},
            dataset_size=N,
        )
        trainer.build(num_processes, process_id)
        for metrics in trainer.epoch():
            ...
    """

    def __init__(
        self,
        cfg: TrainerConfig,
        *,
        loss_fn: Callable,
        init_fn: Callable,
        optimizer,
        fetch_batch: Callable[[np.ndarray], Any],
        dataset_size: int,
        strategy: Any = None,
        sampler_seed: int = 0,
        devices=None,
        strategy_cache: Any = None,
        param_specs: Any = None,  # e.g. "planner" | spec tree | callable
        frozen: Any = None,  # non-trained pytree (LoRA base model)
    ):
        self.cfg = cfg
        self.loss_fn = loss_fn
        self.init_fn = init_fn
        self.optimizer = optimizer
        self.fetch_batch = fetch_batch
        self.dataset_size = dataset_size
        self.base_strategy = strategy
        self.sampler_seed = sampler_seed
        self.devices = devices
        # Strategy persistence (StrategyCache / MasterStrategyCache):
        # an elastic rebuild with an unchanged fingerprint skips the
        # search instead of re-profiling mid-recovery.
        self.strategy_cache = strategy_cache
        self.param_specs = param_specs
        self.frozen = frozen

        self.job = None  # AcceleratedJob
        self.state = None
        self.sampler: Optional[ElasticSampler] = None
        self.num_processes = 0
        self.process_id = 0
        self.grad_accum = 1
        self.micro_batch = 0
        self._rng_seed = 0

    # -- world (re)formation -------------------------------------------------
    def build(self, num_processes: int, process_id: int) -> None:
        """(Re)build the pjit step for the current world.  Called at start
        and after every membership change; preserves params/opt-state if
        already initialized (device_put onto the new sharding) and the
        sampler position (reference ``ElasticTrainer.reset``)."""
        import jax

        self._build_job(num_processes, process_id)
        old_state = self.state
        if old_state is None:
            self.state = self.job.create_state(
                jax.random.PRNGKey(self._rng_seed)
            )
        else:
            # Reshard carried state onto the new mesh/sharding.
            self.state = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s),
                old_state,
                self.job.state_sharding,
            )
        self._finish_world(num_processes, process_id)

    def _build_job(self, num_processes: int, process_id: int) -> None:
        """Mesh + pjit (re)build for a world size — everything except the
        state carry, so :meth:`reshard_live` can route the carry through
        the plan/move data path instead of a blind ``device_put``."""
        import jax

        from dlrover_tpu.parallel.accelerate import accelerate

        self.micro_batch, self.grad_accum = resolve_grad_accum(
            self.cfg.global_batch_size,
            num_processes,
            self.cfg.max_micro_batch_per_proc,
        )
        logger.info(
            "elastic trainer build: %d procs, micro=%d accum=%d "
            "(global batch %d preserved)",
            num_processes, self.micro_batch, self.grad_accum,
            self.cfg.global_batch_size,
        )
        sample_idx = np.arange(
            self.micro_batch * self.grad_accum, dtype=np.int64
        )
        sample_local = self.fetch_batch(sample_idx)
        # accelerate() wants the batch with the GLOBAL leading dim.
        devs = self.devices
        if devs is None:
            devs = jax.devices()
        sample_global = jax.tree_util.tree_map(
            lambda x: np.repeat(
                np.asarray(x), num_processes, axis=0
            )[: self.micro_batch * self.grad_accum * num_processes],
            sample_local,
        )
        strat = self.base_strategy
        if strat is None:
            strat = "auto"
        self.job = accelerate(
            loss_fn=self.loss_fn,
            init_fn=self.init_fn,
            optimizer=self.optimizer,
            sample_batch=sample_global,
            strategy=strat,
            devices=devs,
            grad_accum=self.grad_accum,
            cache=self.strategy_cache,
            param_specs=self.param_specs,
            frozen=self.frozen,
        )

    def _finish_world(self, num_processes: int, process_id: int) -> None:
        if self.sampler is None:
            self.sampler = ElasticSampler(
                self.dataset_size,
                batch_size_per_process=self.micro_batch * self.grad_accum,
                num_processes=num_processes,
                process_id=process_id,
                seed=self.sampler_seed,
            )
        else:
            self.sampler = self.sampler.reshard(num_processes, process_id)
        self.num_processes = num_processes
        self.process_id = process_id

    def reshard_live(self, num_processes: int, process_id: int):
        """Resize as a data-plane move, not a restart (ISSUE 6 / ROADMAP
        item 1): quiesce at the step boundary, re-jit for the new world,
        then rebuild the carried state through the reshard planner/mover
        (validated segment tiling, CRC'd cross-host payloads) instead of
        an opaque ``device_put``.

        Returns a :class:`~dlrover_tpu.reshard.coordinator.ReshardOutcome`
        on success.  On ANY plan/move/verify failure it raises
        :class:`~dlrover_tpu.reshard.coordinator.ReshardError` — loudly —
        after which the trainer must be recovered via the checkpoint
        ladder (``build()`` + engine restore), the correctness backstop
        this live path never replaces."""
        from dlrover_tpu.reshard.coordinator import (
            ReshardError,
            ReshardOutcome,
            reshard_shards,
        )

        if self.state is None:
            self.build(num_processes, process_id)
            return ReshardOutcome(ok=True, reason="fresh state, no move")
        import time

        import jax

        t0 = time.perf_counter()
        old_state = self.state
        try:
            # Quiesce BEFORE tearing into the rebuild: the old step may
            # still be writing donated buffers asynchronously.
            jax.block_until_ready(old_state)
            from dlrover_tpu.checkpoint.tree_utils import flatten_to_shards

            tensors, infos = flatten_to_shards(old_state)
        except Exception as e:  # noqa: BLE001 - unreadable old state:
            # nothing to move; the checkpoint ladder owns recovery.
            raise ReshardError(f"quiesce/snapshot failed: {e}") from e
        self._build_job(num_processes, process_id)
        target = jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(
                np.shape(x),
                getattr(x, "dtype", None) or np.asarray(x).dtype,
                sharding=s,
            ),
            old_state,
            self.job.state_sharding,
        )
        new_state, stats = reshard_shards(tensors, infos, target)
        self.state = new_state
        self._finish_world(num_processes, process_id)
        outcome = ReshardOutcome(
            ok=True,
            downtime_s=time.perf_counter() - t0,
            moved_local_mb=stats["local_bytes"] / (1 << 20),
            moved_cross_mb=stats["cross_bytes"] / (1 << 20),
            segments=stats["segments"],
        )
        logger.info(
            "live reshard to %d procs done in %.3fs (%.1f MB moved) — "
            "no restart", num_processes, outcome.downtime_s,
            outcome.moved_mb,
        )
        return outcome

    # -- stepping ------------------------------------------------------------
    @property
    def step(self) -> int:
        if self.state is None:
            return 0
        return int(np.asarray(self.state["step"]))

    def train_on_indices(self, indices: np.ndarray):
        import jax

        batch_np = self.fetch_batch(indices)
        batch = jax.tree_util.tree_map(
            lambda x, s: jax.make_array_from_process_local_data(
                s, np.asarray(x)
            ),
            batch_np,
            self.job.batch_sharding,
        )
        self.state, metrics = self.job.train_step(self.state, batch)
        return metrics

    def epoch(self) -> Iterator[dict]:
        """Iterate the rest of the current epoch, yielding metrics."""
        for indices in self.sampler:
            yield self.train_on_indices(indices)

    # -- checkpointable trainer state ---------------------------------------
    def state_dict(self) -> dict:
        return {
            "sampler": self.sampler.state_dict() if self.sampler else {},
            "global_batch_size": self.cfg.global_batch_size,
        }

    def load_state_dict(self, sd: dict) -> None:
        if self.sampler is not None and sd.get("sampler"):
            self.sampler.load_state_dict(sd["sampler"])


class ElasticDataLoader:
    """Index-stream loader with master-tunable batch size (reference
    ``ElasticDataLoader trainer/torch/elastic/dataloader.py:26``: the
    master's strategy generator pushes ``DataLoaderConfig`` updates and the
    loader applies them between batches)."""

    def __init__(
        self,
        sampler: ElasticSampler,
        fetch_batch: Callable[[np.ndarray], Any],
        *,
        master_client=None,
    ):
        self.sampler = sampler
        self.fetch_batch = fetch_batch
        self.client = master_client
        self._config_version = -1

    def _maybe_apply_config(self) -> None:
        if self.client is None:
            return
        try:
            cfg = self.client.get_parallel_config()
        except Exception as e:  # noqa: BLE001
            logger.debug("parallel-config poll failed: %s", e)
            return
        if cfg.version <= self._config_version:
            return
        if self.sampler.completed_steps != 0:
            # Mid-epoch resume: changing the batch size would reinterpret
            # the checkpointed position under a different partition and
            # skip/repeat samples; apply at the next epoch boundary.
            return
        self._config_version = cfg.version
        bs = cfg.dataloader.get("batch_size")
        if bs and int(bs) != self.sampler.batch_size_per_process:
            logger.info(
                "dataloader: master tuned batch size %d -> %d",
                self.sampler.batch_size_per_process, int(bs),
            )
            self.sampler.batch_size_per_process = int(bs)

    def __iter__(self):
        """One epoch.  Master-pushed batch-size changes apply at epoch
        boundaries (the sampler reads its batch size when iteration
        starts)."""
        self._maybe_apply_config()
        for indices in self.sampler:
            yield self.fetch_batch(indices)
