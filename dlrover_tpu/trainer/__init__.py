"""L6 trainer SDK: what user training scripts import.

``init()`` bootstraps the JAX distributed runtime from the agent's env
contract; ``ElasticTrainer``/``ElasticSampler``/``ElasticDataLoader`` give
elastic-aware training utilities (SURVEY.md §1 L6, reference
``dlrover/trainer/``).
"""

from dlrover_tpu.trainer.bootstrap import ElasticContext, init  # noqa: F401
from dlrover_tpu.trainer.elastic import (  # noqa: F401
    ElasticDataLoader,
    ElasticTrainer,
    TrainerConfig,
    resolve_grad_accum,
)
from dlrover_tpu.trainer.sampler import ElasticSampler  # noqa: F401
from dlrover_tpu.trainer.trainer import (  # noqa: F401
    EarlyStoppingCallback,
    LoggingCallback,
    Trainer,
    TrainerCallback,
    TrainerControl,
    TrainerState,
    TrainingArgs,
    build_lr_schedule,
    build_optimizer,
)
