"""Config-driven training executor.

Parity with the reference's conf-driven estimator executor
(``dlrover/trainer/tensorflow/executor/estimator_executor.py`` +
``util/conf_util.py``: a declarative conf names the model, data and run
parameters; the executor assembles and runs the training).  TPU-native
shape: a :class:`TrainConf` (python dict, JSON file, or ``.py`` file
exposing ``CONF``) selects a registered model family and its sizes, the
synthetic/file data source, TrainingArgs, and the acceleration strategy;
:func:`execute` builds the full :class:`~dlrover_tpu.trainer.trainer.
Trainer` and runs it.  Model families register via
:func:`register_model_family`, so user models plug in without touching
this module.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import json
from typing import Any, Callable, Dict, Optional

import numpy as np

from dlrover_tpu.common.log import logger
from dlrover_tpu.trainer.trainer import Trainer, TrainerState, TrainingArgs

# family name -> builder(conf) -> (loss_fn, init_fn, fetch_batch)
_FAMILIES: Dict[str, Callable] = {}


def register_model_family(name: str):
    def deco(fn):
        _FAMILIES[name] = fn
        return fn

    return deco


@dataclasses.dataclass
class TrainConf:
    """The declarative job spec (reference ``conf`` module surface)."""

    model: str = "nanogpt"            # registered family
    model_args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    dataset_size: int = 4096
    seq_len: int = 64
    train: Dict[str, Any] = dataclasses.field(default_factory=dict)
    strategy: Optional[Dict[str, Any]] = None  # mesh/remat/accum override

    @classmethod
    def load(cls, source) -> "TrainConf":
        """From a dict, a JSON path, or a ``.py`` path exposing CONF."""
        if isinstance(source, cls):
            return source
        if isinstance(source, dict):
            return cls(**source)
        if str(source).endswith(".json"):
            with open(source) as f:
                return cls(**json.load(f))
        if str(source).endswith(".py"):
            spec = importlib.util.spec_from_file_location("_conf", source)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            conf = getattr(mod, "CONF")
            return conf if isinstance(conf, cls) else cls(**conf)
        raise ValueError(f"unsupported conf source {source!r}")


# -- built-in families -------------------------------------------------------


def _synthetic_tokens(indices, seq_len: int, vocab: int) -> np.ndarray:
    """Deterministic, index-addressable token sequences (elastic
    re-partition safe: any process can materialize any record)."""
    rngs = np.random.RandomState(0)
    base = rngs.randint(0, vocab, size=(seq_len + 1,))
    return np.stack(
        [(base + int(i)) % vocab for i in indices]
    ).astype("int32")


@register_model_family("nanogpt")
def _nanogpt(conf: TrainConf):
    from dlrover_tpu.models import nanogpt

    cfg = nanogpt.GPTConfig.tiny()
    cfg = type(cfg)(
        **{**cfg.__dict__, "block_size": conf.seq_len, **conf.model_args}
    )

    def fetch(indices):
        out = _synthetic_tokens(indices, conf.seq_len, cfg.vocab_size)
        return {"tokens": out[:, :-1], "targets": out[:, 1:]}

    def loss_fn(params, batch):
        return nanogpt.loss_fn(
            params, batch["tokens"], batch["targets"], cfg
        )

    return loss_fn, lambda r: nanogpt.init_params(r, cfg), fetch


@register_model_family("llama")
def _llama(conf: TrainConf):
    from dlrover_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    cfg = dataclasses.replace(cfg, **conf.model_args)

    def fetch(indices):
        out = _synthetic_tokens(indices, conf.seq_len, cfg.vocab_size)
        return {"tokens": out}

    def loss_fn(params, batch):
        return llama.loss_fn(params, batch, cfg)

    return loss_fn, lambda r: llama.init_params(r, cfg), fetch


def _synthetic_image_fetch(num_classes, image_size, channels):
    """Index-addressable synthetic labeled images (learnable; elastic
    re-partition safe): record i's label and pixels derive from i alone.
    Class prototypes are index-independent — built once, not per fetch."""
    protos = np.random.RandomState(0).randn(
        num_classes, image_size, image_size, channels
    ).astype(np.float32)

    def fetch(indices):
        idx = np.asarray(indices, np.int64)
        labels = (idx % num_classes).astype(np.int32)
        noise = np.stack(
            [
                # Offset the seed so record 0's stream is not the
                # prototype generator's (which would make its "noise"
                # perfectly correlated with protos[0]).
                np.random.RandomState(int(i) + 1).randn(
                    image_size, image_size, channels
                )
                for i in idx
            ]
        ).astype(np.float32)
        return {
            "images": protos[labels] + 0.3 * noise,
            "labels": labels,
        }

    return fetch


@register_model_family("vit")
def _vit(conf: TrainConf):
    from dlrover_tpu.models import vit

    cfg = vit.ViTConfig.tiny(**conf.model_args)
    fetch = _synthetic_image_fetch(
        cfg.num_classes, cfg.image_size, cfg.channels
    )

    def loss_fn(params, batch):
        return vit.loss_fn(params, batch, cfg)

    return loss_fn, lambda r: vit.init_params(r, cfg), fetch


@register_model_family("cnn")
def _cnn(conf: TrainConf):
    from dlrover_tpu.models import cnn

    cfg = cnn.CNNConfig.tiny(**conf.model_args)
    fetch = _synthetic_image_fetch(
        cfg.num_classes, cfg.image_size, cfg.channels
    )

    def loss_fn(params, batch):
        return cnn.loss_fn(params, batch, cfg)

    return loss_fn, lambda r: cnn.init_params(r, cfg), fetch


# -- the executor ------------------------------------------------------------


def build_trainer(
    source,
    *,
    elastic_ctx=None,
    devices=None,
) -> Trainer:
    """Conf -> assembled Trainer (the executor's setup half)."""
    conf = TrainConf.load(source)
    if conf.model not in _FAMILIES:
        raise ValueError(
            f"unknown model family {conf.model!r}; registered: "
            f"{sorted(_FAMILIES)}"
        )
    loss_fn, init_fn, fetch = _FAMILIES[conf.model](conf)
    args = TrainingArgs(**conf.train)

    strategy = None
    if conf.strategy is not None:
        from dlrover_tpu.parallel.accelerate import Strategy
        from dlrover_tpu.parallel.mesh import MeshSpec

        sd = dict(conf.strategy)
        mesh = MeshSpec(**sd.pop("mesh", {}))
        strategy = Strategy(mesh=mesh, **sd)

    kw: Dict[str, Any] = {}
    if elastic_ctx is not None:
        kw.update(
            master_client=elastic_ctx.client,
            step_reporter=elastic_ctx.report_step,
            num_processes=elastic_ctx.num_processes,
            process_id=elastic_ctx.process_id,
        )
    return Trainer(
        loss_fn=loss_fn,
        init_fn=init_fn,
        args=args,
        fetch_batch=fetch,
        dataset_size=conf.dataset_size,
        eval_fetch=fetch,
        eval_dataset_size=max(64, args.global_batch_size * 2),
        strategy=strategy,
        devices=devices,
        **kw,
    )


def execute(source, **kw) -> TrainerState:
    """Conf in, trained state out (the executor's run half)."""
    conf = TrainConf.load(source)  # load ONCE: .py confs execute on load
    trainer = build_trainer(conf, **kw)
    logger.info(
        "conf executor: model=%s steps=%d",
        conf.model, trainer.args.max_steps,
    )
    return trainer.train()


def main(argv=None) -> int:  # pragma: no cover - thin CLI shell
    """``python -m dlrover_tpu.trainer.conf_executor conf.json`` — run a
    declarative training job (under the elastic launcher or standalone)."""
    import argparse
    import sys

    p = argparse.ArgumentParser("dlrover-tpu-exec")
    p.add_argument("conf", help="JSON/.py conf file")
    args = p.parse_args(argv)

    import dlrover_tpu.trainer as sdk

    ctx = sdk.init()
    state = execute(args.conf, elastic_ctx=ctx)
    print(f"TRAIN_DONE step={state.step}", flush=True)
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
