"""Worker-process bootstrap: from agent env contract to a live JAX world.

The TPU-native replacement for torch's ``init_process_group`` + torchelastic
env plumbing (reference ``training.py _set_master_addr_port :570`` and the
worker-side ``torch.distributed`` init): the agent hands each worker its
``process_id``/``num_processes``/coordinator via env; ``init()`` brings up
``jax.distributed``, connects the master client, and returns an
:class:`ElasticContext` for step reporting, dynamic sharding and checkpoint
access.
"""

from __future__ import annotations

import atexit
import os
from typing import Optional

from dlrover_tpu import chaos
from dlrover_tpu.agent.master_client import MasterClient, build_master_client
from dlrover_tpu.common import env as env_utils
from dlrover_tpu.common.jax_env import (
    ensure_platform,
    initialize_distributed_from_env,
)
from dlrover_tpu.common.log import logger, set_role


class ElasticContext:
    """What a worker knows about its place in the elastic job."""

    def __init__(self):
        self.node_id = env_utils.get_node_id()
        self.node_rank = env_utils.get_node_rank()
        self.node_num = env_utils.get_node_num()
        self.process_id = env_utils.get_process_id()
        self.num_processes = env_utils.get_num_processes()
        self.local_rank = int(os.environ.get("DLROVER_TPU_LOCAL_RANK", 0))
        self.restart_count = int(
            os.environ.get("DLROVER_TPU_RESTART_COUNT", 0)
        )
        self.rdzv_round = int(os.environ.get("DLROVER_TPU_RDZV_ROUND", 0))
        #: Fleet role of this process (ISSUE 10): entrypoints shared by
        #: several roles (e.g. llama_serve_fleet) branch on it.
        self.node_role = os.environ.get("DLROVER_TPU_NODE_ROLE", "worker")
        self.job_name = env_utils.get_job_name()
        self.master_addr = env_utils.get_master_addr()
        self.client: Optional[MasterClient] = None
        self.distributed = False
        self._last_metrics_report = 0.0
        self._last_reshard_poll = 0.0
        self._last_reshard_epoch = -1

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    def report_step(self, step: int) -> None:
        """Feed the master's speed monitor / goodput accounting (leader
        only; reference ``report_global_step``) and, throttled, this node's
        step-metrics diagnosis stream (per-node stall detection,
        reference xpu-timer collector)."""
        # Chaos: ``worker.kill:rank=R,step=N`` hard-kills this worker at a
        # deterministic step; the agent's monitor loop must detect it,
        # breakpoint-save, and re-form the world.
        chaos.inject("worker.kill", rank=self.process_id, step=step)
        if self.client is None:
            return
        if self.is_leader:
            try:
                self.client.report_global_step(step)
            except Exception as e:  # noqa: BLE001
                logger.warning("report_step failed: %s", e)
        if self.local_rank == 0:
            import time as _time

            nowm = _time.monotonic()
            if nowm - self._last_metrics_report > 30.0:
                self._last_metrics_report = nowm
                try:
                    import json as _json

                    self.client.report_diagnosis_data(
                        "step_metrics",
                        _json.dumps({"step": step, "ts": _time.time()}),
                    )
                except Exception as e:  # noqa: BLE001
                    # Missing a heartbeat is survivable; a silent
                    # string of them looks like a hang to the master.
                    logger.debug("step-metrics report failed: %s", e)


    # -- live resharding (ISSUE 6) ------------------------------------------
    def poll_reshard(self):
        """Between-steps check for a pending resize epoch (the master's
        live-reshard broadcast).  Throttled to
        ``Context.reshard_poll_interval`` so it can ride the step loop;
        returns a ``ReshardEpochInfo`` exactly once per NEW preparing
        epoch, else ``None``.  The caller (the training loop) quiesces at
        the step boundary, runs ``ElasticTrainer.reshard_live``, and
        reports the verdict via :meth:`report_reshard`."""
        if self.client is None:
            return None
        import time as _time

        from dlrover_tpu.common.global_context import get_context

        now = _time.monotonic()
        if now - self._last_reshard_poll < get_context().reshard_poll_interval:
            return None
        self._last_reshard_poll = now
        try:
            info = self.client.get_reshard_epoch()
        except Exception as e:  # noqa: BLE001
            logger.debug("reshard-epoch poll failed: %s", e)
            return None
        if info.status != "preparing" or info.epoch <= self._last_reshard_epoch:
            return None
        self._last_reshard_epoch = info.epoch
        logger.info(
            "reshard: observed resize epoch %d -> %d processes (spec=%s)",
            info.epoch, info.target_num_processes, info.target_spec,
        )
        return info

    def report_reshard(self, epoch: int, outcome=None, error: str = "") -> None:
        """Report a live-reshard verdict back to the master (best-effort:
        a lost report only means the epoch times out into the restart
        ladder — safe, just slower)."""
        if self.client is None:
            return
        try:
            if outcome is not None and getattr(outcome, "ok", False):
                self.client.report_reshard(
                    epoch, True,
                    downtime_ms=outcome.downtime_s * 1000.0,
                    moved_mb=outcome.moved_mb,
                )
            else:
                self.client.report_reshard(
                    epoch, False, reason=error or "reshard failed"
                )
        except Exception as e:  # noqa: BLE001
            logger.warning("reshard report failed: %s", e)


_ctx: Optional[ElasticContext] = None


def init(connect_master: bool = True) -> ElasticContext:
    """Bootstrap this worker process.  Idempotent."""
    global _ctx
    if _ctx is not None:
        return _ctx
    ctx = ElasticContext()
    set_role(f"worker-{ctx.process_id}")
    ensure_platform()
    from dlrover_tpu.common.jax_env import enable_compilation_cache

    if enable_compilation_cache():
        logger.info("persistent XLA compilation cache enabled")
    ctx.distributed = initialize_distributed_from_env()
    if ctx.distributed:
        import jax

        logger.info(
            "jax.distributed up: process %d/%d, %d local / %d global devices",
            ctx.process_id, ctx.num_processes,
            jax.local_device_count(), jax.device_count(),
        )
        atexit.register(_shutdown)
    if connect_master and ctx.master_addr:
        ctx.client = build_master_client(ctx.master_addr, ctx.node_id)
    _ctx = ctx
    return ctx


def get_elastic_context() -> Optional[ElasticContext]:
    return _ctx


def _shutdown() -> None:
    try:
        import threading

        import jax
        from jax.experimental import multihost_utils

        # Ranks can be many steps apart in wall-clock at exit (async
        # dispatch); sync first so the coordination service's shutdown
        # barrier (short timeout) sees everyone arrive together.  The sync
        # is bounded: a worker exiting alone (crash path) must not block
        # the agent's failure detection waiting for peers that will never
        # arrive.
        done = threading.Event()

        def _sync():
            try:
                multihost_utils.sync_global_devices("dlrover_tpu_exit")
            # graftcheck: disable=CC104 -- exit barrier is best-effort
            # by design: a crashed peer must not turn our clean exit
            # into a hang (the timeout path below documents this)
            except Exception:  # noqa: BLE001
                pass
            done.set()

        threading.Thread(target=_sync, daemon=True).start()
        if done.wait(timeout=60.0):
            jax.distributed.shutdown()
        # else: skip the shutdown barrier entirely; process teardown
        # closes the coordination channel and peers learn via heartbeat.
    # graftcheck: disable=CC104 -- teardown must never mask the
    # worker's real exit status with a shutdown-path error
    except Exception:  # noqa: BLE001
        pass
