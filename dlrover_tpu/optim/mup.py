"""muP — Maximal Update Parametrization (Tensor Programs V).

Capability parity with reference ``atorch/mup/`` (``module.py``,
``shape.py`` infshape bookkeeping, ``optim.py`` MuAdam/MuSGD): transfer
hyperparameters tuned at a small base width to a large target width by
scaling init and per-param Adam learning rates.

JAX formulation: instead of wrapping modules, we compare each param's shape
against its *base-model* shape (``jax.eval_shape`` on the small config) to
classify leaves, then (a) rescale an existing standard init and (b) wrap
the optimizer with a per-leaf update scale.  Convention: 2-D weights are
``(fan_in, fan_out)`` as used by ``x @ W`` throughout ``models/``.

Rules (Adam):
  - matrix-like (>=2 dims grown vs base): lr_mult = 1/width_mult,
    init std already ~1/sqrt(fan_in) in standard inits — kept;
  - vector-like (bias/norm/embedding rows): untouched;
  - output head (fan_in grown, fan_out fixed = vocab): lr_mult =
    1/width_mult and init scaled by 1/sqrt(width_mult) (zero-init also
    valid and supported via ``zero_output=True``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclasses.dataclass(frozen=True)
class InfShape:
    """Per-param muP classification (the reference's ``infshape``)."""

    shape: tuple
    base_shape: tuple
    ninf: int  # number of dims that grow with width
    width_mult: float  # fan_in ratio vs base (1.0 if fan_in fixed)

    @property
    def matrix_like(self) -> bool:
        return self.ninf >= 2

    @property
    def hidden_grown(self) -> bool:
        return self.ninf >= 1


def _classify(shape, base_shape) -> InfShape:
    shape = tuple(int(s) for s in shape)
    base_shape = tuple(int(s) for s in base_shape)
    if len(shape) != len(base_shape):
        raise ValueError(
            f"rank mismatch {shape} vs base {base_shape}; "
            "base model must be the same architecture at smaller width"
        )
    inf_dims = [i for i, (s, b) in enumerate(zip(shape, base_shape)) if s != b]
    if len(shape) >= 2:
        fan_in_dim = len(shape) - 2  # (fan_in, fan_out) for 2-D
        width_mult = (
            shape[fan_in_dim] / base_shape[fan_in_dim]
            if fan_in_dim in inf_dims
            else 1.0
        )
    else:
        width_mult = 1.0
    return InfShape(shape, base_shape, len(inf_dims), width_mult)


def infer_width_mults(params_or_shapes: Any, base_shapes: Any) -> Any:
    """Tree of :class:`InfShape` from target params (or ShapeDtypeStructs)
    and base-model shapes (``jax.eval_shape(init_fn_base, rng)``)."""
    return jax.tree_util.tree_map(
        lambda p, b: _classify(np.shape(p), np.shape(b)),
        params_or_shapes,
        base_shapes,
    )


def mup_init_params(
    init_fn: Callable,
    rng,
    base_shapes: Any,
    *,
    output_match: Callable[[tuple], bool] | None = None,
    zero_output: bool = False,
) -> Any:
    """Run ``init_fn(rng)`` then apply muP init corrections.

    Standard inits (normal/sqrt-fan-in) are already muP-correct for hidden
    matrices; the output head additionally shrinks by ``1/sqrt(width_mult)``
    (or zero-inits).  ``output_match(path_tuple)`` selects head leaves; by
    default a leaf whose LAST path component is exactly one of
    ``lm_head/output/readout/head``.
    """
    params = init_fn(rng)
    infshapes = infer_width_mults(params, base_shapes)

    _HEAD_NAMES = {"lm_head", "output", "readout", "head"}

    def is_output(path) -> bool:
        # DictKey has .key, SequenceKey .idx, GetAttrKey .name.
        names = [
            getattr(k, "key", None)
            or getattr(k, "name", None)
            or getattr(k, "idx", None)
            or str(k)
            for k in path
        ]
        if output_match is not None:
            return output_match(tuple(names))
        # Only the LAST path component counts, and only on exact match —
        # substring matching would catch hidden projections like
        # 'attn/output_proj' and wrongly shrink their init.
        return bool(names) and str(names[-1]).lower() in _HEAD_NAMES

    def fix(path, p, inf: InfShape):
        if is_output(path) and inf.hidden_grown:
            if zero_output:
                return jnp.zeros_like(p)
            return p / np.sqrt(inf.width_mult)
        return p

    return jax.tree_util.tree_map_with_path(fix, params, infshapes)


def mup_scale_adam(infshapes: Any) -> optax.GradientTransformation:
    """Per-leaf update scaling implementing MuAdam (reference
    ``mup/optim.py``): every leaf whose fan_in grew vs base — hidden
    matrices AND the output head — gets ``1/width_mult`` lr; vector-like
    leaves (bias/norm) and embeddings (fan_in = vocab, fixed) have
    ``width_mult == 1`` and pass through.  Chain AFTER the Adam core:
    ``optax.chain(optax.adam(lr), mup_scale_adam(s))``.
    """
    scales = jax.tree_util.tree_map(
        lambda inf: 1.0 / inf.width_mult,
        infshapes,
        is_leaf=lambda x: isinstance(x, InfShape),
    )

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        scaled = jax.tree_util.tree_map(
            lambda u, s: u * s, updates, scales
        )
        return scaled, state

    return optax.GradientTransformation(init, update)
