"""WSAM: sharpness-aware minimization with weighted sharpness (KDD'23).

Capability parity with reference ``atorch/optimizers/wsam.py:11``
(``WeightedSAM``).  The regularized objective is
``L + gamma/(1-gamma) * (L(w+eps) - L(w))``; with ``alpha = gamma/(1-gamma)``
the effective gradient is ``(1-alpha)*g(w) + alpha*g(w+eps)`` (coupled
mode), or — in the decoupled mode the reference defaults to — the base
optimizer consumes ``g(w)`` and the sharpness term
``alpha*(g(w+eps)-g(w))`` is applied directly with the raw learning rate.

The torch version needs closures and two backward passes driven by the
user's loop; in JAX the whole two-gradient step is one pure, jittable
function, and under pjit the implicit gradient mean over the data axis
replaces the reference's explicit ``dist.all_reduce`` calls.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax


class WSAMState(NamedTuple):
    base: Any  # base optimizer state


def _tree_mul(a, s):
    return jax.tree_util.tree_map(lambda x: x * s, a)


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_sub(a, b):
    return jax.tree_util.tree_map(jnp.subtract, a, b)


def wsam_gradient(
    loss_fn: Callable,
    params,
    batch,
    *,
    rho: float = 0.05,
    sam_eps: float = 1e-12,
    adaptive: bool = False,
) -> Tuple[jax.Array, Any, Any]:
    """Return ``(loss, g_w, g_perturbed)`` — the two gradients WSAM needs.

    ``adaptive`` scales the ascent direction by ``p**2`` per-coordinate
    (ASAM-style, reference wsam.py:60)."""
    loss, g = jax.value_and_grad(loss_fn)(params, batch)
    g_asc = (
        jax.tree_util.tree_map(lambda p, gg: jnp.square(p) * gg, params, g)
        if adaptive
        else g
    )
    gnorm = optax.global_norm(g_asc)
    scale = rho / (gnorm + sam_eps)
    perturbed = jax.tree_util.tree_map(
        lambda p, gg: p + scale * gg, params, g_asc
    )
    g_p = jax.grad(loss_fn)(perturbed, batch)
    return loss, g, g_p


class WeightedSAM:
    """Functional WSAM wrapper over an optax base optimizer.

    Usage::

        opt = WeightedSAM(
            optax.adamw(3e-4), loss_fn, rho=0.05, gamma=0.9,
            sharpness_lr=3e-4,  # decoupled mode: matches the base lr
        )
        state = opt.init(params)
        params, state, loss = jax.jit(opt.step)(params, state, batch)
    """

    def __init__(
        self,
        base: optax.GradientTransformation,
        loss_fn: Callable,
        *,
        rho: float = 0.05,
        gamma: float = 0.9,
        sam_eps: float = 1e-12,
        adaptive: bool = False,
        decouple: bool = True,
        sharpness_lr: float | None = None,
        max_norm: float | None = None,
    ):
        self.base = base
        self.loss_fn = loss_fn
        self.rho = rho
        self.gamma = gamma
        self.alpha = gamma / (1.0 - gamma)
        self.sam_eps = sam_eps
        self.adaptive = adaptive
        self.decouple = decouple
        # Decoupled sharpness step uses the raw lr (reference applies
        # ``-lr*alpha*sharpness`` with the group's lr, wsam.py:100-106);
        # optax hides the base lr, so it must be passed explicitly.
        if decouple and sharpness_lr is None:
            raise ValueError(
                "decouple=True requires sharpness_lr (pass the base "
                "optimizer's learning rate)"
            )
        self.sharpness_lr = sharpness_lr
        self.max_norm = max_norm

    def init(self, params) -> WSAMState:
        return WSAMState(base=self.base.init(params))

    def step(self, params, state: WSAMState, batch):
        loss, g, g_p = wsam_gradient(
            self.loss_fn,
            params,
            batch,
            rho=self.rho,
            sam_eps=self.sam_eps,
            adaptive=self.adaptive,
        )
        if self.max_norm is not None:
            g = optax.clip_by_global_norm(self.max_norm).update(g, None)[0]
            g_p = optax.clip_by_global_norm(self.max_norm).update(
                g_p, None
            )[0]
        if self.decouple:
            updates, base_state = self.base.update(g, state.base, params)
            new_params = optax.apply_updates(params, updates)
            sharp = _tree_sub(g_p, g)
            new_params = _tree_add(
                new_params,
                _tree_mul(sharp, -self.sharpness_lr * self.alpha),
            )
        else:
            g_eff = _tree_add(
                _tree_mul(g, 1.0 - self.alpha), _tree_mul(g_p, self.alpha)
            )
            updates, base_state = self.base.update(
                g_eff, state.base, params
            )
            new_params = optax.apply_updates(params, updates)
        return new_params, WSAMState(base=base_state), loss
