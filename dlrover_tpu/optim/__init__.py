"""Optimizers (SURVEY.md #54/#56/#63).

The reference ships torch optimizers (``atorch/optimizers/``: AGD
``agd.py:18``, WSAM ``wsam.py:11``, BF16 master-weight optimizer
``bf16_optimizer.py``) plus CUDA int8-state Adam
(``ops/csrc/quantization/quantization_optimizer.cu``) and muP
(``atorch/mup/``).  Here they are optax-style functional transforms: state
lives in pytrees that shard on the mesh like any other (ZeRO falls out of
GSPMD), and everything is jit/scan-safe.
"""

from dlrover_tpu.ops.quant import adam8bit
from dlrover_tpu.optim.agd import agd
from dlrover_tpu.optim.bf16 import bf16_master_weights
from dlrover_tpu.optim.mup import (
    InfShape,
    infer_width_mults,
    mup_init_params,
    mup_scale_adam,
)
from dlrover_tpu.optim.wsam import WeightedSAM, wsam_gradient

__all__ = [
    "adam8bit",
    "agd",
    "bf16_master_weights",
    "WeightedSAM",
    "wsam_gradient",
    "InfShape",
    "infer_width_mults",
    "mup_init_params",
    "mup_scale_adam",
]
