"""AGD: auto-switchable optimizer preconditioned by stepwise gradient
difference (NeurIPS'23).

Capability parity with reference ``atorch/optimizers/agd.py:18``.  The
preconditioner is the EMA of the *difference* of bias-corrected first
moments between consecutive steps — near convergence the difference shrinks
below ``delta`` and the optimizer degrades gracefully toward SGD-with-
momentum; early on it behaves adaptively like Adam.

Implemented as an optax ``GradientTransformation`` so it composes with
``optax.chain``/schedules and its state shards on the mesh like params.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax


class AGDState(NamedTuple):
    count: jax.Array
    exp_avg: optax.Params  # first moment m_t
    exp_avg_sq: optax.Params  # EMA of squared stepwise moment difference
    max_exp_avg_sq: optax.Params  # amsgrad running max (zeros if disabled)


def agd(
    learning_rate: Union[float, optax.Schedule] = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
    clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """AGD transform.  ``delta`` is the switching threshold: coordinates
    whose preconditioner falls below ``delta*sqrt(bc2)`` take SGD-like
    steps.  ``weight_decay`` is decoupled (AdamW style)."""

    lr_fn = (
        learning_rate
        if callable(learning_rate)
        else (lambda _: learning_rate)
    )

    def init(params):
        def zeros():
            return jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )

        return AGDState(
            count=jnp.zeros((), jnp.int32),
            exp_avg=zeros(),
            exp_avg_sq=zeros(),
            # Scalar placeholders when amsgrad is off — no param-sized
            # fp32 copy wasted in HBM/checkpoints.
            max_exp_avg_sq=zeros()
            if amsgrad
            else jax.tree_util.tree_map(
                lambda _: jnp.zeros((), jnp.float32), params
            ),
        )

    def update(grads, state, params=None):
        count = state.count + 1
        t = count.astype(jnp.float32)
        bc1_old = 1.0 - b1 ** (t - 1.0)
        bc1 = 1.0 - b1**t
        bc2 = 1.0 - b2**t
        lr_t = lr_fn(count)
        lr_adjust = lr_t * jnp.sqrt(bc2) / bc1

        def per_leaf(g, m, v, vmax, p):
            gf = g.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * gf
            # Stepwise difference of bias-corrected first moments; at t=1
            # there is no previous moment, so use the moment itself
            # (reference agd.py:126-131).
            diff = jnp.where(
                count == 1,
                m_new / bc1,
                m_new / bc1 - m / jnp.maximum(bc1_old, 1e-12),
            )
            v_new = b2 * v + (1.0 - b2) * jnp.square(diff)
            vmax_new = jnp.maximum(vmax, v_new) if amsgrad else vmax
            denom_sq = vmax_new if amsgrad else v_new
            denom = jnp.maximum(jnp.sqrt(denom_sq), delta * jnp.sqrt(bc2))
            step_dir = m_new / denom
            if clip is not None:
                step_dir = jnp.clip(step_dir, -clip, clip)
            upd = -lr_adjust * step_dir
            if weight_decay and p is not None:
                upd = upd - lr_t * weight_decay * p.astype(jnp.float32)
            return upd.astype(g.dtype), m_new, v_new, vmax_new

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        flat_vm = treedef.flatten_up_to(state.max_exp_avg_sq)
        flat_p = (
            treedef.flatten_up_to(params)
            if params is not None
            else [None] * len(flat_g)
        )
        outs = [
            per_leaf(g, m, v, vm, p)
            for g, m, v, vm, p in zip(
                flat_g, flat_m, flat_v, flat_vm, flat_p
            )
        ]
        updates = treedef.unflatten([o[0] for o in outs])
        return updates, AGDState(
            count=count,
            exp_avg=treedef.unflatten([o[1] for o in outs]),
            exp_avg_sq=treedef.unflatten([o[2] for o in outs]),
            max_exp_avg_sq=treedef.unflatten([o[3] for o in outs]),
        )

    return optax.GradientTransformation(init, update)
