"""Host-offloaded optimizer state: the ZeRO-Offload / CPU-Adam analogue.

Parity target: the reference's CPU-offload Adam
(``atorch/atorch/optimizers/adam_offload.py`` — optimizer state pinned in
host DRAM, gradients streamed to CPU, params updated there) and the
offload half of its ZeRO family.  The TPU-native mechanism is different
and much simpler: XLA itself can place arrays in **host memory**
(``memory_kind="pinned_host"``) while the compiled step streams them
through HBM for the update — no hand-written pinned-buffer management,
no separate CPU optimizer implementation, same optimizer math.

``offload_opt_state(tx)`` wraps any optax ``GradientTransformation`` so
its state rests host-side; ``host_shardings_for`` computes the matching
shardings to pass as ``jit``'s out_shardings (offload is a placement
property, so it composes with any mesh/partitioning).  Whether the
runtime can stream host-resident operands through a compiled step is
probed once (``supports_host_offload``): TPU runtimes can; the CPU test
backend lacks the placement custom-call, so there everything degrades to
plain device placement with identical numerics.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import optax


def host_memory_kind() -> Optional[str]:
    """'pinned_host' when the default device exposes a host memory space
    (TPU runtimes do), else None.

    Probes ``jax.local_devices()[0]`` — the first device addressable
    from THIS process — never ``jax.devices()[0]``: on multi-host jobs
    the globally-first device belongs to process 0, and probing it from
    other processes raises, which would make the probe answer True on
    process 0 and False elsewhere, so each process would compile a
    different step (SPMD divergence → deadlock)."""
    try:
        dev = jax.local_devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
    except Exception:  # noqa: BLE001 - older runtimes
        return None
    return "pinned_host" if "pinned_host" in kinds else None


@functools.cache
def supports_host_offload() -> bool:
    """True when the backend can compile a step whose inputs/outputs live
    in pinned_host (i.e. it registers the device-placement annotation;
    TPU yes, CPU test backend no)."""
    kind = host_memory_kind()
    if kind is None:
        return False
    try:
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        dev = jax.local_devices()[0]  # addressable from this process
        hs = SingleDeviceSharding(dev, memory_kind=kind)
        x = jax.device_put(jnp.zeros((8,), jnp.float32), hs)
        jax.jit(lambda v: v * 2.0, out_shardings=hs)(x).block_until_ready()
        return True
    except Exception:  # noqa: BLE001 - capability probe
        return False


def with_memory_kind(sharding, kind: Optional[str]):
    """Rebind a (Named)Sharding to a memory kind; identity if kind=None."""
    if kind is None:
        return sharding
    return sharding.with_memory_kind(kind)


def host_shardings_for(opt_state_shardings: Any) -> Any:
    """Map an opt-state sharding pytree to its host-resident twin (pass
    as the ``opt_state`` part of the jitted step's in/out_shardings so
    XLA keeps m/v in host DRAM between steps and streams them during the
    update).  Identity when the backend can't stream host operands."""
    if not supports_host_offload():
        return opt_state_shardings
    kind = host_memory_kind()
    return jax.tree_util.tree_map(
        lambda s: with_memory_kind(s, kind), opt_state_shardings
    )


def offload_opt_state(tx: optax.GradientTransformation,
                      ) -> optax.GradientTransformation:
    """Wrap ``tx`` so ``init`` places its state in host memory.

    The update math is untouched; only the state's resting placement
    changes, and only on backends that can stream host operands through
    a compiled step (otherwise returns ``tx`` unchanged).  Use together
    with :func:`host_shardings_for` on the jitted step so the placement
    survives the train-step round trip.
    """
    if not supports_host_offload():
        return tx
    kind = host_memory_kind()

    def init(params):
        state = tx.init(params)

        def to_host(x):
            if not hasattr(x, "sharding"):
                return x
            return jax.device_put(
                x, with_memory_kind(x.sharding, kind)
            )

        return jax.tree_util.tree_map(to_host, state)

    return optax.GradientTransformation(init, tx.update)
