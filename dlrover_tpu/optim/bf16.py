"""BF16 training with fp32 master weights as an optax wrapper.

Capability parity with reference ``atorch/optimizers/bf16_optimizer.py``:
model params live in bf16 (MXU-friendly), a fp32 master copy lives inside
the optimizer state, grads are accumulated/applied in fp32, and the bf16
params are re-materialized from the masters every step — no drift from
repeated bf16 round-tripping.

On TPU the master copy shards exactly like the param (same shape), so under
an ``fsdp`` axis this is ZeRO-style mixed precision for free.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class BF16State(NamedTuple):
    master: optax.Params  # fp32 master weights
    base: Any


def bf16_master_weights(
    base: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Wrap ``base`` so it updates fp32 masters while emitting bf16-safe
    param updates.

    The returned transform REQUIRES ``params`` in ``update`` and emits
    ``new_bf16 - old_bf16`` as the update, so ``optax.apply_updates``
    produces exactly the bf16 cast of the new master."""

    def init(params):
        master = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params
        )
        return BF16State(master=master, base=base.init(master))

    def update(grads, state: BF16State, params=None):
        if params is None:
            raise ValueError("bf16_master_weights requires params")
        grads32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads
        )
        updates32, base_state = base.update(
            grads32, state.base, state.master
        )
        new_master = optax.apply_updates(state.master, updates32)
        emitted = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype) - p, new_master, params
        )
        return emitted, BF16State(master=new_master, base=base_state)

    return optax.GradientTransformation(init, update)
