"""RL configuration (reference ``atorch/rl/config.py``: AtorchRLConfig
with per-model strategies + PPO hyperparameters from the trlx lineage)."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class PPOConfig:
    # Rollout shape.
    rollout_batch_size: int = 16
    response_length: int = 8
    temperature: float = 1.0
    top_k: int = 0  # 0 = full softmax sampling

    # PPO core (reference ppo_util.loss defaults).
    ppo_epochs: int = 4
    minibatch_size: int = 8
    gamma: float = 1.0
    lam: float = 0.95
    cliprange: float = 0.2
    cliprange_value: float = 0.2
    vf_coef: float = 0.5
    entropy_coef: float = 0.0
    use_whitening: bool = True
    max_grad_norm: float = 1.0

    # KL regularization against the frozen reference model.
    init_kl_coef: float = 0.1
    kl_target: Optional[float] = None  # None = fixed coefficient
    kl_horizon: int = 10000

    # Optimization.
    actor_lr: float = 1e-4
    critic_lr: float = 1e-3

    def __post_init__(self):
        assert self.rollout_batch_size % self.minibatch_size == 0, (
            "rollout batch must be a multiple of the minibatch"
        )


class FixedKLController:
    """Constant beta (reference ppo_util/trlx FixedKLController)."""

    def __init__(self, value: float):
        self.value = float(value)

    def update(self, current_kl: float, n_steps: int) -> None:
        pass


class AdaptiveKLController:
    """Proportional controller driving measured KL toward a target
    (reference AdaptiveKLController; Ziegler et al. 2019)."""

    def __init__(self, init_kl_coef: float, target: float, horizon: int):
        self.value = float(init_kl_coef)
        self.target = float(target)
        self.horizon = int(horizon)

    def update(self, current_kl: float, n_steps: int) -> None:
        error = min(max(current_kl / self.target - 1.0, -0.2), 0.2)
        self.value *= 1.0 + error * n_steps / self.horizon


def make_kl_controller(cfg: PPOConfig):
    if cfg.kl_target is None:
        return FixedKLController(cfg.init_kl_coef)
    return AdaptiveKLController(
        cfg.init_kl_coef, cfg.kl_target, cfg.kl_horizon
    )
