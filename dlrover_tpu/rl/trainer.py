"""PPOTrainer: the make-experience -> PPO-update loop.

Parity with reference ``rl/trainer/ppo_trainer.py`` (+ ``rl_trainer.py``
base): ``make_experience`` rolls the actor out on a prompt batch, scores
it, computes KL-shaped rewards and GAE; ``train`` iterates PPO epochs of
shuffled minibatches through one jitted actor+critic update (donated
state, optax chains with clipping).  The KL controller adapts the
penalty between batches (reference AdaptiveKLController wiring).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from dlrover_tpu.common.log import logger
from dlrover_tpu.rl import ppo
from dlrover_tpu.rl.config import PPOConfig, make_kl_controller
from dlrover_tpu.rl.engine import ModelEngine, ModelRole
from dlrover_tpu.rl.replay_buffer import ReplayBuffer


class PPOTrainer:
    def __init__(
        self,
        engine: ModelEngine,
        config: Optional[PPOConfig] = None,
        *,
        seed: int = 0,
    ):
        import optax

        self.engine = engine
        self.config = config or engine.config
        self.kl_ctl = make_kl_controller(self.config)
        self.buffer = ReplayBuffer(seed=seed)
        self.rng = jax.random.PRNGKey(seed)
        self.step = 0

        c = self.config
        self.actor_tx = optax.chain(
            optax.clip_by_global_norm(c.max_grad_norm),
            optax.adam(c.actor_lr),
        )
        self.critic_tx = optax.chain(
            optax.clip_by_global_norm(c.max_grad_norm),
            optax.adam(c.critic_lr),
        )
        self.actor_opt = self.actor_tx.init(
            engine.params(ModelRole.ACTOR)
        )
        self.critic_opt = self.critic_tx.init(
            engine.params(ModelRole.CRITIC)
        )
        self._train_step: dict = {}  # prompt_len -> jitted step
        self._prompt_len: Optional[int] = None

    # -- experience ----------------------------------------------------------
    def make_experience(
        self, prompts: np.ndarray
    ) -> Dict[str, np.ndarray]:
        """One rollout batch -> buffered experience (reference
        ``make_experience``)."""
        cfg = self.config
        prompts = jnp.asarray(prompts)
        self._prompt_len = int(prompts.shape[1])
        self.rng, sub = jax.random.split(self.rng)
        tokens = self.engine.generate(prompts, sub)
        logprobs, ref_logprobs, values = self.engine.rollout_forward(
            tokens, self._prompt_len
        )
        mask = self.engine.response_mask(tokens, self._prompt_len)
        scores = jnp.asarray(
            self.engine.score(np.asarray(tokens)), jnp.float32
        )
        rewards, seq_kl = ppo.compute_rewards(
            scores, logprobs, ref_logprobs, mask, self.kl_ctl.value
        )
        advantages, returns = ppo.gae_advantages(
            values, rewards, mask, cfg.gamma, cfg.lam, cfg.use_whitening
        )
        exp = {
            "tokens": np.asarray(tokens),
            "mask": np.asarray(mask),
            "old_logprobs": np.asarray(logprobs),
            "old_values": np.asarray(values),
            "advantages": np.asarray(advantages),
            "returns": np.asarray(returns),
        }
        self.buffer.add(exp)
        self.kl_ctl.update(
            float(seq_kl.mean()), n_steps=prompts.shape[0]
        )
        return {
            "score_mean": float(scores.mean()),
            "kl_mean": float(seq_kl.mean()),
            "kl_coef": self.kl_ctl.value,
        }

    # -- update --------------------------------------------------------------
    def _build_train_step(self, P: int):
        cfg = self.config
        engine = self.engine
        actor = engine.roles[ModelRole.ACTOR]
        critic = engine.roles[ModelRole.CRITIC]
        R = cfg.response_length

        def loss_fn(actor_p, critic_p, mb):
            tokens = mb["tokens"]
            resp = tokens[:, P : P + R]
            logits = actor.apply_fn(actor_p, tokens)[
                :, P - 1 : P + R - 1, :
            ]
            logprobs = ppo.logprobs_from_logits(logits, resp)
            values = critic.apply_fn(critic_p, tokens)[:, P : P + R]
            entropy = None
            if cfg.entropy_coef > 0:
                logp_all = jax.nn.log_softmax(logits, axis=-1)
                entropy = -(jnp.exp(logp_all) * logp_all).sum(-1)
            return ppo.ppo_loss(
                logprobs, values,
                mb["old_logprobs"], mb["old_values"],
                mb["advantages"], mb["returns"], mb["mask"],
                cliprange=cfg.cliprange,
                cliprange_value=cfg.cliprange_value,
                vf_coef=cfg.vf_coef,
                entropy=entropy,
                entropy_coef=cfg.entropy_coef,
            )

        def train_step(actor_p, critic_p, actor_opt, critic_opt, mb):
            import optax

            (_, stats), (ga, gc) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(actor_p, critic_p, mb)
            ua, actor_opt = self.actor_tx.update(ga, actor_opt, actor_p)
            actor_p = optax.apply_updates(actor_p, ua)
            uc, critic_opt = self.critic_tx.update(
                gc, critic_opt, critic_p
            )
            critic_p = optax.apply_updates(critic_p, uc)
            return actor_p, critic_p, actor_opt, critic_opt, stats

        return jax.jit(train_step, donate_argnums=(0, 1, 2, 3))

    def train(self) -> Dict[str, float]:
        """Consume the buffer: ``ppo_epochs`` passes of shuffled
        minibatches (reference ``rl_training``).  Returns mean stats."""
        cfg = self.config
        P = self._prompt_len
        assert P is not None, "call make_experience before train"
        if P not in self._train_step:
            self._train_step[P] = self._build_train_step(P)
        step_fn = self._train_step[P]
        actor_p = self.engine.params(ModelRole.ACTOR)
        critic_p = self.engine.params(ModelRole.CRITIC)
        agg: Dict[str, list] = {}
        try:
            for _ in range(cfg.ppo_epochs):
                for mb in self.buffer.minibatches(cfg.minibatch_size):
                    mb = {k: jnp.asarray(v) for k, v in mb.items()}
                    (actor_p, critic_p, self.actor_opt, self.critic_opt,
                     stats) = step_fn(
                        actor_p, critic_p, self.actor_opt,
                        self.critic_opt, mb,
                    )
                    for k, v in stats.items():
                        agg.setdefault(k, []).append(float(v))
                    self.step += 1
        finally:
            # The step donates its inputs (incl. the arrays the engine
            # held), so the engine must always be re-pointed at the
            # latest LIVE buffers — even when a minibatch raises, or the
            # engine is left holding deleted arrays.
            self.engine.set_params(ModelRole.ACTOR, actor_p)
            self.engine.set_params(ModelRole.CRITIC, critic_p)
        self.buffer.clear()
        return {k: float(np.mean(v)) for k, v in agg.items()}

    # -- the outer loop ------------------------------------------------------
    def learn(
        self,
        prompt_iter,
        total_iterations: int,
        *,
        log_every: int = 1,
    ) -> Dict[str, float]:
        """make_experience + train, ``total_iterations`` times
        (reference ``rl_training`` outer loop)."""
        last: Dict[str, float] = {}
        for it in range(total_iterations):
            prompts = next(prompt_iter)
            roll = self.make_experience(np.asarray(prompts))
            stats = self.train()
            last = {**roll, **stats}
            if log_every and it % log_every == 0:
                logger.info(
                    "ppo iter %d | score %.4f kl %.4f loss %.4f",
                    it, roll["score_mean"], roll["kl_mean"],
                    stats.get("loss/total", float("nan")),
                )
        return last
