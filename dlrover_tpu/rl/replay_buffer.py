"""Rollout storage + minibatch iteration (reference
``rl/replay_buffer/replay_buffer.py`` ReplayBuffer over PPORLElement
batches: store experience dicts, shuffle, yield minibatches)."""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np


class ReplayBuffer:
    """Holds one (or more) rollouts of experience as a dict of arrays
    sharing a leading batch dim; iterates shuffled minibatches."""

    def __init__(self, seed: int = 0):
        self._items: List[Dict[str, np.ndarray]] = []
        self.rng = np.random.default_rng(seed)

    def add(self, experience: Dict[str, np.ndarray]) -> None:
        sizes = {k: len(v) for k, v in experience.items()}
        assert len(set(sizes.values())) == 1, f"ragged batch: {sizes}"
        self._items.append(
            {k: np.asarray(v) for k, v in experience.items()}
        )

    def clear(self) -> None:
        self._items.clear()

    def __len__(self) -> int:
        return sum(len(next(iter(it.values()))) for it in self._items)

    def _stacked(self) -> Dict[str, np.ndarray]:
        keys = self._items[0].keys()
        return {
            k: np.concatenate([it[k] for it in self._items]) for k in keys
        }

    def minibatches(
        self, minibatch_size: int, shuffle: bool = True
    ) -> Iterator[Dict[str, np.ndarray]]:
        if not self._items:
            return
        data = self._stacked()
        n = len(next(iter(data.values())))
        order = np.arange(n)
        if shuffle:
            self.rng.shuffle(order)
        for lo in range(0, n - minibatch_size + 1, minibatch_size):
            idx = order[lo : lo + minibatch_size]
            yield {k: v[idx] for k, v in data.items()}
