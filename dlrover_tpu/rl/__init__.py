"""RL engine: PPO post-training for language models, TPU-native.

Parity with reference ``atorch/rl`` (``model_engine/model_engine.py:35``
per-role model management with per-model acceleration strategies,
``ppo_utils/ppo_util.py`` the PPO math, ``replay_buffer/replay_buffer.py``,
``trainer/ppo_trainer.py`` + ``trainer/rl_trainer.py`` the
make-experience -> train loop).  TPU-first shape: the four model roles
(actor, critic, reference, reward) are pytrees + pure apply fns sharded
through ``accelerate()``; generation is a jitted ``lax.scan`` decode; the
PPO update is one pjit'd step over actor+critic jointly.
"""

from dlrover_tpu.rl.config import PPOConfig  # noqa: F401
from dlrover_tpu.rl.engine import ModelEngine, ModelRole  # noqa: F401
from dlrover_tpu.rl.replay_buffer import ReplayBuffer  # noqa: F401
from dlrover_tpu.rl.trainer import PPOTrainer  # noqa: F401
