"""PPO math as pure jnp functions (reference ``rl/ppo_utils/ppo_util.py``:
``get_kl_penalty :19``, ``get_rewards :55``, ``loss :79``,
``get_advantages_and_returns :147``).  Everything here is jit-safe:
static shapes, ``lax.scan`` for the reverse-time GAE recursion, masks for
variable-length responses."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def logprobs_from_logits(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Per-token log p(token) — [B, T, V], [B, T] -> [B, T]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(
        logp, tokens[..., None], axis=-1
    ).squeeze(-1)


def whiten(x: jax.Array, mask: jax.Array, shift_mean: bool = True):
    """Mask-aware whitening (reference ``whiten`` with use_whitening)."""
    denom = jnp.maximum(mask.sum(), 1.0)
    mean = (x * mask).sum() / denom
    var = ((x - mean) ** 2 * mask).sum() / denom
    out = (x - mean) * jax.lax.rsqrt(var + 1e-8)
    if not shift_mean:
        out = out + mean
    return out


def kl_penalty(
    logprobs: jax.Array, ref_logprobs: jax.Array
) -> jax.Array:
    """Per-token KL estimate between actor and frozen reference
    (reference ``get_kl_penalty``: logprob difference on the sampled
    tokens)."""
    return logprobs - ref_logprobs


def compute_rewards(
    scores: jax.Array,       # [B] sequence-level reward-model scores
    logprobs: jax.Array,     # [B, T] actor logprobs of the response
    ref_logprobs: jax.Array, # [B, T]
    mask: jax.Array,         # [B, T] 1 on response tokens
    kl_coef: float,
) -> Tuple[jax.Array, jax.Array]:
    """Dense rewards: -beta*KL per token, plus the score on each
    sequence's LAST response token (reference ``get_rewards``).
    Returns (rewards [B, T], mean per-sequence KL [B])."""
    kl = kl_penalty(logprobs, ref_logprobs) * mask
    rewards = -kl_coef * kl
    # Index of the last mask=1 position per row.
    last = jnp.maximum(mask.sum(axis=1) - 1, 0).astype(jnp.int32)
    rewards = rewards.at[jnp.arange(rewards.shape[0]), last].add(scores)
    seq_kl = kl.sum(axis=1) / jnp.maximum(mask.sum(axis=1), 1.0)
    return rewards, seq_kl


def gae_advantages(
    values: jax.Array,   # [B, T]
    rewards: jax.Array,  # [B, T]
    mask: jax.Array,     # [B, T]
    gamma: float,
    lam: float,
    use_whitening: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation over the response window
    (reference ``get_advantages_and_returns``): the reverse-time
    recursion is a ``lax.scan`` over T (no Python loop under jit).
    Returns (advantages, returns), both [B, T]."""
    B, T = values.shape
    next_values = jnp.concatenate(
        [values[:, 1:], jnp.zeros((B, 1), values.dtype)], axis=1
    )
    # Tokens past the response end contribute nothing.
    deltas = (rewards + gamma * next_values * mask - values) * mask

    def step(carry, xs):
        delta_t, mask_t = xs
        carry = delta_t + gamma * lam * carry * mask_t
        return carry, carry

    _, adv_rev = jax.lax.scan(
        step,
        jnp.zeros((B,), values.dtype),
        (deltas.T[::-1], mask.T[::-1]),
    )
    advantages = adv_rev[::-1].T * mask
    returns = advantages + values * mask
    if use_whitening:
        advantages = whiten(advantages, mask) * mask
    return jax.lax.stop_gradient(advantages), jax.lax.stop_gradient(returns)


def ppo_loss(
    logprobs: jax.Array,      # [B, T] current actor logprobs
    values: jax.Array,        # [B, T] current critic values
    old_logprobs: jax.Array,  # [B, T] rollout-time actor logprobs
    old_values: jax.Array,    # [B, T] rollout-time critic values
    advantages: jax.Array,    # [B, T]
    returns: jax.Array,       # [B, T]
    mask: jax.Array,          # [B, T]
    *,
    cliprange: float,
    cliprange_value: float,
    vf_coef: float,
    entropy: jax.Array = None,  # [B, T] optional policy entropy
    entropy_coef: float = 0.0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Clipped-surrogate policy loss + clipped value loss (reference
    ``ppo_util.loss :79``).  Returns (scalar loss, stats dict)."""
    n = jnp.maximum(mask.sum(), 1.0)

    ratio = jnp.exp((logprobs - old_logprobs) * mask)
    pg1 = -advantages * ratio
    pg2 = -advantages * jnp.clip(
        ratio, 1.0 - cliprange, 1.0 + cliprange
    )
    pg_loss = (jnp.maximum(pg1, pg2) * mask).sum() / n
    pg_clipfrac = ((pg2 > pg1).astype(jnp.float32) * mask).sum() / n

    v_clipped = old_values + jnp.clip(
        values - old_values, -cliprange_value, cliprange_value
    )
    vf1 = (values - returns) ** 2
    vf2 = (v_clipped - returns) ** 2
    vf_loss = 0.5 * (jnp.maximum(vf1, vf2) * mask).sum() / n
    vf_clipfrac = ((vf2 > vf1).astype(jnp.float32) * mask).sum() / n

    loss = pg_loss + vf_coef * vf_loss
    stats = {
        "loss/policy": pg_loss,
        "loss/value": vf_loss,
        "policy/clipfrac": pg_clipfrac,
        "value/clipfrac": vf_clipfrac,
        "policy/approx_kl": (
            0.5 * ((logprobs - old_logprobs) ** 2 * mask).sum() / n
        ),
        "ratio/mean": (ratio * mask).sum() / n,
    }
    if entropy is not None and entropy_coef > 0.0:
        ent = (entropy * mask).sum() / n
        loss = loss - entropy_coef * ent
        stats["policy/entropy"] = ent
    stats["loss/total"] = loss
    return loss, stats
