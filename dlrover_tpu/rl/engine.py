"""Model engine: the four PPO model roles and their jitted programs.

Parity with reference ``rl/model_engine/model_engine.py:35`` (ModelEngine
holding actor/ref/critic/reward models, applying a per-model acceleration
strategy, exposing train/eval modes and save/load).  TPU-native shape:
each role is (pure apply fn, params pytree); "strategies" are sharding
placements on the params — jit propagates them (GSPMD) — plus donation on
the train step.  Generation is a jitted fixed-length ``lax.scan`` decode
(static shapes; TPU-friendly), the analogue of the reference's separate
inference-mode model unwrapping.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from dlrover_tpu.common.log import logger
from dlrover_tpu.rl.config import PPOConfig
from dlrover_tpu.rl.ppo import logprobs_from_logits


class ModelRole:
    ACTOR = "actor"
    CRITIC = "critic"
    REFERENCE = "reference"
    REWARD = "reward"


class _BoundedCache(dict):
    """Insertion-ordered dict capped at ``maxsize``: free-form prompt
    lengths in long RL runs must not grow the per-length jit memo (and
    XLA executable count) without bound — evict the oldest entry.

    Every eviction logs: a working set larger than ``maxsize`` means a
    recompile per generate call, and that thrash must be visible (fix:
    bucket prompt lengths, or raise ``jit_cache_size``)."""

    def __init__(self, maxsize: int = 16):
        super().__init__()
        self.maxsize = max(1, maxsize)  # 0 would crash the eviction

    def __setitem__(self, key, value):
        if key not in self and len(self) >= self.maxsize:
            evicted = next(iter(self))
            del self[evicted]
            logger.warning(
                "jit memo full (%d entries): evicting key %r for %r — "
                "a working set above the cap recompiles every call; "
                "bucket prompt lengths or raise jit_cache_size",
                self.maxsize, evicted, key,
            )
        super().__setitem__(key, value)


@dataclasses.dataclass
class RoleSpec:
    """One model role: ``apply(params, tokens) -> output``.

    actor/reference outputs logits [B, T, V]; critic outputs values
    [B, T]; reward outputs sequence scores [B].

    ``generate_fn(params, prompts, rng) -> [B, P+R] tokens``, when set
    on the actor, replaces the engine's fallback full-recompute decode
    with an efficient sampler — e.g. :func:`llama_cached_generate`'s
    prefill + KV-cache scan, the analogue of the reference RL stack
    delegating generation to vllm
    (``atorch/rl/model_engine/model_engine.py:35``)."""

    apply_fn: Callable[[Any, jax.Array], jax.Array]
    params: Any
    trainable: bool = False
    generate_fn: Optional[Callable[[Any, jax.Array, jax.Array],
                                   jax.Array]] = None


def llama_cached_generate(cfg, ppo_config: PPOConfig,
                          jit_cache_size: int = 16,
                          quant_kv: bool = False,
                          draft: Optional[Tuple[Any, Any]] = None,
                          draft_k: int = 4) -> Callable:
    """Build an actor ``generate_fn`` backed by the KV-cache decoder
    (``models.llama_infer``: prefill + single-token decode, O(T)
    attention per new token).  Prompts are right-padded to a power-of-
    two BUCKET and decoded through :func:`llama_infer.generate_ragged`
    with their true length, so free-form prompt lengths share a handful
    of compiled programs instead of one per length (ADVICE r3) — pass
    the result as ``RoleSpec(..., generate_fn=...)`` for llama actors
    (VERDICT r2 next #4; reference delegates this to vllm,
    ``atorch/rl/model_engine/model_engine.py:35``).

    ``draft=(draft_params, draft_cfg)`` routes rollouts through BATCHED
    SPECULATIVE decoding (:func:`llama_infer.generate_speculative_batched`,
    the vllm spec-decode role): the draft proposes ``draft_k`` tokens
    per round and the actor verifies them in one chunked ragged
    forward; the sampled-token law is unchanged (rejection sampling),
    only the actor-forward count drops."""
    from dlrover_tpu.models import llama_infer

    jitted: Dict[int, Callable] = _BoundedCache(jit_cache_size)

    def gen(params, prompts, rng):
        plen = int(prompts.shape[1])
        if draft is not None:
            draft_params, draft_cfg = draft
            out, _ = llama_infer.generate_speculative_batched(
                params, cfg, draft_params, draft_cfg, prompts,
                jnp.full((prompts.shape[0],), plen, jnp.int32),
                max_new_tokens=ppo_config.response_length,
                k=draft_k, quant_kv=quant_kv,
                temperature=ppo_config.temperature,
                top_k=ppo_config.top_k, rng=rng,
            )
            return out[:, : plen + ppo_config.response_length]
        if cfg.sliding_window > 0:
            # Windowed models COULD ride the ragged path on a dense
            # cache (llama_infer ring=False), but rollouts are
            # batch-aligned anyway, and generate()'s ROLLING ring
            # buffer keeps decode memory O(window) instead of
            # O(prompt+response) — the reason this per-exact-length
            # jit special case stays (memoized, still bounded).
            if ("win", plen) not in jitted:
                jitted[("win", plen)] = jax.jit(
                    lambda p, pr, r: llama_infer.generate(
                        p, cfg, pr,
                        max_new_tokens=ppo_config.response_length,
                        rng=r,
                        temperature=ppo_config.temperature,
                        top_k=ppo_config.top_k,
                        quant_kv=quant_kv,
                    )
                )
            return jitted[("win", plen)](params, prompts, rng)
        bucket = max(8, 1 << (plen - 1).bit_length())
        if bucket not in jitted:
            def run(p, pr, lens, r):
                out, _ = llama_infer.generate_ragged(
                    p, cfg, pr, lens,
                    max_new_tokens=ppo_config.response_length,
                    rng=r,
                    temperature=ppo_config.temperature,
                    top_k=ppo_config.top_k,
                    quant_kv=quant_kv,
                )
                return out

            jitted[bucket] = jax.jit(run)
        B = prompts.shape[0]
        padded = jnp.zeros((B, bucket), prompts.dtype).at[
            :, :plen
        ].set(prompts)
        lens = jnp.full((B,), plen, jnp.int32)
        out = jitted[bucket](params, padded, lens, rng)
        # Rows are compacted (prompt then continuation), so the RL
        # contract [B, plen + R] is exactly the leading columns.
        return out[:, : plen + ppo_config.response_length]

    return gen


class ModelEngine:
    """Owns role specs and compiles the rollout-side programs."""

    def __init__(
        self,
        roles: Dict[str, RoleSpec],
        config: PPOConfig,
        *,
        reward_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        eos_token: int = -1,
    ):
        """``roles`` must contain actor + critic; reference defaults to a
        frozen copy of the actor's initial params; reward comes from the
        REWARD role or a host ``reward_fn(tokens) -> scores`` (e.g. a
        programmatic verifier — the RLVR shape)."""
        assert ModelRole.ACTOR in roles and ModelRole.CRITIC in roles
        self.roles = dict(roles)
        if ModelRole.REFERENCE not in self.roles:
            actor = self.roles[ModelRole.ACTOR]
            self.roles[ModelRole.REFERENCE] = RoleSpec(
                apply_fn=actor.apply_fn,
                params=jax.tree_util.tree_map(jnp.array, actor.params),
            )
        if reward_fn is None and ModelRole.REWARD not in self.roles:
            raise ValueError("need a REWARD role or a reward_fn")
        self.reward_fn = reward_fn
        self.config = config
        self.eos_token = eos_token
        # Jitted programs are specialized on prompt_len (slicing offsets
        # are static); cache per length so a changed prompt shape rebuilds
        # instead of silently computing with stale offsets.  Bounded:
        # free-form prompt lengths must not grow executables unboundedly.
        self._generate = _BoundedCache()
        self._rollout_forward = _BoundedCache()

    # -- role access (reference get_model/actor/critic properties) ----------
    def params(self, role: str) -> Any:
        return self.roles[role].params

    def set_params(self, role: str, params: Any) -> None:
        self.roles[role].params = params

    def sync_reference_to_actor(self) -> None:
        """Refresh the frozen reference from the current actor (reference
        hybrid-engine weight sync before each experience phase when KL is
        measured against the latest policy)."""
        self.roles[ModelRole.REFERENCE].params = jax.tree_util.tree_map(
            jnp.array, self.roles[ModelRole.ACTOR].params
        )

    # -- generation ----------------------------------------------------------
    def _build_generate(self, prompt_len: int):
        cfg = self.config
        actor = self.roles[ModelRole.ACTOR]
        R = cfg.response_length

        def generate(params, prompts, rng):
            B = prompts.shape[0]
            buf = jnp.concatenate(
                [prompts, jnp.zeros((B, R), prompts.dtype)], axis=1
            )

            def step(carry, i):
                buf, rng = carry
                rng, sub = jax.random.split(rng)
                logits = actor.apply_fn(params, buf)
                pos = prompt_len + i - 1
                if cfg.temperature <= 0.0:
                    # Greedy — same contract as the KV-cache path
                    # (llama_infer.generate); dividing by 0 would NaN.
                    tok = jnp.argmax(logits[:, pos, :], axis=-1)
                    buf = buf.at[:, prompt_len + i].set(
                        tok.astype(buf.dtype)
                    )
                    return (buf, rng), None
                next_logits = logits[:, pos, :] / cfg.temperature
                if cfg.top_k > 0:
                    kth = jnp.sort(next_logits, axis=-1)[
                        :, -cfg.top_k, None
                    ]
                    next_logits = jnp.where(
                        next_logits < kth, -jnp.inf, next_logits
                    )
                tok = jax.random.categorical(sub, next_logits)
                buf = buf.at[:, prompt_len + i].set(
                    tok.astype(buf.dtype)
                )
                return (buf, rng), None

            (buf, _), _ = jax.lax.scan(
                step, (buf, rng), jnp.arange(R)
            )
            return buf

        return jax.jit(generate)

    def generate(
        self, prompts: jax.Array, rng: jax.Array
    ) -> jax.Array:
        """Sample ``response_length`` tokens after each prompt; returns
        the full [B, P+R] token buffer.  Uses the actor's ``generate_fn``
        (KV-cache decode, O(T) per token) when provided; the fallback is
        the full-recompute scan (O(T^2) — fine for tiny policies, not
        for transformer rollouts)."""
        actor = self.roles[ModelRole.ACTOR]
        if actor.generate_fn is not None:
            return actor.generate_fn(actor.params, prompts, rng)
        plen = int(prompts.shape[1])
        if plen not in self._generate:
            self._generate[plen] = self._build_generate(plen)
        return self._generate[plen](
            self.params(ModelRole.ACTOR), prompts, rng
        )

    # -- rollout-side forward (logprobs, ref logprobs, values) ---------------
    def _build_rollout_forward(self, prompt_len: int):
        actor = self.roles[ModelRole.ACTOR]
        ref = self.roles[ModelRole.REFERENCE]
        critic = self.roles[ModelRole.CRITIC]
        R = self.config.response_length

        def forward(actor_p, ref_p, critic_p, tokens):
            # Response tokens are predicted from the previous position.
            resp = tokens[:, prompt_len : prompt_len + R]
            logits = actor.apply_fn(actor_p, tokens)[
                :, prompt_len - 1 : prompt_len + R - 1, :
            ]
            ref_logits = ref.apply_fn(ref_p, tokens)[
                :, prompt_len - 1 : prompt_len + R - 1, :
            ]
            values = critic.apply_fn(critic_p, tokens)[
                :, prompt_len : prompt_len + R
            ]
            return (
                logprobs_from_logits(logits, resp),
                logprobs_from_logits(ref_logits, resp),
                values,
            )

        return jax.jit(forward)

    def rollout_forward(self, tokens: jax.Array, prompt_len: int):
        if prompt_len not in self._rollout_forward:
            self._rollout_forward[prompt_len] = (
                self._build_rollout_forward(prompt_len)
            )
        return self._rollout_forward[prompt_len](
            self.params(ModelRole.ACTOR),
            self.params(ModelRole.REFERENCE),
            self.params(ModelRole.CRITIC),
            tokens,
        )

    def score(self, tokens: np.ndarray) -> np.ndarray:
        """Sequence-level rewards from the reward model or host fn."""
        if self.reward_fn is not None:
            return np.asarray(self.reward_fn(np.asarray(tokens)))
        spec = self.roles[ModelRole.REWARD]
        return np.asarray(spec.apply_fn(spec.params, jnp.asarray(tokens)))

    def response_mask(self, tokens: jax.Array, prompt_len: int):
        """[B, R] mask: 1 up to and including the first EOS (if any)."""
        R = self.config.response_length
        resp = tokens[:, prompt_len : prompt_len + R]
        if self.eos_token < 0:
            return jnp.ones(resp.shape, jnp.float32)
        is_eos = (resp == self.eos_token).astype(jnp.int32)
        after_eos = jnp.cumsum(
            jnp.concatenate(
                [jnp.zeros_like(is_eos[:, :1]), is_eos[:, :-1]], axis=1
            ),
            axis=1,
        )
        return (after_eos == 0).astype(jnp.float32)

    # -- persistence (reference ModelEngine.save/load) -----------------------
    def save(self, ckpt, step: int, opt_states: Optional[dict] = None
             ) -> None:
        """Stage all roles (+ optimizer states) through a
        FlashCheckpointer."""
        state = {
            r: spec.params for r, spec in self.roles.items()
        }
        if opt_states:
            state["opt"] = opt_states
        ckpt.save(state, meta={"step": step}, storage=True)

    def load(
        self, ckpt, opt_template: Optional[dict] = None
    ) -> Optional[Tuple[int, Optional[dict]]]:
        """Restore all roles; pass the optimizer-state pytree structure as
        ``opt_template`` to get the saved optimizer state back too (the
        restore target must contain the key for it to be filled)."""
        state = {r: spec.params for r, spec in self.roles.items()}
        if opt_template is not None:
            state["opt"] = opt_template
        restored = ckpt.load(target=state)
        if restored is None:
            return None
        got, meta = restored
        opt = got.pop("opt", None)
        for r, params in got.items():
            if r in self.roles:
                self.roles[r].params = params
        logger.info("rl engine: restored step %s", meta.get("step"))
        return int(meta.get("step", 0)), opt
