"""On-disk shard file format + the commit protocol helpers.

One shard file per process per step::

    <ckpt_dir>/step_<N>/shard_<process_id>.ckpt     (header|meta|tensor data)
    <ckpt_dir>/step_<N>/.done_<process_id>          (done file, commit vote)
    <ckpt_dir>/step_<N>/checkpoint.meta             (world info, leader)
    <ckpt_dir>/latest_checkpointed_step.txt         (tracker, written last)

Mirrors the reference's done-file + tracker commit
(``ckpt_saver.py commit_checkpoint :822``): a step directory is valid iff the
tracker names it, and the tracker is only advanced after every shard's done
file exists — a crash mid-persist leaves the previous step intact.

Format v2 (magic ``DLRTPUF2``) adds end-to-end integrity: the 20-byte header
carries a CRC-32 of the msgpack meta blob, and every tensor's meta carries a
CRC-32 of its data blob, both computed on :func:`pack_shard` and verified on
:func:`unpack_shard`/:func:`verify_shard`.  v1 shards (``DLRTPUF1``, no CRCs)
remain readable — only structural checks apply to them.  Every way a payload
can be damaged (short file, bad magic, meta past EOF, undecodable meta, blob
out of bounds, CRC mismatch, garbage dtype/shape) surfaces as one typed
:class:`ShardCorruptionError`, which the restore ladder treats like absence
and :mod:`dlrover_tpu.checkpoint.fsck` reports to operators.  A step that
fails verification is **quarantined** (:func:`quarantine_step`): its dir is
renamed ``step_N.corrupt`` (marker file on backends without rename) and
excluded from :func:`list_steps`, restore candidates, and rotation.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from typing import Dict, Optional, Tuple

import msgpack
import numpy as np

from dlrover_tpu import chaos
from dlrover_tpu.common.constants import CheckpointConstant as CC
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.native import shm_lib
from dlrover_tpu.common.storage import CheckpointStorage

FORMAT_VERSION = 2
_MAGIC_V1 = b"DLRTPUF1"
_MAGIC = b"DLRTPUF2"
_V1_HEADER = 16  # magic u64 | meta_len u64
_V2_HEADER = 20  # magic u64 | meta_len u64 | meta_crc u32

# Below this size the ctypes round-trip costs more than it saves; zlib's
# C loop is already fast for small buffers.
_NATIVE_CRC_MIN_BYTES = 1 << 20

QUARANTINE_SUFFIX = ".corrupt"
QUARANTINE_MARKER = ".quarantined"


class ShardCorruptionError(Exception):
    """A shard payload failed structural or CRC verification.

    The one exception type for every corruption mode, so callers (restore
    ladder, replica exchange, fsck) can treat damage uniformly — skip the
    shard, fall through to an older step — instead of crashing on raw
    ``struct.error``/``ValueError`` from whichever parse line tripped.
    """

    def __init__(self, reason: str, path: str = ""):
        self.reason = reason
        self.path = path
        super().__init__(f"{path}: {reason}" if path else reason)


def shard_version(data: bytes) -> Optional[int]:
    """Format version by magic (1 or 2), or ``None`` for foreign bytes."""
    magic = bytes(data[:8])
    if magic == _MAGIC:
        return 2
    if magic == _MAGIC_V1:
        return 1
    return None


def crc32_bytes(buf) -> int:
    """CRC-32 (zlib polynomial) of a bytes-like buffer.

    Large buffers go through the native ``shm_crc32`` kernel
    (``native/shm_arena.cc``) when the toolchain built it — same
    polynomial, same result — with ``zlib.crc32`` as the fallback."""
    if len(buf) >= _NATIVE_CRC_MIN_BYTES:
        lib = shm_lib()
        if lib is not None:
            arr = np.frombuffer(buf, dtype=np.uint8)
            return int(lib.shm_crc32(arr.ctypes.data, arr.nbytes, 0))
    return zlib.crc32(buf) & 0xFFFFFFFF


def step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step_{step:010d}")


def shard_path(ckpt_dir: str, step: int, process_id: int) -> str:
    return os.path.join(step_dir(ckpt_dir, step), f"shard_{process_id:05d}.ckpt")


def done_path(ckpt_dir: str, step: int, process_id: int) -> str:
    return os.path.join(step_dir(ckpt_dir, step), f".done_{process_id:05d}")


def tracker_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, CC.TRACKER_FILE)


def pack_shard(tensors: Dict[str, np.ndarray], extra: dict) -> bytes:
    metas = {}
    blobs = []
    offset = 0
    for key, arr in tensors.items():
        shape = list(np.shape(arr))
        # ascontiguousarray promotes 0-d to 1-d; keep the true shape.
        arr = np.ascontiguousarray(arr)
        try:
            dtype_key = (
                arr.dtype.name
                if np.dtype(arr.dtype.name) == arr.dtype
                else arr.dtype.str
            )
        except TypeError:
            dtype_key = arr.dtype.str
        blob = arr.reshape(-1).view(np.uint8).tobytes()
        metas[key] = {
            "dtype": dtype_key,
            "shape": shape,
            "offset": offset,
            "nbytes": int(arr.nbytes),
            "crc32": crc32_bytes(blob),
        }
        blobs.append(blob)
        offset += arr.nbytes
    meta_blob = msgpack.packb(
        {"format": FORMAT_VERSION, "tensors": metas, "extra": extra},
        use_bin_type=True,
    )
    header = _MAGIC + struct.pack("<QI", len(meta_blob), crc32_bytes(meta_blob))
    return header + meta_blob + b"".join(blobs)


def _parse_meta(data: bytes, path: str = "") -> Tuple[dict, int, int]:
    """Validate header + meta blob; returns (meta, data_base, version).

    Every structural defect — not just the happy-path magic check —
    raises :class:`ShardCorruptionError`."""
    if len(data) < _V1_HEADER:
        raise ShardCorruptionError(
            f"file shorter than the shard header ({len(data)} bytes)", path
        )
    magic = bytes(data[:8])
    if magic == _MAGIC:
        version = 2
        if len(data) < _V2_HEADER:
            raise ShardCorruptionError("v2 header truncated", path)
        meta_len, meta_crc = struct.unpack("<QI", data[8:_V2_HEADER])
        base = _V2_HEADER
    elif magic == _MAGIC_V1:
        version = 1
        (meta_len,) = struct.unpack("<Q", data[8:_V1_HEADER])
        meta_crc = None
        base = _V1_HEADER
    else:
        raise ShardCorruptionError(
            f"bad magic {magic!r} — not a dlrover_tpu shard", path
        )
    if base + meta_len > len(data):
        raise ShardCorruptionError(
            f"meta region ({meta_len}B) extends past EOF "
            f"({len(data)}B file)", path,
        )
    meta_raw = bytes(data[base : base + meta_len])
    if meta_crc is not None and crc32_bytes(meta_raw) != meta_crc:
        raise ShardCorruptionError("meta CRC mismatch", path)
    try:
        meta = msgpack.unpackb(meta_raw, raw=False)
    except Exception as e:  # noqa: BLE001 - any decode failure is corruption
        raise ShardCorruptionError(f"meta blob undecodable: {e}", path) from e
    if (
        not isinstance(meta, dict)
        or not isinstance(meta.get("tensors"), dict)
        or not isinstance(meta.get("extra"), dict)
    ):
        raise ShardCorruptionError("meta structure invalid", path)
    return meta, base + meta_len, version


def _tensor_blob(data: bytes, base: int, key: str, tm, path: str):
    """Bounds-checked zero-copy view of one tensor's bytes."""
    try:
        offset = int(tm["offset"])
        nbytes = int(tm["nbytes"])
    except (KeyError, TypeError, ValueError) as e:
        raise ShardCorruptionError(
            f"tensor {key!r} meta invalid: {e}", path
        ) from e
    if offset < 0 or nbytes < 0 or base + offset + nbytes > len(data):
        raise ShardCorruptionError(
            f"tensor {key!r} blob (offset={offset}, nbytes={nbytes}) "
            "truncated or out of bounds", path,
        )
    return memoryview(data)[base + offset : base + offset + nbytes]


def _check_tensor_crc(buf, key: str, tm, version: int, path: str) -> None:
    if version < 2:
        return  # v1 shards carry no CRCs
    want = tm.get("crc32")
    if not isinstance(want, int):
        raise ShardCorruptionError(
            f"tensor {key!r} missing crc32 in v2 meta", path
        )
    if crc32_bytes(buf) != want:
        raise ShardCorruptionError(
            f"tensor {key!r} CRC mismatch (bit rot or torn write)", path
        )


def verify_shard(data: bytes, path: str = "") -> dict:
    """Full integrity check without materializing arrays: header, meta CRC,
    per-tensor bounds + CRCs.  Returns the shard's ``extra`` metadata;
    raises :class:`ShardCorruptionError` on any damage."""
    meta, base, version = _parse_meta(data, path)
    for key, tm in meta["tensors"].items():
        buf = _tensor_blob(data, base, key, tm, path)
        _check_tensor_crc(buf, key, tm, version, path)
    return meta["extra"]


def unpack_shard(
    data: bytes, path: str = ""
) -> Tuple[Dict[str, np.ndarray], dict]:
    """Decode (and verify) a shard payload; ``path`` only labels errors."""
    meta, base, version = _parse_meta(data, path)
    tensors = {}
    for key, tm in meta["tensors"].items():
        buf = _tensor_blob(data, base, key, tm, path)
        _check_tensor_crc(buf, key, tm, version, path)
        try:
            arr = (
                np.frombuffer(buf, dtype=np.dtype(tm["dtype"]))
                .reshape(tm["shape"])
                .copy()
            )
        except Exception as e:  # noqa: BLE001 - garbage dtype/shape meta
            raise ShardCorruptionError(
                f"tensor {key!r} undecodable: {e}", path
            ) from e
        tensors[key] = arr
    return tensors, meta["extra"]


def validate_staged_state(
    tensors,
    extra,
    *,
    expect_process_id: Optional[int] = None,
    expect_num_processes: Optional[int] = None,
) -> Optional[str]:
    """Sanity-check a shm-staged state before it is persisted or
    replicated.  Returns a rejection reason, or ``None`` when coherent —
    a torn arena read must never become a committed shard."""
    if not isinstance(tensors, dict) or not tensors:
        return "no tensors staged"
    if not isinstance(extra, dict):
        return "extra metadata missing"
    try:
        step = int(extra.get("step"))
    except (TypeError, ValueError):
        return f"staged step {extra.get('step')!r} is not an int"
    if step < 0:
        return f"staged step {step} is negative"
    if not extra.get("tensors_info"):
        return "tensors_info missing (state could never be reassembled)"
    pid = extra.get("process_id")
    if (
        expect_process_id is not None
        and pid is not None
        and int(pid) != int(expect_process_id)
    ):
        return f"staged process_id {pid} != expected {expect_process_id}"
    world = extra.get("num_processes")
    if (
        expect_num_processes is not None
        and world is not None
        and int(world) != int(expect_num_processes)
    ):
        return f"staged num_processes {world} != expected {expect_num_processes}"
    return None


def _chaos_damage_blob(blob: bytes, step: int, process_id: int) -> bytes:
    """Data-corruption chaos sites, applied to the packed payload just
    before the storage write — the written file carries the damage while
    the done-file/commit protocol proceeds normally, exactly the silent
    bit-rot / torn-write scenario the restore ladder must survive."""
    if chaos.inject(
        "storage.corrupt_shard", step=step, rank=process_id
    ) is not None:
        # Flip a byte near the tail (tensor data region when any tensor
        # bytes exist, meta otherwise — both are CRC-covered).
        damaged = bytearray(blob)
        damaged[max(0, len(damaged) - 7)] ^= 0xFF
        blob = bytes(damaged)
    if chaos.inject(
        "storage.truncate_shard", step=step, rank=process_id
    ) is not None:
        blob = blob[: max(1, len(blob) // 2)]
    return blob


def write_shard(
    storage: CheckpointStorage,
    ckpt_dir: str,
    step: int,
    process_id: int,
    tensors: Dict[str, np.ndarray],
    extra: dict,
) -> None:
    storage.safe_makedirs(step_dir(ckpt_dir, step))
    blob = _chaos_damage_blob(pack_shard(tensors, extra), step, process_id)
    storage.write(blob, shard_path(ckpt_dir, step, process_id))
    storage.write(str(time.time()), done_path(ckpt_dir, step, process_id))


def read_shard(
    storage: CheckpointStorage, ckpt_dir: str, step: int, process_id: int
) -> Optional[Tuple[Dict[str, np.ndarray], dict]]:
    """Read + verify one shard.  ``None`` when absent; raises
    :class:`ShardCorruptionError` (with the path filled in) on damage."""
    path = shard_path(ckpt_dir, step, process_id)
    data = storage.read(path)
    if data is None:
        return None
    return unpack_shard(data, path=path)


def list_shard_ids(storage: CheckpointStorage, ckpt_dir: str, step: int) -> list:
    out = []
    for name in storage.listdir(step_dir(ckpt_dir, step)):
        if name.startswith("shard_") and name.endswith(".ckpt"):
            out.append(int(name[len("shard_") : -len(".ckpt")]))
    return sorted(out)


def all_shards_done(
    storage: CheckpointStorage, ckpt_dir: str, step: int, world_size: int
) -> bool:
    return all(
        storage.exists(done_path(ckpt_dir, step, pid))
        for pid in range(world_size)
    )


def wait_sync_barrier(client, step: int, timeout: float,
                      stop_event=None) -> bool:
    """Bounded wait on the master's cross-node step barrier before commit.

    The barrier is advisory (skew detection) — the done files are the real
    commit votes — so a master that restarted and lost its rendezvous
    state (the barrier can then never open) or died outright must not
    block durability past ``timeout``.  Returns True once the barrier
    opened; False on timeout or when ``stop_event`` was set."""
    if client is None:
        return True
    deadline = time.time() + timeout
    while time.time() < deadline:
        if stop_event is not None and stop_event.is_set():
            return False
        try:
            if client.sync_checkpoint(step):
                return True
        except Exception as e:  # noqa: BLE001
            logger.debug(
                "sync_checkpoint(%d) RPC failed (retrying): %s", step, e
            )
        time.sleep(0.5)
    return False


def resolve_keep_last(max_to_keep) -> int:
    """One home for the rotation contract: ``None`` -> default (keep 3),
    ``0`` -> keep ALL step dirs, ``N > 0`` -> keep the newest N."""
    return 3 if max_to_keep is None else int(max_to_keep)


def commit(
    storage: CheckpointStorage, ckpt_dir: str, step: int, keep_last: int = 3
) -> None:
    """Advance the tracker and GC old step dirs (leader only).

    The tracker write is the atomic commit point (temp + fsync + rename):
    a crash before it leaves the previous committed step intact; a crash
    after it leaves this step fully committed.  The two chaos sites below
    pin down exactly those two halves.
    """
    chaos.inject("ckpt.crash_before_commit", step=step)
    storage.write(str(step), tracker_path(ckpt_dir))
    chaos.inject("ckpt.crash_after_commit", step=step)
    logger.info("checkpoint step %d committed at %s", step, ckpt_dir)
    # Rotation only counts live steps: quarantined dirs are operator
    # evidence, neither GC'd here nor taking a keep_last slot.
    steps = list_steps(storage, ckpt_dir)
    for old in sorted(steps)[:-keep_last] if keep_last > 0 else []:
        if old != step:
            storage.safe_rmtree(step_dir(ckpt_dir, old))


def is_step_quarantined(
    storage: CheckpointStorage, ckpt_dir: str, step: int
) -> bool:
    """Marker-file quarantine check (backends without directory rename)."""
    return storage.exists(
        os.path.join(step_dir(ckpt_dir, step), QUARANTINE_MARKER)
    )


def quarantine_step(
    storage: CheckpointStorage, ckpt_dir: str, step: int
) -> Optional[str]:
    """Exclude a verification-failed step from every restore path.

    Renames ``step_N`` -> ``step_N.corrupt`` (atomic on POSIX); backends
    without directory rename get a ``.quarantined`` marker file instead.
    Both forms are invisible to :func:`list_steps` and rotation but kept
    on disk as operator evidence for ``checkpoint.fsck``.  Returns the
    quarantined path, or ``None`` when the dir was already gone (e.g. a
    concurrent rank won the rename race)."""
    src = step_dir(ckpt_dir, step)
    if not storage.exists(src):
        return None
    dst = src + QUARANTINE_SUFFIX
    if storage.rename_dir(src, dst):
        logger.warning("checkpoint step %d quarantined -> %s", step, dst)
        return dst
    try:
        storage.write(
            str(time.time()), os.path.join(src, QUARANTINE_MARKER)
        )
    except Exception as e:  # noqa: BLE001 - dir raced away mid-quarantine
        logger.warning("quarantine of step %d failed: %s", step, e)
        return None
    logger.warning(
        "checkpoint step %d quarantined in place (marker file)", step
    )
    return src


def list_steps(storage: CheckpointStorage, ckpt_dir: str) -> list:
    """All step numbers with a live step dir present (committed or not);
    quarantined dirs (renamed or marker) are excluded."""
    steps = []
    for name in storage.listdir(ckpt_dir):
        if not name.startswith("step_") or name.endswith(QUARANTINE_SUFFIX):
            continue
        try:
            step = int(name[len("step_"):])
        except ValueError:
            continue
        if is_step_quarantined(storage, ckpt_dir, step):
            continue
        steps.append(step)
    return steps


def list_quarantined(storage: CheckpointStorage, ckpt_dir: str) -> list:
    """(step, dirpath) per quarantined step dir, either form."""
    out = []
    for name in storage.listdir(ckpt_dir):
        if not name.startswith("step_"):
            continue
        if name.endswith(QUARANTINE_SUFFIX):
            try:
                step = int(
                    name[len("step_") : -len(QUARANTINE_SUFFIX)]
                )
            except ValueError:
                continue
            out.append((step, os.path.join(ckpt_dir, name)))
        else:
            try:
                step = int(name[len("step_"):])
            except ValueError:
                continue
            if is_step_quarantined(storage, ckpt_dir, step):
                out.append((step, os.path.join(ckpt_dir, name)))
    return sorted(out)


def latest_step(storage: CheckpointStorage, ckpt_dir: str) -> Optional[int]:
    content = storage.read(tracker_path(ckpt_dir), mode="r")
    if not content:
        return None
    try:
        return int(str(content).strip())
    except ValueError:
        return None
