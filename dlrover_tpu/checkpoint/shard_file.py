"""On-disk shard file format + the commit protocol helpers.

One shard file per process per step::

    <ckpt_dir>/step_<N>/shard_<process_id>.ckpt     (header|meta|tensor data)
    <ckpt_dir>/step_<N>/.done_<process_id>          (done file, commit vote)
    <ckpt_dir>/step_<N>/checkpoint.meta             (world info, leader)
    <ckpt_dir>/latest_checkpointed_step.txt         (tracker, written last)

Mirrors the reference's done-file + tracker commit
(``ckpt_saver.py commit_checkpoint :822``): a step directory is valid iff the
tracker names it, and the tracker is only advanced after every shard's done
file exists — a crash mid-persist leaves the previous step intact.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Dict, Optional, Tuple

import msgpack
import numpy as np

from dlrover_tpu import chaos
from dlrover_tpu.common.constants import CheckpointConstant as CC
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.storage import CheckpointStorage

_MAGIC = b"DLRTPUF1"


def step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step_{step:010d}")


def shard_path(ckpt_dir: str, step: int, process_id: int) -> str:
    return os.path.join(step_dir(ckpt_dir, step), f"shard_{process_id:05d}.ckpt")


def done_path(ckpt_dir: str, step: int, process_id: int) -> str:
    return os.path.join(step_dir(ckpt_dir, step), f".done_{process_id:05d}")


def tracker_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, CC.TRACKER_FILE)


def pack_shard(tensors: Dict[str, np.ndarray], extra: dict) -> bytes:
    metas = {}
    blobs = []
    offset = 0
    for key, arr in tensors.items():
        shape = list(np.shape(arr))
        # ascontiguousarray promotes 0-d to 1-d; keep the true shape.
        arr = np.ascontiguousarray(arr)
        try:
            dtype_key = (
                arr.dtype.name
                if np.dtype(arr.dtype.name) == arr.dtype
                else arr.dtype.str
            )
        except TypeError:
            dtype_key = arr.dtype.str
        metas[key] = {
            "dtype": dtype_key,
            "shape": shape,
            "offset": offset,
            "nbytes": int(arr.nbytes),
        }
        blobs.append(arr.reshape(-1).view(np.uint8).tobytes())
        offset += arr.nbytes
    meta_blob = msgpack.packb(
        {"tensors": metas, "extra": extra}, use_bin_type=True
    )
    header = _MAGIC + struct.pack("<Q", len(meta_blob))
    return header + meta_blob + b"".join(blobs)


def unpack_shard(data: bytes) -> Tuple[Dict[str, np.ndarray], dict]:
    if data[:8] != _MAGIC:
        raise ValueError("not a dlrover_tpu shard file")
    (meta_len,) = struct.unpack("<Q", data[8:16])
    meta = msgpack.unpackb(data[16 : 16 + meta_len], raw=False)
    base = 16 + meta_len
    tensors = {}
    for key, tm in meta["tensors"].items():
        start = base + tm["offset"]
        buf = data[start : start + tm["nbytes"]]
        tensors[key] = np.frombuffer(buf, dtype=np.dtype(tm["dtype"])).reshape(
            tm["shape"]
        ).copy()
    return tensors, meta["extra"]


def write_shard(
    storage: CheckpointStorage,
    ckpt_dir: str,
    step: int,
    process_id: int,
    tensors: Dict[str, np.ndarray],
    extra: dict,
) -> None:
    storage.safe_makedirs(step_dir(ckpt_dir, step))
    storage.write(pack_shard(tensors, extra), shard_path(ckpt_dir, step, process_id))
    storage.write(str(time.time()), done_path(ckpt_dir, step, process_id))


def read_shard(
    storage: CheckpointStorage, ckpt_dir: str, step: int, process_id: int
) -> Optional[Tuple[Dict[str, np.ndarray], dict]]:
    data = storage.read(shard_path(ckpt_dir, step, process_id))
    if data is None:
        return None
    return unpack_shard(data)


def list_shard_ids(storage: CheckpointStorage, ckpt_dir: str, step: int) -> list:
    out = []
    for name in storage.listdir(step_dir(ckpt_dir, step)):
        if name.startswith("shard_") and name.endswith(".ckpt"):
            out.append(int(name[len("shard_") : -len(".ckpt")]))
    return sorted(out)


def all_shards_done(
    storage: CheckpointStorage, ckpt_dir: str, step: int, world_size: int
) -> bool:
    return all(
        storage.exists(done_path(ckpt_dir, step, pid))
        for pid in range(world_size)
    )


def wait_sync_barrier(client, step: int, timeout: float,
                      stop_event=None) -> bool:
    """Bounded wait on the master's cross-node step barrier before commit.

    The barrier is advisory (skew detection) — the done files are the real
    commit votes — so a master that restarted and lost its rendezvous
    state (the barrier can then never open) or died outright must not
    block durability past ``timeout``.  Returns True once the barrier
    opened; False on timeout or when ``stop_event`` was set."""
    if client is None:
        return True
    deadline = time.time() + timeout
    while time.time() < deadline:
        if stop_event is not None and stop_event.is_set():
            return False
        try:
            if client.sync_checkpoint(step):
                return True
        except Exception as e:  # noqa: BLE001
            logger.debug(
                "sync_checkpoint(%d) RPC failed (retrying): %s", step, e
            )
        time.sleep(0.5)
    return False


def resolve_keep_last(max_to_keep) -> int:
    """One home for the rotation contract: ``None`` -> default (keep 3),
    ``0`` -> keep ALL step dirs, ``N > 0`` -> keep the newest N."""
    return 3 if max_to_keep is None else int(max_to_keep)


def commit(
    storage: CheckpointStorage, ckpt_dir: str, step: int, keep_last: int = 3
) -> None:
    """Advance the tracker and GC old step dirs (leader only).

    The tracker write is the atomic commit point (temp + fsync + rename):
    a crash before it leaves the previous committed step intact; a crash
    after it leaves this step fully committed.  The two chaos sites below
    pin down exactly those two halves.
    """
    chaos.inject("ckpt.crash_before_commit", step=step)
    storage.write(str(step), tracker_path(ckpt_dir))
    chaos.inject("ckpt.crash_after_commit", step=step)
    logger.info("checkpoint step %d committed at %s", step, ckpt_dir)
    steps = []
    for name in storage.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                steps.append(int(name[len("step_"):]))
            except ValueError:
                pass
    for old in sorted(steps)[:-keep_last] if keep_last > 0 else []:
        if old != step:
            storage.safe_rmtree(step_dir(ckpt_dir, old))


def list_steps(storage: CheckpointStorage, ckpt_dir: str) -> list:
    """All step numbers with a step dir present (committed or not)."""
    steps = []
    for name in storage.listdir(ckpt_dir):
        if name.startswith("step_"):
            try:
                steps.append(int(name[len("step_"):]))
            except ValueError:
                pass
    return steps


def latest_step(storage: CheckpointStorage, ckpt_dir: str) -> Optional[int]:
    content = storage.read(tracker_path(ckpt_dir), mode="r")
    if not content:
        return None
    try:
        return int(str(content).strip())
    except ValueError:
        return None
