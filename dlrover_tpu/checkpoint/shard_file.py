"""On-disk shard file format + the commit protocol helpers.

One shard file per process per step::

    <ckpt_dir>/step_<N>/shard_<process_id>.ckpt     (header|meta|tensor data)
    <ckpt_dir>/step_<N>/.done_<process_id>          (done file, commit vote)
    <ckpt_dir>/step_<N>/checkpoint.meta             (world info, leader)
    <ckpt_dir>/latest_checkpointed_step.txt         (tracker, written last)

Mirrors the reference's done-file + tracker commit
(``ckpt_saver.py commit_checkpoint :822``): a step directory is valid iff the
tracker names it, and the tracker is only advanced after every shard's done
file exists — a crash mid-persist leaves the previous step intact.

Format v2 (magic ``DLRTPUF2``) adds end-to-end integrity: the 20-byte header
carries a CRC-32 of the msgpack meta blob, and every tensor's meta carries a
CRC-32 of its data blob, both computed on :func:`pack_shard` and verified on
:func:`unpack_shard`/:func:`verify_shard`.  v1 shards (``DLRTPUF1``, no CRCs)
remain readable — only structural checks apply to them.  Every way a payload
can be damaged (short file, bad magic, meta past EOF, undecodable meta, blob
out of bounds, CRC mismatch, garbage dtype/shape) surfaces as one typed
:class:`ShardCorruptionError`, which the restore ladder treats like absence
and :mod:`dlrover_tpu.checkpoint.fsck` reports to operators.  A step that
fails verification is **quarantined** (:func:`quarantine_step`): its dir is
renamed ``step_N.corrupt`` (marker file on backends without rename) and
excluded from :func:`list_steps`, restore candidates, and rotation.

Two writers produce the same bytes: :func:`pack_shard` (reference
implementation, materializes the blob) and :class:`ShardStreamWriter` /
:func:`write_shard_from_views` (the hot path: streams tensor bytes
straight from the caller's views — typically the shm arena mapping — in
bounded chunks, CRC folded into the same single pass, zero intermediate
full-state buffers, optional parallel range workers).
:func:`verify_shard_file` is the bounded-memory counterpart of
:func:`verify_shard` for shards larger than RAM headroom.
"""

from __future__ import annotations

import dataclasses
import os
import struct
import time
import zlib
from typing import Dict, Iterable, Optional, Set, Tuple

import msgpack
import numpy as np

from dlrover_tpu import chaos
from dlrover_tpu.common.byte_audit import audit
from dlrover_tpu.common.constants import CheckpointConstant as CC
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.native import shm_lib
from dlrover_tpu.common.storage import CheckpointStorage, drain_ranges

FORMAT_VERSION = 2
_MAGIC_V1 = b"DLRTPUF1"
_MAGIC = b"DLRTPUF2"
_V1_HEADER = 16  # magic u64 | meta_len u64
_V2_HEADER = 20  # magic u64 | meta_len u64 | meta_crc u32

# Below this size the ctypes round-trip costs more than it saves; zlib's
# C loop is already fast for small buffers.
_NATIVE_CRC_MIN_BYTES = 1 << 20

# Streaming writer: bytes per write/CRC chunk.  Large enough that syscall
# and ctypes overheads vanish, small enough to bound resident pressure.
STREAM_CHUNK_BYTES = 8 << 20

# Chunked-verify meta-read ceiling (see verify_shard_file): far above any
# real meta blob, far below "materialize the data region by accident".
_VERIFY_META_CAP = 256 << 20

# Meta placeholder for the single-pass streamed write: tensor CRCs are only
# known after the data pass, but the meta region (which *contains* them)
# precedes the data in the file.  msgpack minimally encodes ints, so the
# meta's byte length depends on the CRC values; 0xFFFFFFFF pins each
# placeholder to msgpack's 5-byte uint32 form — the same width as any real
# CRC >= 65536.  A shard whose every tensor CRC matches that width (all but
# ~1.5e-5 per tensor) gets its header+meta patched in place after the one
# data pass; otherwise the writer re-streams at the corrected base (rare
# second pass, counted by the byte audit).
_CRC_PLACEHOLDER = 0xFFFFFFFF

QUARANTINE_SUFFIX = ".corrupt"
QUARANTINE_MARKER = ".quarantined"


class ShardCorruptionError(Exception):
    """A shard payload failed structural or CRC verification.

    The one exception type for every corruption mode, so callers (restore
    ladder, replica exchange, fsck) can treat damage uniformly — skip the
    shard, fall through to an older step — instead of crashing on raw
    ``struct.error``/``ValueError`` from whichever parse line tripped.
    """

    def __init__(self, reason: str, path: str = ""):
        self.reason = reason
        self.path = path
        super().__init__(f"{path}: {reason}" if path else reason)


def shard_version(data: bytes) -> Optional[int]:
    """Format version by magic (1 or 2), or ``None`` for foreign bytes."""
    magic = bytes(data[:8])
    if magic == _MAGIC:
        return 2
    if magic == _MAGIC_V1:
        return 1
    return None


_NATIVE_CRC_FASTER: Optional[bool] = None


def _native_crc_faster() -> bool:
    """One-time measured choice between the native ``shm_crc32`` kernel
    and ``zlib.crc32`` for large buffers.

    PR 3 assumed the native kernel wins; on hosts whose zlib carries a
    slice-by-8/SIMD CRC it is the *byte-at-a-time table loop* that loses
    (measured 327 vs 1000 MB/s on the CI container), and the CRC pass is
    half the streamed persist's cost.  Both produce the same polynomial,
    so the choice is pure throughput: hash 1 MB with each once and cache
    the verdict (a benign race — both racers compute the same answer)."""
    global _NATIVE_CRC_FASTER
    if _NATIVE_CRC_FASTER is None:
        lib = shm_lib()
        if lib is None:
            _NATIVE_CRC_FASTER = False
        else:
            # Pre-touch the pages and warm both code paths, then take
            # best-of-3: the lazy first call can land mid-persist on a
            # contended core, and a single preempted sample (or the
            # cold-page bias of whichever backend runs first) must not
            # stick the slower backend for the process lifetime.
            probe = np.ones(1 << 20, dtype=np.uint8)
            lib.shm_crc32(probe.ctypes.data, probe.nbytes, 0)
            zlib.crc32(probe)
            t_native = t_zlib = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                lib.shm_crc32(probe.ctypes.data, probe.nbytes, 0)
                t_native = min(t_native, time.perf_counter() - t0)
                t0 = time.perf_counter()
                zlib.crc32(probe)
                t_zlib = min(t_zlib, time.perf_counter() - t0)
            _NATIVE_CRC_FASTER = t_native < t_zlib
            logger.debug(
                "crc32 backend: native %.1f MB/s vs zlib %.1f MB/s -> %s",
                1.0 / max(t_native, 1e-9), 1.0 / max(t_zlib, 1e-9),
                "native" if _NATIVE_CRC_FASTER else "zlib",
            )
    return _NATIVE_CRC_FASTER


def crc32_update(buf, crc: int = 0) -> int:
    """Fold a bytes-like buffer into a running CRC-32 (zlib polynomial).

    ``crc32_update(b, crc32_update(a))`` == ``crc32_bytes(a + b)`` — the
    streaming writer and chunked verifier hash tensor bytes in bounded
    chunks with no concatenation.  Large chunks go through whichever of
    the native ``shm_crc32`` kernel (``native/shm_arena.cc``,
    seed-continuable) and ``zlib.crc32`` measured faster on this host."""
    if len(buf) >= _NATIVE_CRC_MIN_BYTES and _native_crc_faster():
        arr = np.frombuffer(buf, dtype=np.uint8)
        return int(shm_lib().shm_crc32(arr.ctypes.data, arr.nbytes, crc))
    return zlib.crc32(buf, crc) & 0xFFFFFFFF


def crc32_bytes(buf) -> int:
    """CRC-32 (zlib polynomial) of a whole bytes-like buffer."""
    return crc32_update(buf, 0)


def step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step_{step:010d}")


def shard_path(ckpt_dir: str, step: int, process_id: int) -> str:
    return os.path.join(step_dir(ckpt_dir, step), f"shard_{process_id:05d}.ckpt")


def done_path(ckpt_dir: str, step: int, process_id: int) -> str:
    return os.path.join(step_dir(ckpt_dir, step), f".done_{process_id:05d}")


def tracker_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, CC.TRACKER_FILE)


def _dtype_key(dtype) -> str:
    """dtype.name round-trips extended types (bfloat16/fp8 via ml_dtypes)
    where dtype.str degrades to raw void ('<V2')."""
    try:
        return dtype.name if np.dtype(dtype.name) == dtype else dtype.str
    except TypeError:
        return dtype.str


def _byte_view(arr: np.ndarray) -> memoryview:
    """Flat uint8 memoryview of an array's data — zero-copy for
    contiguous inputs (the shm arena case); a non-contiguous input costs
    one per-tensor compaction copy (audited).  0-d inputs get a new 1-d
    VIEW from ascontiguousarray (identity changes, memory doesn't), so
    the audit gates on shares_memory, not identity."""
    contig = np.ascontiguousarray(arr)
    if (
        audit.enabled
        and contig is not arr
        and not np.shares_memory(contig, arr)
    ):
        audit.record_copy(int(contig.nbytes), "ascontiguousarray")
    if contig.nbytes == 0:
        return memoryview(b"")
    return memoryview(contig.reshape(-1).view(np.uint8))


def pack_shard(
    tensors: Dict[str, np.ndarray],
    extra: dict,
    meta_extra: Optional[Dict[str, dict]] = None,
) -> bytes:
    """``meta_extra`` optionally overlays per-tensor meta fields — the
    sliced/incremental persist passes flat uint8 slice payloads here with
    the REAL dtype/shape plus ``slice``/``full_nbytes``/``ref`` fields
    (see the module docstring's format notes); field order matches the
    streaming writer so outputs stay byte-identical."""
    metas = {}
    blobs = []
    offset = 0
    for key, arr in tensors.items():
        shape = list(np.shape(arr))
        # ascontiguousarray promotes 0-d to 1-d; keep the true shape.
        arr = np.ascontiguousarray(arr)
        blob = arr.reshape(-1).view(np.uint8).tobytes()
        audit.record_copy(len(blob), "pack_tobytes")
        metas[key] = {
            "dtype": _dtype_key(arr.dtype),
            "shape": shape,
            "offset": offset,
            "nbytes": int(arr.nbytes),
            "crc32": crc32_bytes(blob),
        }
        if meta_extra and key in meta_extra:
            metas[key].update(meta_extra[key])
        blobs.append(blob)
        offset += arr.nbytes
    meta_blob = msgpack.packb(
        {"format": FORMAT_VERSION, "tensors": metas, "extra": extra},
        use_bin_type=True,
    )
    header = _MAGIC + struct.pack("<QI", len(meta_blob), crc32_bytes(meta_blob))
    audit.record_copy(offset, "pack_join")
    return header + meta_blob + b"".join(blobs)


def _parse_header(
    head: bytes, total_len: int, path: str = ""
) -> Tuple[int, int, Optional[int], int]:
    """Validate the fixed header given the file's total length; returns
    (version, meta_len, meta_crc, meta_base).  Shared by the in-memory
    and streaming verifiers so every structural defect raises the same
    :class:`ShardCorruptionError`."""
    if total_len < _V1_HEADER:
        raise ShardCorruptionError(
            f"file shorter than the shard header ({total_len} bytes)", path
        )
    magic = bytes(head[:8])
    if magic == _MAGIC:
        version = 2
        if total_len < _V2_HEADER:
            raise ShardCorruptionError("v2 header truncated", path)
        meta_len, meta_crc = struct.unpack("<QI", head[8:_V2_HEADER])
        base = _V2_HEADER
    elif magic == _MAGIC_V1:
        version = 1
        (meta_len,) = struct.unpack("<Q", head[8:_V1_HEADER])
        meta_crc = None
        base = _V1_HEADER
    else:
        raise ShardCorruptionError(
            f"bad magic {magic!r} — not a dlrover_tpu shard", path
        )
    if base + meta_len > total_len:
        raise ShardCorruptionError(
            f"meta region ({meta_len}B) extends past EOF "
            f"({total_len}B file)", path,
        )
    return version, int(meta_len), meta_crc, base


def _decode_meta(
    meta_raw: bytes, meta_crc: Optional[int], path: str = ""
) -> dict:
    if meta_crc is not None and crc32_bytes(meta_raw) != meta_crc:
        raise ShardCorruptionError("meta CRC mismatch", path)
    try:
        meta = msgpack.unpackb(meta_raw, raw=False)
    except Exception as e:  # noqa: BLE001 - any decode failure is corruption
        raise ShardCorruptionError(f"meta blob undecodable: {e}", path) from e
    if (
        not isinstance(meta, dict)
        or not isinstance(meta.get("tensors"), dict)
        or not isinstance(meta.get("extra"), dict)
    ):
        raise ShardCorruptionError("meta structure invalid", path)
    return meta


def _parse_meta(data: bytes, path: str = "") -> Tuple[dict, int, int]:
    """Validate header + meta blob; returns (meta, data_base, version)."""
    version, meta_len, meta_crc, base = _parse_header(data, len(data), path)
    meta = _decode_meta(bytes(data[base : base + meta_len]), meta_crc, path)
    return meta, base + meta_len, version


def _blob_bounds(
    key: str, tm, limit: int, path: str = ""
) -> Tuple[int, int]:
    """Validated (offset, nbytes) of one tensor's blob relative to the
    data region, against ``limit`` bytes of data-region capacity."""
    try:
        offset = int(tm["offset"])
        nbytes = int(tm["nbytes"])
    except (KeyError, TypeError, ValueError) as e:
        raise ShardCorruptionError(
            f"tensor {key!r} meta invalid: {e}", path
        ) from e
    if offset < 0 or nbytes < 0 or offset + nbytes > limit:
        raise ShardCorruptionError(
            f"tensor {key!r} blob (offset={offset}, nbytes={nbytes}) "
            "truncated or out of bounds", path,
        )
    return offset, nbytes


def _tensor_blob(data: bytes, base: int, key: str, tm, path: str):
    """Bounds-checked zero-copy view of one tensor's bytes."""
    offset, nbytes = _blob_bounds(key, tm, len(data) - base, path)
    return memoryview(data)[base + offset : base + offset + nbytes]


def _check_tensor_crc(buf, key: str, tm, version: int, path: str) -> None:
    if version < 2:
        return  # v1 shards carry no CRCs
    want = tm.get("crc32")
    if not isinstance(want, int):
        raise ShardCorruptionError(
            f"tensor {key!r} missing crc32 in v2 meta", path
        )
    if crc32_bytes(buf) != want:
        raise ShardCorruptionError(
            f"tensor {key!r} CRC mismatch (bit rot or torn write)", path
        )


def verify_shard(data: bytes, path: str = "") -> dict:
    """Full integrity check without materializing arrays: header, meta CRC,
    per-tensor bounds + CRCs.  Returns the shard's ``extra`` metadata;
    raises :class:`ShardCorruptionError` on any damage."""
    meta, base, version = _parse_meta(data, path)
    for key, tm in meta["tensors"].items():
        buf = _tensor_blob(data, base, key, tm, path)
        _check_tensor_crc(buf, key, tm, version, path)
    return meta["extra"]


def _read_file_meta(f, path: str = "") -> Tuple[dict, int, int, int]:
    """Validated header + meta blob from a seekable shard file WITHOUT
    touching the data region; returns (meta, version, file_size,
    data_base).  The one implementation of the bounded meta read —
    the streaming verifier and the meta-only reader must never drift on
    header validation.  Raises :class:`ShardCorruptionError` (the meta
    CRC covers everything read here)."""
    f.seek(0, os.SEEK_END)
    size = f.tell()
    f.seek(0)
    version, meta_len, meta_crc, base = _parse_header(
        f.read(min(size, _V2_HEADER)), size, path
    )
    # Cap the meta read: a bit-flipped meta_len that still lands inside
    # the file would otherwise materialize gigabytes here and OOM the
    # verifier on exactly the damaged shard it exists to diagnose.  Real
    # metas are a few KB..MB (the shm arena caps staging meta at 8MB).
    if meta_len > _VERIFY_META_CAP:
        raise ShardCorruptionError(
            f"meta region ({meta_len}B) implausibly large "
            f"(cap {_VERIFY_META_CAP}B) — header corrupt", path,
        )
    f.seek(base)
    meta = _decode_meta(f.read(meta_len), meta_crc, path)
    return meta, version, size, base + meta_len


def verify_shard_file(
    f, path: str = "", chunk_bytes: int = STREAM_CHUNK_BYTES
) -> Tuple[dict, int]:
    """:func:`verify_shard` over a seekable binary file in bounded chunks.

    Peak memory is ``max(meta_len, chunk_bytes)`` regardless of shard
    size, so fsck can verify shards larger than host RAM headroom.
    Returns ``(extra, format_version)``; raises
    :class:`ShardCorruptionError` on any damage (same reasons as the
    in-memory verifier — both ride the shared parse helpers)."""
    meta, version, size, data_base = _read_file_meta(f, path)
    # Offset order == file order for packed/streamed shards; sorting keeps
    # the read head moving forward even on adversarial metas.
    items = sorted(
        meta["tensors"].items(),
        key=lambda kv: kv[1].get("offset", 0)
        if isinstance(kv[1], dict) and isinstance(kv[1].get("offset"), int)
        else 0,
    )
    for key, tm in items:
        offset, nbytes = _blob_bounds(key, tm, size - data_base, path)
        if version < 2:
            continue  # v1 shards carry no CRCs; bounds checks only
        want = tm.get("crc32")
        if not isinstance(want, int):
            raise ShardCorruptionError(
                f"tensor {key!r} missing crc32 in v2 meta", path
            )
        f.seek(data_base + offset)
        crc = 0
        remaining = nbytes
        while remaining > 0:
            chunk = f.read(min(chunk_bytes, remaining))
            if not chunk:
                raise ShardCorruptionError(
                    f"tensor {key!r} blob (offset={offset}, "
                    f"nbytes={nbytes}) truncated or out of bounds", path,
                )
            crc = crc32_update(chunk, crc)
            remaining -= len(chunk)
        if crc != want:
            raise ShardCorruptionError(
                f"tensor {key!r} CRC mismatch (bit rot or torn write)",
                path,
            )
    return meta["extra"], version


def unpack_shard(
    data: bytes, path: str = ""
) -> Tuple[Dict[str, np.ndarray], dict]:
    """Decode (and verify) a shard payload; ``path`` only labels errors."""
    meta, base, version = _parse_meta(data, path)
    tensors = {}
    for key, tm in meta["tensors"].items():
        if tm.get("slice") is not None or isinstance(tm.get("ref"), dict):
            # This payload alone cannot rebuild the tensor (bytes live in
            # other ranks' slices or an older step); callers of the
            # standalone decoder (replica exchange, interop) must never
            # see such payloads — treat as a rejected payload.
            raise ShardCorruptionError(
                f"tensor {key!r} is a sliced/incremental entry; decode "
                "via read_shard_pieces", path,
            )
        buf = _tensor_blob(data, base, key, tm, path)
        _check_tensor_crc(buf, key, tm, version, path)
        tensors[key] = _materialize_tensor(key, tm, buf, path)
    return tensors, meta["extra"]


def validate_staged_state(
    tensors,
    extra,
    *,
    expect_process_id: Optional[int] = None,
    expect_num_processes: Optional[int] = None,
) -> Optional[str]:
    """Sanity-check a shm-staged state before it is persisted or
    replicated.  Returns a rejection reason, or ``None`` when coherent —
    a torn arena read must never become a committed shard."""
    if not isinstance(tensors, dict) or not tensors:
        return "no tensors staged"
    if not isinstance(extra, dict):
        return "extra metadata missing"
    try:
        step = int(extra.get("step"))
    except (TypeError, ValueError):
        return f"staged step {extra.get('step')!r} is not an int"
    if step < 0:
        return f"staged step {step} is negative"
    if not extra.get("tensors_info"):
        return "tensors_info missing (state could never be reassembled)"
    pid = extra.get("process_id")
    if (
        expect_process_id is not None
        and pid is not None
        and int(pid) != int(expect_process_id)
    ):
        return f"staged process_id {pid} != expected {expect_process_id}"
    world = extra.get("num_processes")
    if (
        expect_num_processes is not None
        and world is not None
        and int(world) != int(expect_num_processes)
    ):
        return f"staged num_processes {world} != expected {expect_num_processes}"
    return None


def _chaos_damage_blob(blob: bytes, step: int, process_id: int) -> bytes:
    """Data-corruption chaos sites, applied to the packed payload just
    before the storage write — the written file carries the damage while
    the done-file/commit protocol proceeds normally, exactly the silent
    bit-rot / torn-write scenario the restore ladder must survive."""
    if chaos.inject(
        "storage.corrupt_shard", step=step, rank=process_id
    ) is not None:
        # Flip a byte near the tail (tensor data region when any tensor
        # bytes exist, meta otherwise — both are CRC-covered).
        damaged = bytearray(blob)
        damaged[max(0, len(damaged) - 7)] ^= 0xFF
        blob = bytes(damaged)
    if chaos.inject(
        "storage.truncate_shard", step=step, rank=process_id
    ) is not None:
        blob = blob[: max(1, len(blob) // 2)]
    return blob


def write_shard(
    storage: CheckpointStorage,
    ckpt_dir: str,
    step: int,
    process_id: int,
    tensors: Dict[str, np.ndarray],
    extra: dict,
    meta_extra: Optional[Dict[str, dict]] = None,
) -> None:
    """Legacy pack-then-write persist (one monolithic blob).  The hot
    paths use :func:`write_shard_from_views`; this stays as the reference
    implementation the interop tests compare against byte-for-byte."""
    storage.safe_makedirs(step_dir(ckpt_dir, step))
    blob = _chaos_damage_blob(
        pack_shard(tensors, extra, meta_extra), step, process_id
    )
    storage.write(blob, shard_path(ckpt_dir, step, process_id))
    storage.write(str(time.time()), done_path(ckpt_dir, step, process_id))


class ShardStreamWriter:
    """Single-pass, zero-copy v2 shard writer.

    Where :func:`pack_shard` materializes three full copies of the state
    (arena read copy, per-tensor ``tobytes``, blob join) before the bytes
    ever reach storage, this writer streams tensor bytes **directly from
    the caller's memoryviews** (typically the shm arena mapping) to the
    storage sink in ``chunk_bytes`` chunks, folding each tensor's CRC-32
    incrementally during that same pass.  The header+meta region — whose
    byte length depends on those CRCs (see ``_CRC_PLACEHOLDER``) — is
    patched in place afterwards.  Output is **byte-identical** to
    ``pack_shard(tensors, extra)`` for the same inputs.

    ``workers > 1`` splits the tensors into contiguous byte-balanced
    ranges drained concurrently via positional writes into the
    preallocated file (``CheckpointStorage.write_shard_ranges``; POSIX
    pwrite fast path, sequential on object stores).

    Lifetime contract: the caller must keep the views' backing memory
    mapped and fenced against writers for the duration of
    :meth:`write` — the agent saver holds the per-rank fencing lock and
    arena mutex across this call.
    """

    def __init__(
        self,
        storage: CheckpointStorage,
        path: str,
        tensors: Dict[str, np.ndarray],
        extra: dict,
        *,
        workers: int = 1,
        chunk_bytes: int = STREAM_CHUNK_BYTES,
        damage_ctx: Optional[Tuple[int, int]] = None,
        meta_extra: Optional[Dict[str, dict]] = None,
    ):
        self._storage = storage
        self._path = path
        self._tensors = tensors
        self._extra = extra
        self._workers = max(1, int(workers))
        self._chunk = max(1 << 16, int(chunk_bytes))
        self._damage_ctx = damage_ctx
        self._meta_extra = meta_extra or {}
        self._crcs: Dict[str, int] = {}
        self._stats: dict = {}

    # -- layout --------------------------------------------------------------
    def _layout(self):
        """(placeholder metas, [(key, byte_view, rel_offset)], data_bytes) —
        identical field order and offsets to :func:`pack_shard`."""
        metas: Dict[str, dict] = {}
        views = []
        offset = 0
        for key, arr in self._tensors.items():
            arr = np.asarray(arr)
            shape = list(np.shape(arr))
            view = _byte_view(arr)
            metas[key] = {
                "dtype": _dtype_key(arr.dtype),
                "shape": shape,
                "offset": offset,
                "nbytes": int(arr.nbytes),
                # An empty blob's CRC is exactly 0 — pin it now so a 0-d
                # optimizer scalar or empty buffer never forces the
                # relayout pass just to shrink a placeholder.
                "crc32": _CRC_PLACEHOLDER if arr.nbytes else 0,
            }
            if key in self._meta_extra:
                metas[key].update(self._meta_extra[key])
            views.append((key, view, offset))
            offset += int(arr.nbytes)
        return metas, views, offset

    def _partition(self, views, n: int):
        """Contiguous byte-balanced groups, one per range worker."""
        if n <= 1 or len(views) <= 1:
            return [views] if views else []
        total = sum(len(v) for _, v, _ in views)
        target = max(1, total // n)
        groups, cur, cur_bytes = [], [], 0
        for item in views:
            cur.append(item)
            cur_bytes += len(item[1])
            if cur_bytes >= target and len(groups) < n - 1:
                groups.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            groups.append(cur)
        return groups

    def _gen(self, group):
        """Yield one group's tensor bytes in bounded chunks, folding each
        tensor's CRC-32 as a side effect of the same traversal."""
        for key, view, _rel in group:
            crc = 0
            for lo in range(0, len(view), self._chunk):
                chunk = view[lo : lo + self._chunk]
                crc = crc32_update(chunk, crc)
                audit.record_write(len(chunk))
                yield chunk
            self._crcs[key] = crc

    def _ranges(self, groups, base: int):
        return [(base + g[0][2], self._gen(g)) for g in groups if g]

    # -- write ---------------------------------------------------------------
    def write(self) -> dict:
        metas, views, data_bytes = self._layout()
        meta_ph = msgpack.packb(
            {"format": FORMAT_VERSION, "tensors": metas, "extra": self._extra},
            use_bin_type=True,
        )
        base = _V2_HEADER + len(meta_ph)
        groups = self._partition(views, self._workers)
        self._stats = {
            "data_bytes": data_bytes,
            "tensors": len(views),
            "workers": min(self._workers, max(1, len(groups))),
            "passes": 1,
        }

        def _finalize(sink):
            nonlocal base
            # Real CRCs are known only now; dict(m, ...) keeps key order,
            # so the meta blob matches pack_shard's byte-for-byte.
            real = {
                k: dict(m, crc32=self._crcs.get(k, 0))
                for k, m in metas.items()
            }
            meta_blob = msgpack.packb(
                {
                    "format": FORMAT_VERSION,
                    "tensors": real,
                    "extra": self._extra,
                },
                use_bin_type=True,
            )
            if len(meta_blob) != len(meta_ph):
                # A tensor CRC landed below 65536 (~1.5e-5 per tensor) and
                # msgpack encodes it narrower than the placeholder: the
                # data region must shift.  Rare second pass, audited.
                base = _V2_HEADER + len(meta_blob)
                audit.record_pass("stream_relayout")
                self._stats["passes"] += 1
                drain_ranges(
                    sink, self._ranges(groups, base), self._workers
                )
                sink.truncate(base + data_bytes)
            total = base + data_bytes
            sink.write_at(
                _MAGIC
                + struct.pack(
                    "<QI", len(meta_blob), crc32_bytes(meta_blob)
                ),
                0,
            )
            sink.write_at(meta_blob, _V2_HEADER)
            self._apply_chaos(sink, total)
            self._stats["total_bytes"] = total

        audit.record_pass("stream_data")
        self._storage.write_shard_ranges(
            self._path,
            base + data_bytes,
            self._ranges(groups, base),
            workers=self._workers,
            finalize=_finalize,
        )
        self._stats["crcs"] = dict(self._crcs)
        return dict(self._stats)

    def _apply_chaos(self, sink, total: int) -> None:
        """Same damage semantics as ``_chaos_damage_blob``, applied to the
        streamed file before its atomic publish."""
        if self._damage_ctx is None:
            return
        step, pid = self._damage_ctx
        # Every data byte is in the (unpublished) tmp file: the widow-
        # slice crash — the rank dies with its slice streamed but never
        # published or done-voted, so the step's slice set cannot cover
        # the state and the coverage proof must block commit.
        chaos.inject("storage.slice_crash", step=step, rank=pid)
        if chaos.inject(
            "storage.corrupt_shard", step=step, rank=pid
        ) is not None:
            pos = max(0, total - 7)
            cur = sink.read_at(1, pos)
            if cur:
                sink.write_at(bytes([cur[0] ^ 0xFF]), pos)
        if chaos.inject(
            "storage.truncate_shard", step=step, rank=pid
        ) is not None:
            sink.truncate(max(1, total // 2))


def write_shard_from_views(
    storage: CheckpointStorage,
    ckpt_dir: str,
    step: int,
    process_id: int,
    tensors: Dict[str, np.ndarray],
    extra: dict,
    *,
    workers: int = 1,
    chunk_bytes: int = STREAM_CHUNK_BYTES,
    meta_extra: Optional[Dict[str, dict]] = None,
) -> dict:
    """Streamed, zero-copy counterpart of :func:`write_shard`: same file
    bytes, same done-file vote, no intermediate full-state buffers.
    ``tensors`` may be live shm-arena views — see
    :class:`ShardStreamWriter` for the lifetime contract.  Returns the
    writer's stats dict (bytes, passes, workers, per-tensor crcs)."""
    storage.safe_makedirs(step_dir(ckpt_dir, step))
    writer = ShardStreamWriter(
        storage,
        shard_path(ckpt_dir, step, process_id),
        tensors,
        extra,
        workers=workers,
        chunk_bytes=chunk_bytes,
        damage_ctx=(step, process_id),
        meta_extra=meta_extra,
    )
    stats = writer.write()
    storage.write(str(time.time()), done_path(ckpt_dir, step, process_id))
    return stats


@dataclasses.dataclass
class ShardManifest:
    """One shard's validated header + meta, read WITHOUT touching the
    data region: everything the restore planner needs to decide what to
    read (placement ``tensors_info``, per-tensor blob offsets, slice
    bounds, refs) — fetched once and reused by the data read, so shard
    selection never pays a second header+meta pass (ISSUE 7 satellite).
    The meta CRC covers everything held here."""

    meta: dict
    version: int
    size: int
    data_base: int
    path: str

    @property
    def tensors(self) -> dict:
        return self.meta["tensors"]

    @property
    def extra(self) -> dict:
        return self.meta["extra"]


def read_shard_manifest(
    storage: CheckpointStorage, ckpt_dir: str, step: int, process_id: int
) -> Optional[ShardManifest]:
    """Meta-only read of one shard.  ``None`` when absent; raises
    :class:`ShardCorruptionError` on structural damage."""
    path = shard_path(ckpt_dir, step, process_id)
    f = storage.open_read(path)
    if f is None:
        return None
    try:
        meta, version, size, data_base = _read_file_meta(f, path)
        return ShardManifest(meta, version, size, data_base, path)
    finally:
        f.close()


def read_shard_meta(
    storage: CheckpointStorage, ckpt_dir: str, step: int, process_id: int
) -> Optional[dict]:
    """Header + meta-only read of one shard: the ``extra`` dict (step,
    ``tensors_info`` placement, world metadata) WITHOUT touching the
    data region.  ``None`` when absent; raises
    :class:`ShardCorruptionError` on structural damage (the meta CRC
    covers everything read here)."""
    man = read_shard_manifest(storage, ckpt_dir, step, process_id)
    return None if man is None else man.extra


def _materialize_tensor(key: str, tm, blob, path: str) -> np.ndarray:
    """Decode one full (unsliced) tensor blob into its real array."""
    try:
        return (
            np.frombuffer(blob, dtype=np.dtype(tm["dtype"]))
            .reshape(tm["shape"])
            .copy()
        )
    except Exception as e:  # noqa: BLE001 - garbage dtype/shape meta
        raise ShardCorruptionError(
            f"tensor {key!r} undecodable: {e}", path
        ) from e


def _read_blob_at(f, man: ShardManifest, key: str, tm) -> bytes:
    """Read + CRC-verify one tensor's blob from an open shard file."""
    offset, nbytes = _blob_bounds(
        key, tm, man.size - man.data_base, man.path
    )
    f.seek(man.data_base + offset)
    blob = f.read(nbytes)
    if len(blob) != nbytes:
        raise ShardCorruptionError(
            f"tensor {key!r} blob (offset={offset}, nbytes={nbytes}) "
            "truncated or out of bounds", man.path,
        )
    _check_tensor_crc(blob, key, tm, man.version, man.path)
    return blob


def _read_ref_blob(
    storage: CheckpointStorage,
    ckpt_dir: str,
    process_id: int,
    key: str,
    tm,
    man_cache: Dict[int, ShardManifest],
    depth: int = 0,
) -> bytes:
    """Resolve an incremental-save reference: the bytes live in an older
    step's shard for the SAME rank and key (chains are flattened at save
    time — every ref targets the step that physically holds the bytes —
    but resolution stays depth-bounded defensively).  Any break in the
    chain (missing step, missing key, bounds/CRC mismatch) is corruption
    of THIS shard: the restore ladder then falls back a step."""
    if depth > 8:
        raise ShardCorruptionError(
            f"tensor {key!r} ref chain exceeds depth 8 (cycle?)"
        )
    ref = tm["ref"]
    try:
        ref_step = int(ref["step"])
        ref_crc = int(ref["crc32"])
        ref_nbytes = int(ref["nbytes"])
    except (KeyError, TypeError, ValueError) as e:
        raise ShardCorruptionError(
            f"tensor {key!r} ref meta invalid: {e}"
        ) from e
    man = man_cache.get(ref_step)
    if man is None:
        man = read_shard_manifest(storage, ckpt_dir, ref_step, process_id)
        if man is None:
            raise ShardCorruptionError(
                f"tensor {key!r} references step {ref_step} whose shard "
                "is missing (GC'd or lost)"
            )
        man_cache[ref_step] = man
    tm2 = man.tensors.get(key)
    if tm2 is None:
        raise ShardCorruptionError(
            f"tensor {key!r} missing from referenced step {ref_step}",
            man.path,
        )
    if tm2.get("slice") != tm.get("slice"):
        raise ShardCorruptionError(
            f"tensor {key!r} slice bounds changed across the ref chain "
            f"({tm.get('slice')} vs {tm2.get('slice')})", man.path,
        )
    if isinstance(tm2.get("ref"), dict):
        return _read_ref_blob(
            storage, ckpt_dir, process_id, key, tm2, man_cache, depth + 1
        )
    if int(tm2.get("nbytes", -1)) != ref_nbytes or int(
        tm2.get("crc32", -1)
    ) != ref_crc:
        raise ShardCorruptionError(
            f"tensor {key!r} referenced bytes in step {ref_step} do not "
            "match the reference (rewritten or damaged)", man.path,
        )
    f = storage.open_read(man.path)
    if f is None:
        raise ShardCorruptionError(
            f"tensor {key!r} referenced shard unreadable", man.path
        )
    try:
        return _read_blob_at(f, man, key, tm2)
    finally:
        f.close()


def read_shard_pieces(
    storage: CheckpointStorage,
    ckpt_dir: str,
    step: int,
    process_id: int,
    *,
    manifest: Optional[ShardManifest] = None,
    keys: Optional[Set[str]] = None,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, dict], dict]]:
    """Read + verify one shard's pieces, resolving incremental refs.

    Returns ``(tensors, slices, extra)``: full entries come back as real
    arrays; sliced entries as flat uint8 payloads with ``slices[key]``
    holding their tensor meta (``slice``/``full_nbytes``/dtype/shape) for
    :meth:`ShardSource.add`.  ``manifest`` reuses an already-fetched
    (CRC-verified) header+meta; ``keys`` restricts the data reads to the
    named tensors — the plan-driven restore's minimal slice set.
    ``None`` when absent; raises :class:`ShardCorruptionError` on damage.
    """
    man = manifest or read_shard_manifest(storage, ckpt_dir, step, process_id)
    if man is None:
        return None
    f = storage.open_read(man.path)
    if f is None:
        return None
    man_cache: Dict[int, ShardManifest] = {}
    tensors: Dict[str, np.ndarray] = {}
    slices: Dict[str, dict] = {}
    try:
        for key, tm in man.tensors.items():
            if keys is not None and key not in keys:
                continue
            if isinstance(tm.get("ref"), dict):
                blob = _read_ref_blob(
                    storage, ckpt_dir, process_id, key, tm, man_cache
                )
            else:
                blob = _read_blob_at(f, man, key, tm)
            if tm.get("slice") is not None:
                tensors[key] = np.frombuffer(blob, dtype=np.uint8).copy()
                slices[key] = tm
            else:
                tensors[key] = _materialize_tensor(key, tm, blob, man.path)
    finally:
        f.close()
    return tensors, slices, man.extra


def read_shard(
    storage: CheckpointStorage, ckpt_dir: str, step: int, process_id: int
) -> Optional[Tuple[Dict[str, np.ndarray], dict]]:
    """Read + verify one COMPLETE shard (refs resolved; refuses sliced
    shards, whose bytes live across ranks — use :func:`read_shard_pieces`
    for those).  ``None`` when absent; raises
    :class:`ShardCorruptionError` (with the path filled in) on damage."""
    got = read_shard_pieces(storage, ckpt_dir, step, process_id)
    if got is None:
        return None
    tensors, slices, extra = got
    if slices:
        raise ValueError(
            f"shard (step {step}, proc {process_id}) holds cross-replica "
            "slices; assemble via read_shard_pieces + ShardSource"
        )
    return tensors, extra


def list_shard_ids(storage: CheckpointStorage, ckpt_dir: str, step: int) -> list:
    out = []
    for name in storage.listdir(step_dir(ckpt_dir, step)):
        if name.startswith("shard_") and name.endswith(".ckpt"):
            out.append(int(name[len("shard_") : -len(".ckpt")]))
    return sorted(out)


def all_shards_done(
    storage: CheckpointStorage, ckpt_dir: str, step: int, world_size: int
) -> bool:
    return all(
        storage.exists(done_path(ckpt_dir, step, pid))
        for pid in range(world_size)
    )


def wait_sync_barrier(client, step: int, timeout: float,
                      stop_event=None) -> bool:
    """Bounded wait on the master's cross-node step barrier before commit.

    The barrier is advisory (skew detection) — the done files are the real
    commit votes — so a master that restarted and lost its rendezvous
    state (the barrier can then never open) or died outright must not
    block durability past ``timeout``.  Returns True once the barrier
    opened; False on timeout or when ``stop_event`` was set."""
    if client is None:
        return True
    deadline = time.time() + timeout
    while time.time() < deadline:
        if stop_event is not None and stop_event.is_set():
            return False
        try:
            if client.sync_checkpoint(step):
                return True
        except Exception as e:  # noqa: BLE001
            logger.debug(
                "sync_checkpoint(%d) RPC failed (retrying): %s", step, e
            )
        time.sleep(0.5)
    return False


def resolve_keep_last(max_to_keep) -> int:
    """One home for the rotation contract: ``None`` -> default (keep 3),
    ``0`` -> keep ALL step dirs, ``N > 0`` -> keep the newest N."""
    return 3 if max_to_keep is None else int(max_to_keep)


def commit(
    storage: CheckpointStorage, ckpt_dir: str, step: int, keep_last: int = 3
) -> None:
    """Advance the tracker and GC old step dirs (leader only).

    The tracker write is the atomic commit point (temp + fsync + rename):
    a crash before it leaves the previous committed step intact; a crash
    after it leaves this step fully committed.  The two chaos sites below
    pin down exactly those two halves.
    """
    chaos.inject("ckpt.crash_before_commit", step=step)
    storage.write(str(step), tracker_path(ckpt_dir))
    chaos.inject("ckpt.crash_after_commit", step=step)
    logger.info("checkpoint step %d committed at %s", step, ckpt_dir)
    # Rotation only counts live steps: quarantined dirs are operator
    # evidence, neither GC'd here nor taking a keep_last slot.  Steps
    # whose bytes a retained step still REFERENCES (incremental saves)
    # are holders, not garbage: deleting one would break every newer
    # step's ref chain, so they survive rotation until unreferenced.
    steps = list_steps(storage, ckpt_dir)
    doomed = sorted(steps)[:-keep_last] if keep_last > 0 else []
    if not doomed:
        return
    retained = [s for s in steps if s not in set(doomed)] + [step]
    try:
        protected = referenced_steps(storage, ckpt_dir, retained)
    except Exception as e:  # noqa: BLE001 - rotation is housekeeping:
        # an unreadable meta must never fail the commit, and keeping a
        # step too long is safe where deleting a holder is not.
        logger.warning("rotation ref scan failed (keeping all): %s", e)
        protected = set(steps)
    for old in doomed:
        if old == step:
            continue
        if old in protected:
            logger.info(
                "rotation: keeping step %d (referenced by a newer "
                "incremental step)", old,
            )
            continue
        storage.safe_rmtree(step_dir(ckpt_dir, old))


def referenced_steps(
    storage: CheckpointStorage, ckpt_dir: str, roots: Iterable[int]
) -> Set[int]:
    """Transitive closure of the steps referenced by ``roots``'s shards
    (the ``ref_steps`` summary each incremental shard records) — what
    rotation must not delete and fsck walks.  A shard whose meta cannot
    be read contributes nothing (its step is unrestorable regardless)."""
    seen: Set[int] = set(int(s) for s in roots)
    frontier = list(seen)
    out: Set[int] = set()
    while frontier:
        s = frontier.pop()
        for pid in list_shard_ids(storage, ckpt_dir, s):
            try:
                extra = read_shard_meta(storage, ckpt_dir, s, pid)
            except ShardCorruptionError:
                continue
            for r in (extra or {}).get("ref_steps") or []:
                r = int(r)
                out.add(r)
                if r not in seen:
                    seen.add(r)
                    frontier.append(r)
    return out


def is_step_quarantined(
    storage: CheckpointStorage, ckpt_dir: str, step: int
) -> bool:
    """Marker-file quarantine check (backends without directory rename)."""
    return storage.exists(
        os.path.join(step_dir(ckpt_dir, step), QUARANTINE_MARKER)
    )


def quarantine_step(
    storage: CheckpointStorage, ckpt_dir: str, step: int
) -> Optional[str]:
    """Exclude a verification-failed step from every restore path.

    Renames ``step_N`` -> ``step_N.corrupt`` (atomic on POSIX); backends
    without directory rename get a ``.quarantined`` marker file instead.
    Both forms are invisible to :func:`list_steps` and rotation but kept
    on disk as operator evidence for ``checkpoint.fsck``.  Returns the
    quarantined path, or ``None`` when the dir was already gone (e.g. a
    concurrent rank won the rename race)."""
    src = step_dir(ckpt_dir, step)
    if not storage.exists(src):
        return None
    dst = src + QUARANTINE_SUFFIX
    if storage.rename_dir(src, dst):
        logger.warning("checkpoint step %d quarantined -> %s", step, dst)
        return dst
    try:
        storage.write(
            str(time.time()), os.path.join(src, QUARANTINE_MARKER)
        )
    except Exception as e:  # noqa: BLE001 - dir raced away mid-quarantine
        logger.warning("quarantine of step %d failed: %s", step, e)
        return None
    logger.warning(
        "checkpoint step %d quarantined in place (marker file)", step
    )
    return src


def list_steps(storage: CheckpointStorage, ckpt_dir: str) -> list:
    """All step numbers with a live step dir present (committed or not);
    quarantined dirs (renamed or marker) are excluded."""
    steps = []
    for name in storage.listdir(ckpt_dir):
        if not name.startswith("step_") or name.endswith(QUARANTINE_SUFFIX):
            continue
        try:
            step = int(name[len("step_"):])
        except ValueError:
            continue
        if is_step_quarantined(storage, ckpt_dir, step):
            continue
        steps.append(step)
    return steps


def list_quarantined(storage: CheckpointStorage, ckpt_dir: str) -> list:
    """(step, dirpath) per quarantined step dir, either form."""
    out = []
    for name in storage.listdir(ckpt_dir):
        if not name.startswith("step_"):
            continue
        if name.endswith(QUARANTINE_SUFFIX):
            try:
                step = int(
                    name[len("step_") : -len(QUARANTINE_SUFFIX)]
                )
            except ValueError:
                continue
            out.append((step, os.path.join(ckpt_dir, name)))
        else:
            try:
                step = int(name[len("step_"):])
            except ValueError:
                continue
            if is_step_quarantined(storage, ckpt_dir, step):
                out.append((step, os.path.join(ckpt_dir, name)))
    return sorted(out)


def latest_step(storage: CheckpointStorage, ckpt_dir: str) -> Optional[int]:
    content = storage.read(tracker_path(ckpt_dir), mode="r")
    if not content:
        return None
    try:
        return int(str(content).strip())
    except ValueError:
        return None
