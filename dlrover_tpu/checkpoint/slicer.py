"""Cross-replica sliced persist + dirty-fence incremental saves (ISSUE 7).

The planning layer between the checkpoint engines and the shard writer:

- **Slicing** (:func:`plan_persist`): when a tensor's box is held by
  several ranks (``owners`` from the staged ``tensors_info`` — derived at
  stage time from the leaf's global device->index map, so every rank
  computes the same assignment with zero negotiation), each owner writes
  only a *disjoint, element-aligned, byte-balanced* sub-range of the
  box's C-order buffer.  Aggregate save bandwidth then scales with the
  replica count instead of funnelling every replicated byte through one
  rank's storage link (Orbax 2605.23066 / cross-replica update sharding
  2004.13336).  Tensors smaller than :data:`SLICE_MIN_BYTES` go whole to
  one deterministically-hashed owner instead of degenerate shreds.

- **Dirty fences** (:class:`DirtyTracker`): a save skips tensors whose
  staged bytes carry the same CRC fingerprint the rank persisted at its
  *holder* step (the probe CRCs the staged views in place — for the
  zero-copy paths these ARE the shm arena's mapped bytes — and runs on
  the async persist path, never the synchronous train stall), writing
  a meta ``ref`` to the holder's bytes instead.  Chains are flattened —
  every ref targets the step physically holding the bytes — rotation
  keeps referenced steps alive, and fsck verifies the chain.

- **The coverage proof** (:func:`step_covers`): commit is allowed only
  when the present shards' slices provably tile every tensor.  The proof
  is *reused* from the resharding planner: each tensor's byte buffer is
  a 1-D tensor, each slice a 1-D box, and ``build_plan(src, dst)`` +
  ``ReshardPlan.validate()`` prove exact coverage of the full range —
  no gap, no phantom bytes (``reshard/plan.py``, PR 6).

Pure planning + storage metadata reads — importable without jax, so fsck
can run the coverage proof on any host that sees the storage.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import logger

#: Below this size a tensor is not shredded across owners: it goes whole
#: to one deterministically-chosen owner (hash-balanced across keys).
SLICE_MIN_BYTES = 1 << 16


def slice_bounds(
    nbytes: int, itemsize: int, n_owners: int, owner_index: int
) -> Tuple[int, int]:
    """Byte range ``[lo, hi)`` of one owner's slice of an ``nbytes``
    buffer split across ``n_owners``: element-aligned (no dtype element
    is ever split), contiguous across owners, byte-balanced to within one
    element."""
    if n_owners <= 1:
        return 0, nbytes
    isz = max(1, int(itemsize))
    n_elems = nbytes // isz
    i = int(owner_index)
    lo = (i * n_elems // n_owners) * isz
    if i == n_owners - 1:
        return lo, nbytes
    return lo, ((i + 1) * n_elems // n_owners) * isz


def owner_of_small(key: str, n_owners: int) -> int:
    """Deterministic single owner index for a small tensor — hash-spread
    so many small tensors balance across the replica set."""
    return zlib.crc32(key.encode()) % max(1, n_owners)


def _effective_owners(meta: Optional[dict], world: int) -> Optional[list]:
    """The ranks holding this key's exact box, or ``None`` when unknown
    (then never sliced).  Host leaves are rank-identical by the same
    assumption the restore path has always made, so they are owned by
    the whole world."""
    if meta is None:
        return None
    owners = meta.get("owners")
    if owners is not None:
        return [int(r) for r in owners]
    if meta.get("host"):
        return list(range(world))
    return None


def _byte_view(arr: np.ndarray) -> np.ndarray:
    """Flat uint8 view of an array's bytes (zero-copy for the contiguous
    staged-arena case)."""
    contig = np.ascontiguousarray(arr)
    if contig.nbytes == 0:
        return np.empty(0, dtype=np.uint8)
    return contig.reshape(-1).view(np.uint8)


@dataclasses.dataclass
class SliceHolder:
    """Where one key's slice bytes physically live + the fence
    fingerprint they were persisted with."""

    step: int
    lo: int
    hi: int
    full_nbytes: int
    crc32: int  # CRC of the staged slice bytes == the written blob's CRC


class DirtyTracker:
    """Per-rank memory of what was persisted where — the consumer of the
    arena's per-tensor commit fences.  Lost on restart (the next save is
    then simply full, never wrong)."""

    def __init__(self):
        self._holders: Dict[str, SliceHolder] = {}

    def holder(self, key: str) -> Optional[SliceHolder]:
        return self._holders.get(key)

    def note_plan(self, plan: "PersistPlan", step: int,
                  crcs: Dict[str, int]) -> None:
        """Record a SUCCESSFUL write of ``plan`` at ``step``: written keys
        get this step as holder (with the writer's streamed CRCs); ref'd
        keys keep their existing holder."""
        for key, (lo, hi, full) in plan.layout.items():
            if key in plan.refs:
                continue
            crc = crcs.get(key)
            if crc is None:
                continue
            self._holders[key] = SliceHolder(
                step=int(step), lo=lo, hi=hi, full_nbytes=full,
                crc32=int(crc),
            )

    def reset(self) -> None:
        self._holders.clear()


@dataclasses.dataclass
class PersistPlan:
    """What one rank actually streams for one save."""

    tensors: Dict[str, np.ndarray]  # payloads to write (views)
    meta_extra: Dict[str, dict]  # per-key shard-meta overlays
    extra: dict  # shard extra (copy; ref_steps/sliced markers added)
    layout: Dict[str, Tuple[int, int, int]]  # key -> (lo, hi, full_nbytes)
    refs: Dict[str, int]  # key -> holder step (skipped writes)
    skipped: int
    written_bytes: int  # tensor bytes this rank streams
    logical_bytes: int  # this rank's full unsliced staged bytes


def plan_persist(
    tensors: Dict[str, np.ndarray],
    extra: dict,
    *,
    process_id: int,
    num_processes: int,
    sliced: bool = True,
    tracker: Optional[DirtyTracker] = None,
    holder_exists=None,
) -> PersistPlan:
    """Turn a staged state into this rank's slice of it.

    ``holder_exists(step)`` (when a ``tracker`` is given) must confirm a
    holder step's shard file is still on storage before a ref may target
    it — a holder lost to GC/quarantine forces a rewrite, never a
    dangling reference.  The dirty probe CRCs the staged slice bytes
    in-process (memory speed); the writes it avoids run at storage-link
    speed, which is the asymmetry incremental saves monetize.

    Registered as a sim-bound pure policy (graftcheck DET70x): slice
    assignment is a function of (tensors, process_id, num_processes)
    only — no ambient effects, so every rank computes the identical
    partition without coordination."""
    from dlrover_tpu.checkpoint.shard_file import crc32_bytes, _dtype_key

    info = extra.get("tensors_info") or {}
    out: Dict[str, np.ndarray] = {}
    meta_extra: Dict[str, dict] = {}
    layout: Dict[str, Tuple[int, int, int]] = {}
    refs: Dict[str, int] = {}
    skipped = 0
    written = 0
    logical = 0
    holder_alive: Dict[int, bool] = {}
    for key, arr in tensors.items():
        arr = np.asarray(arr)
        n = int(arr.nbytes)
        logical += n
        owners = _effective_owners(info.get(key), num_processes)
        lo, hi = 0, n
        if (
            sliced
            and owners
            and len(owners) > 1
            and process_id in owners
            and n > 0
        ):
            if n <= SLICE_MIN_BYTES:
                mine = owner_of_small(key, len(owners))
                lo, hi = (0, n) if owners.index(process_id) == mine else (0, 0)
            else:
                lo, hi = slice_bounds(
                    n, arr.dtype.itemsize, len(owners),
                    owners.index(process_id),
                )
        part = (lo, hi) != (0, n)
        base_meta = {
            "dtype": _dtype_key(arr.dtype),
            "shape": list(np.shape(arr)),
        }
        if part:
            base_meta["slice"] = [lo, hi]
            base_meta["full_nbytes"] = n
        layout[key] = (lo, hi, n)
        view = _byte_view(arr)[lo:hi] if part else None
        h = tracker.holder(key) if tracker is not None else None
        if (
            h is not None
            and (h.lo, h.hi, h.full_nbytes) == (lo, hi, n)
            and hi > lo
        ):
            alive = holder_alive.get(h.step)
            if alive is None:
                alive = bool(holder_exists(h.step)) if holder_exists else False
                holder_alive[h.step] = alive
            probe = view if view is not None else _byte_view(arr)
            if alive and crc32_bytes(probe) == h.crc32:
                # Fence untripped: reference the holder's bytes.  The
                # payload written is EMPTY, so full_nbytes must ride the
                # meta even for unsliced entries — the coverage proof
                # reads the covered range from it, never from the
                # (zero) payload size.
                out[key] = np.empty(0, dtype=np.uint8)
                meta_extra[key] = dict(
                    base_meta,
                    full_nbytes=n,
                    ref={"step": h.step, "crc32": h.crc32,
                         "nbytes": hi - lo},
                )
                refs[key] = h.step
                skipped += 1
                continue
        out[key] = view if part else arr
        if part:
            meta_extra[key] = base_meta
        written += int(out[key].nbytes)
    write_extra = dict(extra)
    if refs:
        write_extra["ref_steps"] = sorted({int(s) for s in refs.values()})
    if any("slice" in m for m in meta_extra.values()):
        write_extra["sliced"] = True
    return PersistPlan(
        tensors=out,
        meta_extra=meta_extra,
        extra=write_extra,
        layout=layout,
        refs=refs,
        skipped=skipped,
        written_bytes=written,
        logical_bytes=logical,
    )


# -- the coverage proof (commit gate) ------------------------------------


def step_covers(
    storage,
    ckpt_dir: str,
    step: int,
    manifests: Optional[dict] = None,
) -> Tuple[bool, str]:
    """Prove the step's present shards cover every tensor exactly — the
    reshard planner's :meth:`ReshardPlan.validate` tiling proof, run
    twice:

    1. **Bytes of each box**: pieces are identified by ``(path, box)``
       from the shard's placement info — NOT by the per-rank local key,
       which collides across ranks for sharded (non-replicated) leaves —
       and each box's present byte slices must tile its full C-order
       buffer (each box a 1-D tensor, each slice a 1-D box).
    2. **Boxes of each tensor**: the complete boxes must tile the
       tensor's global shape (the N-D proof), so a dead rank's
       EXCLUSIVE shard of a sharded leaf is caught even when a lying
       done-vote hides the loss.

    Ref entries count as covering their range — their bytes are durable
    elsewhere and fsck verifies the chain.  Returns ``(ok, reason)``;
    any failure means "do not commit"."""
    from dlrover_tpu.checkpoint import shard_file
    from dlrover_tpu.reshard.plan import (
        MeshLayout,
        PlanError,
        TensorInfo,
        build_plan,
    )

    if manifests is None:
        manifests = {}
        try:
            pids = shard_file.list_shard_ids(storage, ckpt_dir, step)
        except Exception as e:  # noqa: BLE001 - unlistable step dir
            return False, f"step dir unlistable: {e}"
        for pid in pids:
            try:
                man = shard_file.read_shard_manifest(
                    storage, ckpt_dir, step, pid
                )
            except shard_file.ShardCorruptionError as e:
                return False, f"shard {pid} meta unreadable: {e}"
            if man is not None:
                manifests[pid] = man
    if not manifests:
        return False, "no shards present"
    box_full: Dict[str, int] = {}  # box id -> full byte size
    paths_expected: set = set()
    paths_present: set = set()
    byte_shards: Dict[int, Dict[str, tuple]] = {}
    nd_tensors: Dict[str, TensorInfo] = {}
    nd_shards: Dict[int, Dict[str, tuple]] = {}
    for pid, man in manifests.items():
        for p in man.extra.get("tree_paths") or []:
            paths_expected.add(p)
        info = man.extra.get("tensors_info") or {}
        keyed: Dict[str, tuple] = {}
        nd_keyed: Dict[str, tuple] = {}
        for key, tm in man.tensors.items():
            im = info.get(key)
            if not isinstance(im, dict) or "path" not in im \
                    or "index" not in im:
                # Unplaceable bytes cannot be proven to cover anything.
                return False, f"shard {pid}: no placement for {key!r}"
            path = im["path"]
            paths_present.add(path)
            box = tuple((int(s), int(e)) for s, e in im["index"])
            bid = f"{path}@{'/'.join(f'{s}:{e}' for s, e in box)}"
            sl = tm.get("slice")
            ref = tm.get("ref") if isinstance(tm.get("ref"), dict) else None
            n_full = int(
                tm.get("full_nbytes")
                # older incremental meta: an unsliced ref's payload IS
                # the full tensor, so the ref's byte count stands in
                or ((ref or {}).get("nbytes", 0) if not sl else 0)
                or tm.get("nbytes")
                or 0
            )
            lo, hi = (int(sl[0]), int(sl[1])) if sl else (0, n_full)
            prev = box_full.get(bid)
            if prev is not None and prev != n_full:
                return (
                    False,
                    f"{bid!r}: full size disagrees across ranks "
                    f"({prev} vs {n_full})",
                )
            box_full[bid] = n_full
            if hi > lo:
                keyed[f"{bid}|{pid}"] = ((lo, hi),)
            gshape = tuple(int(d) for d in im.get("global_shape") or [])
            ti = nd_tensors.get(path)
            if ti is None:
                nd_tensors[path] = TensorInfo(
                    path=path, global_shape=gshape, dtype=None
                )
            elif ti.global_shape != gshape:
                return (
                    False,
                    f"{path!r}: global shape disagrees across ranks "
                    f"({ti.global_shape} vs {gshape})",
                )
            nd_keyed[f"{path}|@{bid}"] = box
        byte_shards[int(pid)] = keyed
        nd_shards[int(pid)] = nd_keyed
    missing_paths = paths_expected - paths_present
    if missing_paths:
        return (
            False,
            f"tensor paths absent from every present shard: "
            f"{sorted(missing_paths)[:3]}",
        )
    tinfos = {
        bid: TensorInfo(path=bid, global_shape=(n,), dtype="uint8")
        for bid, n in box_full.items()
    }
    src = MeshLayout(tensors=tinfos, shards=byte_shards)
    dst = MeshLayout(
        tensors=tinfos,
        shards={
            -1: {
                f"{bid}|full": ((0, n),)
                for bid, n in box_full.items()
                if n > 0
            }
        },
    )
    try:
        build_plan(src, dst).validate()  # proof 1: slice bytes tile boxes
    except PlanError as e:
        return False, str(e)
    nd_dst = MeshLayout(
        tensors=nd_tensors,
        shards={
            -1: {
                f"{path}|full": tuple((0, d) for d in ti.global_shape)
                for path, ti in nd_tensors.items()
            }
        },
    )
    try:
        build_plan(
            MeshLayout(tensors=nd_tensors, shards=nd_shards), nd_dst
        ).validate()  # proof 2: boxes tile the global tensors
    except PlanError as e:
        return False, f"box coverage: {e}"
    return True, "ok"


def commit_gate(storage, ckpt_dir: str, step: int) -> bool:
    """The commit-time wrapper around :func:`step_covers`: log loudly and
    count the block; a gated step keeps the PREVIOUS committed step as
    the restore point, which is exactly the safe outcome."""
    ok, reason = step_covers(storage, ckpt_dir, step)
    if not ok:
        from dlrover_tpu.agent.metrics import integrity_counters

        integrity_counters.inc("ckpt_commit_blocked")
        logger.error(
            "NOT committing step %d: slice coverage unproven (%s)",
            step, reason,
        )
    return ok
