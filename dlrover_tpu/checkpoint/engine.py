"""Worker-side checkpoint engine: pytree -> shm arena -> (async) storage.

Parity with reference ``trainer/torch/flash_checkpoint/engine.py:136``
(``save_to_memory :417``, ``save_to_storage :435``, ``load :454``) +
``full_ckpt_engine.py``, TPU-native: the state is a sharded JAX pytree; each
process stages only its **addressable shards** (no gather, no host blowup),
with ``copy_to_host_async`` overlapping D2H against the step.

Two runtime modes, auto-detected:

- **agent mode** (production): the per-node agent runs an
  ``AsyncCheckpointSaver`` hosting the event queue / fencing locks; persisting
  shm -> storage happens in the *agent process*, so a crashing worker loses
  nothing (breakpoint-save, reference ``save_shm_to_storage :701``).
- **standalone mode** (no agent): a daemon thread in the worker persists; the
  shm arena still survives worker death, so warm restart works either way.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax

from dlrover_tpu import chaos
from dlrover_tpu.agent.metrics import integrity_counters, perf_stats
from dlrover_tpu.checkpoint import shard_file, slicer, tree_utils
from dlrover_tpu.common import env as env_utils
from dlrover_tpu.diagnosis.data import DiagnosisDataType
from dlrover_tpu.common.global_context import get_context
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedQueue,
    socket_path,
)
from dlrover_tpu.common.shm import SharedMemoryArena, arena_name
from dlrover_tpu.common.storage import CheckpointStorage, PosixDiskStorage
from dlrover_tpu.obs import journal


def ckpt_queue_name(job_name: str) -> str:
    return env_utils.run_scoped(f"{job_name}-ckptq")


def ckpt_lock_name(job_name: str, local_rank: int) -> str:
    return env_utils.run_scoped(f"{job_name}-ckptlock-{local_rank}")


def ckpt_stat_name(job_name: str) -> str:
    return env_utils.run_scoped(f"{job_name}-ckptstat")


class CheckpointEngine:
    def __init__(
        self,
        ckpt_dir: str,
        *,
        job_name: str = "",
        storage: Optional[CheckpointStorage] = None,
        master_client=None,
        # None = default rotation (keep 3); 0 = keep ALL step dirs;
        # N > 0 = keep the newest N.
        max_to_keep: Optional[int] = None,
    ):
        self.ckpt_dir = ckpt_dir
        self.job_name = job_name or env_utils.get_job_name()
        self.storage = storage or PosixDiskStorage()
        self.max_to_keep = max_to_keep
        self.client = master_client
        self._ctx = get_context()
        self.process_id = env_utils.get_process_id()
        self.num_processes = env_utils.get_num_processes()
        self.local_rank = int(os.environ.get("DLROVER_TPU_LOCAL_RANK", 0))
        self._arena = SharedMemoryArena(
            arena_name(self.job_name, self.local_rank)
        )
        # In-process arena fence: the standalone persist thread streams
        # from the arena's mapped bytes while the trainer may be staging
        # the next step into it — same contract the agent saver gets from
        # its arena mutex.  Taken INSIDE the cross-process fencing lock.
        self._arena_mu = threading.Lock()
        self._last_saved_step = -1
        self._last_persist_step = -1
        # Train-stall accounting: how long save_to_memory/_storage blocked
        # the step loop (the paper's headline "second-scale stall").
        self.last_stall_ms = 0.0
        self._last_staged_bytes = 0
        self._stat_client: Optional[SharedDict] = None
        # step -> "a corrupt shard was seen while reading this step's
        # candidates" (populated per load; drives quarantine decisions).
        self._step_had_corruption: Dict[int, bool] = {}
        # {path: [box, ...]} of the current load()'s target — drives the
        # reshard-plan shard selection on the storage path; None when
        # loading without a target (ShardSource mode reads everything).
        self._restore_boxes = None
        # (step, pid) -> ShardManifest fetched during shard selection and
        # REUSED by the data read (one header+meta pass per shard per
        # load, not two); reset per load().
        self._man_cache: Dict[Tuple[int, int], Any] = {}
        # Dirty-fence memory: which step physically holds each tensor's
        # last-persisted slice bytes (incremental saves).  Lost on
        # restart — the next save is then full, never wrong.
        self._dirty = slicer.DirtyTracker()

        self.agent_mode = os.path.exists(
            socket_path("queue", ckpt_queue_name(self.job_name))
        )
        self._queue: Optional[SharedQueue] = None
        self._lock: Optional[SharedLock] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._futures: list = []
        if self.agent_mode:
            self._queue = SharedQueue(ckpt_queue_name(self.job_name))
            self._lock = SharedLock(
                ckpt_lock_name(self.job_name, self.local_rank)
            )
            logger.info("checkpoint engine in agent mode")
        else:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-persist"
            )

    # -- save ---------------------------------------------------------------
    def _stage(self, step: int, state: Any, meta: Optional[dict]) -> Tuple[
        Dict[str, np.ndarray], dict
    ]:
        # Overlap all D2H copies before the synchronous flatten walk.
        def _prefetch(x):
            if isinstance(x, jax.Array):
                try:
                    x.copy_to_host_async()
                # graftcheck: disable=CC104 -- prefetch is a pure
                # optimization; the flatten walk below copies
                # synchronously either way
                except Exception:  # noqa: BLE001
                    pass
            return None

        jax.tree_util.tree_map(_prefetch, state)
        tensors, info = tree_utils.flatten_to_shards(state)
        self._last_staged_bytes = sum(
            int(np.asarray(a).nbytes) for a in tensors.values()
        )
        extra = {
            "step": step,
            "meta": meta or {},
            "tensors_info": info,
            "process_id": self.process_id,
            "num_processes": self.num_processes,
            "ckpt_dir": self.ckpt_dir,
            "time": time.time(),
            # Every rank's leaf paths (identical pytree): lets the commit
            # coverage proof notice a dead rank's EXCLUSIVE tensors are
            # absent, not just torn slices of shared ones.
            "tree_paths": sorted({m["path"] for m in info.values()}),
        }
        # A zero-copy persist (agent saver on the fencing lock, or the
        # standalone persist thread on the arena mutex) legitimately
        # holds its lock for a WHOLE streamed storage write, which can
        # exceed a minute on slow storage — waiting is correct; crashing
        # the trainer's save (or hanging it silently) is not.
        if self._lock is not None:
            self._acquire_patiently(
                self._lock.acquire, "shm fencing lock"
            )
        try:
            self._acquire_patiently(
                self._arena_mu.acquire, "arena mutex"
            )
            try:
                self._arena.write_state(tensors, extra=extra)
            finally:
                self._arena_mu.release()
        finally:
            if self._lock is not None:
                self._lock.release()
        self._last_saved_step = step
        return tensors, extra

    @staticmethod
    def _acquire_patiently(
        acquire, what: str, budget: float = 600.0
    ) -> None:
        """Bounded lock wait for the save path: warn each minute, raise
        only after the persist path's own 600s budget — one home for the
        deadline arithmetic both save-path locks share."""
        deadline = time.time() + budget
        while not acquire(timeout=60.0):
            if time.time() >= deadline:
                raise TimeoutError(f"could not acquire {what}")
            logger.warning(
                "save: %s still held (persist in flight?); waiting", what
            )

    def save_to_memory(
        self, step: int, state: Any, meta: Optional[dict] = None
    ) -> None:
        """Stage into shm only — the synchronous train stall; the state
        survives worker crash/restart on this host."""
        t0 = time.perf_counter()
        self._stage(step, state, meta)
        self._note_stall(step, time.perf_counter() - t0)

    def _note_stall(self, step: int, seconds: float) -> None:
        """Surface the measured train stall: local gauge, the agent's
        shared stat dict (scraped as ``ckpt_stall_ms_last``), and the
        master's goodput accounting — the stall is real lost train time
        even though no restart happened."""
        self.last_stall_ms = seconds * 1000.0
        staged_mbps = (
            self._last_staged_bytes / max(seconds, 1e-9) / (1 << 20)
        )
        perf_stats.set("ckpt_stall_ms_last", self.last_stall_ms)
        perf_stats.set("ckpt_staged_mbps", staged_mbps)
        journal("ckpt.stage", step=step, rank=self.process_id,
                stall_ms=round(self.last_stall_ms, 1),
                mbps=round(staged_mbps, 1))
        logger.info(
            "flash ckpt: staged step %d to shm in %.3fs (%.0f MB/s, "
            "train stalled %.1fms)",
            step, seconds, staged_mbps, self.last_stall_ms,
        )
        if self.agent_mode:
            try:
                # One round trip for both stats, short timeout: this sits
                # inside the save path whose whole point is a tens-of-ms
                # stall — a dead stat server (agent restarting) must cost
                # ~2s once, not the 60s default retry budget per save.
                self._stat().update(
                    {
                        f"stall_ms_{self.local_rank}": round(
                            self.last_stall_ms, 3
                        ),
                        f"staged_mbps_{self.local_rank}": round(
                            staged_mbps, 1
                        ),
                    },
                    timeout=2.0,
                )
            except Exception as e:  # noqa: BLE001
                logger.debug("stall stat report failed: %s", e)
        if self.client is not None:
            try:
                self.client.report_ckpt_perf(
                    step=step,
                    stall_ms=self.last_stall_ms,
                    staged_mbps=staged_mbps,
                )
            except Exception as e:  # noqa: BLE001
                logger.debug("ckpt perf report failed: %s", e)

    def _stat(self) -> SharedDict:
        """Cached client connection to the agent saver's stat dict."""
        if self._stat_client is None:
            self._stat_client = SharedDict(ckpt_stat_name(self.job_name))
        return self._stat_client

    def save_to_storage(
        self, step: int, state: Any, meta: Optional[dict] = None
    ) -> None:
        """Stage into shm + request async persistence."""
        t0 = time.perf_counter()
        tensors, extra = self._stage(step, state, meta)
        self._note_stall(step, time.perf_counter() - t0)
        if self.agent_mode:
            self._queue.put(
                {
                    "event": "save",
                    "step": step,
                    "local_rank": self.local_rank,
                    "process_id": self.process_id,
                    "num_processes": self.num_processes,
                    "ckpt_dir": self.ckpt_dir,
                    "max_to_keep": self.max_to_keep,
                }
            )
        else:
            fut = self._pool.submit(self._persist, step)
            self._futures.append((step, fut))

    def _persist(self, step: int) -> None:
        """Standalone async persist: stream the shm arena's staged bytes.

        NOT the host arrays from ``flatten_to_shards`` — on the CPU
        backend those can be zero-copy aliases of live (donated) jax
        buffers, and an async stream from them races the next train step
        into a torn shard whose CRC (computed in the same pass over the
        same torn bytes) would still validate.  The arena holds a stable
        staged copy; ``_arena_mu`` fences it against concurrent
        re-staging for the duration of the zero-copy stream (the
        ``ckpt_zero_copy=False`` knob trades that hold for one copy,
        exactly like the agent saver)."""
        try:
            zero_copy = self._ctx.ckpt_zero_copy
            with self._arena_mu:
                read = self._arena.read_state(copy=not zero_copy)
                if read is None:
                    logger.error(
                        "NOT persisting step %d: arena holds no state",
                        step,
                    )
                    return
                tensors, extra = read
                staged_step = int(extra.get("step", -1))
                if staged_step != step:
                    logger.info(
                        "persist: arena holds step %d (wanted %d) — "
                        "persisting the staged one", staged_step, step,
                    )
                    step = staged_step
                reason = shard_file.validate_staged_state(
                    tensors, extra,
                    expect_process_id=self.process_id,
                    expect_num_processes=self.num_processes,
                )
                if reason is not None:
                    integrity_counters.inc("ckpt_staged_rejected")
                    logger.error(
                        "NOT persisting step %d: staged state invalid "
                        "(%s)", step, reason,
                    )
                    return
                if zero_copy:
                    self._stream_shard(step, tensors, extra)
            if not zero_copy:
                self._stream_shard(step, tensors, extra)
            self._last_persist_step = step
            if self.process_id == 0:
                self._commit_when_ready(step)
        except Exception:  # noqa: BLE001
            logger.exception("checkpoint persist of step %d failed", step)

    def _stream_shard(self, step: int, tensors, extra) -> None:
        """Sliced + incremental streamed persist: this rank writes only
        its disjoint slice of replicated tensors (aggregate fleet write
        bandwidth scales with world size) and skips tensors whose dirty
        fence has not tripped since their holder step (a meta ref
        instead of a rewrite)."""
        chaos.inject("ckpt.slow_storage", step=step, rank=self.process_id)
        t0 = time.perf_counter()
        plan = slicer.plan_persist(
            tensors, extra,
            process_id=self.process_id,
            num_processes=self.num_processes,
            sliced=self._ctx.ckpt_sliced_persist,
            tracker=self._dirty if self._ctx.ckpt_incremental else None,
            holder_exists=lambda s: self.storage.exists(
                shard_file.shard_path(self.ckpt_dir, s, self.process_id)
            ),
        )
        stats = shard_file.write_shard_from_views(
            self.storage, self.ckpt_dir, step, self.process_id,
            plan.tensors, plan.extra,
            workers=self._ctx.ckpt_persist_workers,
            meta_extra=plan.meta_extra,
        )
        self._dirty.note_plan(plan, step, stats.get("crcs", {}))
        mbps = (
            stats["total_bytes"]
            / max(time.perf_counter() - t0, 1e-9) / (1 << 20)
        )
        journal("ckpt.persist", step=step, rank=self.process_id,
                mbps=round(mbps, 1),
                bytes=int(stats["total_bytes"]),
                skipped=int(plan.skipped))
        perf_stats.set("ckpt_persist_mbps", mbps)
        # Standalone = one rank per process: its own persist rate IS its
        # contribution to the fleet aggregate the bench/master sum up.
        perf_stats.set("ckpt_agg_persist_mbps", mbps)
        perf_stats.set("ckpt_tensors_skipped", float(plan.skipped))
        if plan.skipped:
            logger.info(
                "flash ckpt: step %d incremental — %d/%d tensors "
                "unchanged (refs), %d of %d staged bytes written",
                step, plan.skipped, len(plan.tensors),
                plan.written_bytes, plan.logical_bytes,
            )
        if self.client is not None:
            try:
                self.client.report_ckpt_perf(
                    step=step, stall_ms=0.0, persist_mbps=mbps,
                    agg_persist_mbps=mbps,
                    tensors_skipped=plan.skipped,
                )
            except Exception as e:  # noqa: BLE001
                logger.debug("persist perf report failed: %s", e)

    def _commit_when_ready(self, step: int, timeout: float = 600.0) -> bool:
        """Leader: wait for every process's done file (optionally gated by
        the master's cross-node step barrier), prove the slice set covers
        every tensor, then advance the tracker."""
        deadline = time.time() + timeout
        shard_file.wait_sync_barrier(
            self.client, step, min(60.0, timeout / 4)
        )
        while time.time() < deadline:
            if shard_file.all_shards_done(
                self.storage, self.ckpt_dir, step, self.num_processes
            ):
                # Done votes in hand, every write is finished: a failed
                # coverage proof is terminal for this step (the previous
                # committed step stays the restore point).
                if self._ctx.ckpt_commit_coverage and not slicer.commit_gate(
                    self.storage, self.ckpt_dir, step
                ):
                    journal("ckpt.commit", step=step, ok=False,
                            verdict="coverage_blocked")
                    return False
                shard_file.commit(
                    self.storage, self.ckpt_dir, step,
                    keep_last=shard_file.resolve_keep_last(
                        self.max_to_keep
                    ),
                )
                journal("ckpt.commit", step=step, ok=True,
                        verdict="coverage_proven"
                        if self._ctx.ckpt_commit_coverage
                        else "ungated")
                return True
            time.sleep(0.5)
        logger.warning("commit of step %d timed out", step)
        journal("ckpt.commit", step=step, ok=False, verdict="timeout")
        return False

    def wait(self, timeout: float = 600.0) -> bool:
        """Block until the last storage save is fully committed."""
        for step, fut in self._futures:
            try:
                fut.result(timeout=timeout)
            except Exception:  # noqa: BLE001
                logger.exception("pending persist failed")
        self._futures = []
        if self._last_saved_step < 0:
            return True
        deadline = time.time() + timeout
        while time.time() < deadline:
            committed = shard_file.latest_step(self.storage, self.ckpt_dir)
            if committed is not None and committed >= self._last_persist_step:
                return True
            if self.agent_mode:
                stat = self._stat()
                try:
                    done = stat.get(f"persisted_{self.local_rank}", -1)
                    if done is not None and int(done) >= self._last_saved_step:
                        return True
                # graftcheck: disable=CC104 -- poll loop by design: the
                # stat read races the agent writer and simply retries
                # 0.5s later until the wait deadline
                except Exception:  # noqa: BLE001
                    pass
            time.sleep(0.5)
        return False

    # -- load ---------------------------------------------------------------
    def load(
        self, target: Any = None, *, target_mesh=None
    ) -> Optional[Tuple[Any, dict]]:
        """Restore the newest available state: shm (warm) else storage.

        With ``target`` given, returns (pytree-like-target, meta); without,
        returns (ShardSource, meta) for caller-side assembly.

        ``target_mesh`` (restore-to-any-mesh, ROADMAP item 2 entry
        point): re-home ``target`` onto that mesh before assembly — each
        leaf keeps its PartitionSpec (replicated for non-NamedSharding
        leaves) but lands on the NEW world's devices, so a checkpoint
        saved by any M-process world restores onto whatever mesh the new
        world has.  The storage path then reads only the source shards
        the reshard plan proves it needs (see :meth:`_select_pids`)."""
        if target is not None and target_mesh is not None:
            target = self._retarget(target, target_mesh)
        self._restore_boxes = (
            self._target_boxes(target) if target is not None else None
        )
        self._man_cache = {}
        # Zero-copy shm read when the tree is materialized HERE and this
        # process is provably the arena's only writer: with a target,
        # restore_to_target device_puts every piece before load() returns,
        # while the arena stays mapped and (standalone mode) nothing else
        # can write it — so views never outlive their mapping.  In AGENT
        # mode the saver process may concurrently write_state the same
        # arena (replica seed_from_replicas after a re-rendezvous) and
        # this unlocked read would see torn bytes, so it copies.  Without
        # a target the ShardSource escapes to the caller with unbounded
        # lifetime: copy.
        got = self._load_from_shm(
            copy=target is None or self.agent_mode
        )
        got = self._agree_shm_step(got)  # collective: same branch all ranks
        if got is not None:
            source, extra = got
            try:
                result = self._finish_load(source, extra, target)
            except KeyError:
                result = None
                logger.warning(
                    "shm restore incomplete; falling back to storage"
                )
            # Collective: if any rank's shm assembly failed, all ranks
            # fall back together (collective-count symmetry).
            if self._all_ranks_ok(result is not None):
                return result
        # Storage: committed step first, then newer uncommitted steps whose
        # available shards still cover the target (e.g. a breakpoint save
        # from a partial world with replicated state).  Corruption is
        # treated like absence — a damaged step is skipped (and
        # quarantined), never allowed to abort the whole restore.
        result = None
        chosen = -1
        self._step_had_corruption = {}
        for source, extra, selective in self._storage_candidates():
            cand_step = int(extra.get("step", -1))
            try:
                result = self._assemble_candidate(
                    source, extra, target, selective, cand_step
                )
                chosen = max(cand_step, 0)
                break
            except KeyError as e:
                logger.warning(
                    "storage step %s not restorable (%s); trying older",
                    extra.get("step"), e,
                )
            except Exception as e:  # noqa: BLE001 - unverified v1 payloads
                # can fail assembly in arbitrary ways; the ladder must
                # fall through to an older candidate, not crash.
                logger.warning(
                    "storage step %s failed to assemble (%s: %s); "
                    "trying older",
                    extra.get("step"), type(e).__name__, e,
                )
            if self._step_had_corruption.get(cand_step):
                self._quarantine(cand_step)
        return self._agree_storage_step(result, chosen, target)

    def _assemble_candidate(
        self, source, extra, target, selective: bool, step: int
    ):
        """Assemble one storage candidate; when PLAN-SELECTED reads left
        the target uncoverable (selection is bandwidth, never
        correctness), retry the same step reading every shard in full
        before letting the ladder fall to an older step."""
        try:
            return self._finish_load(source, extra, target)
        except KeyError:
            if not selective:
                raise
            logger.warning(
                "storage step %d uncoverable from plan-selected reads; "
                "retrying with a full read", step,
            )
            full = self._read_step(step, selective=False)
            if full is None:
                raise
            return self._finish_load(full[0], full[1], target)

    def _all_ranks_ok(self, ok: bool) -> bool:
        """Collective AND over processes (True everywhere or False
        everywhere); trivially ``ok`` single-process."""
        if self.num_processes <= 1:
            return ok
        try:
            import jax as _jax
            from jax.experimental import multihost_utils

            if _jax.process_count() != self.num_processes:
                return ok
            flags = np.asarray(
                multihost_utils.process_allgather(np.int64(1 if ok else 0))
            ).reshape(-1)
            return bool(flags.all())
        except Exception:  # noqa: BLE001
            return ok

    def _agree_storage_step(self, result, chosen: int, target):
        """Cross-rank agreement on the restored storage step: per-rank read
        failures must not let ranks silently resume from different steps.
        All processes call this (collective); single-process is a no-op."""
        if self.num_processes <= 1:
            return result
        try:
            import jax as _jax
            from jax.experimental import multihost_utils

            if _jax.process_count() != self.num_processes:
                return result
            steps = np.asarray(
                multihost_utils.process_allgather(np.int64(chosen))
            ).reshape(-1)
        except Exception:  # noqa: BLE001 - not in a distributed context
            return result
        if (steps == chosen).all():
            return result  # unanimous (including unanimous "nothing")
        if (steps < 0).any():
            agreed = -1  # someone has nothing restorable: all start fresh
        else:
            agreed = int(steps.min())
        logger.warning(
            "storage restore steps disagree across ranks (%s); agreeing "
            "on %s", steps.tolist(), agreed if agreed >= 0 else "fresh start",
        )
        retry = None
        if agreed >= 0:
            if chosen == agreed:
                retry = result
            else:
                for source, extra, selective in self._storage_candidates():
                    if int(extra.get("step", -1)) != agreed:
                        continue
                    try:
                        retry = self._assemble_candidate(
                            source, extra, target, selective, agreed
                        )
                    except Exception as e:  # noqa: BLE001 - uncoverable or
                        # damaged agreed step: fall to the collective below
                        logger.warning(
                            "agreed step %d failed to assemble: %s",
                            agreed, e,
                        )
                        retry = None
                    break
        # Second collective: every rank must have the agreed step or all
        # abandon the restore together.
        ok = np.asarray(
            multihost_utils.process_allgather(
                np.int64(1 if (retry is not None or agreed < 0) else 0)
            )
        ).reshape(-1)
        if not ok.all():
            logger.warning(
                "agreed storage step %d unrestorable on some rank; "
                "starting fresh", agreed,
            )
            return None
        return retry if agreed >= 0 else None

    def _finish_load(self, source, extra, target):
        meta = extra.get("meta", {})
        meta.setdefault("step", extra.get("step", 0))
        if target is None:
            return source, meta
        state = tree_utils.restore_to_target(target, source)
        return state, meta

    def _agree_shm_step(self, got):
        """Cross-rank shard-step consistency check (reference ckpt_saver's
        ``check_complete_step_before_save`` / shard-step checks): a warm
        restore is only valid when every process staged the SAME step —
        staging lag at a crash can leave ranks a few steps apart, and mixing
        them silently corrupts replicated state.  On disagreement fall back
        to storage, whose commit protocol is all-ranks-atomic.

        Every process must call this (it is a collective)."""
        if self.num_processes <= 1:
            return got
        try:
            from jax.experimental import multihost_utils

            if jax.process_count() != self.num_processes:
                return got
            own = -1 if got is None else int(got[1].get("step", -1))
            steps = np.asarray(
                multihost_utils.process_allgather(np.int64(own))
            ).reshape(-1)
        except Exception:  # noqa: BLE001 - not in a distributed context
            return got
        if (steps >= 0).all() and (steps == steps[0]).all():
            return got
        if got is not None:
            logger.warning(
                "shm restore steps disagree across ranks (%s); "
                "falling back to committed storage checkpoint",
                steps.tolist(),
            )
        return None

    def _load_from_shm(self, copy: bool = True):
        try:
            # reopen() munmaps: fence against a concurrent standalone
            # persist thread streaming from the current mapping.
            with self._arena_mu:
                self._arena.reopen()
                read = self._arena.read_state(copy=copy)
        except (FileNotFoundError, OSError):
            return None  # no arena yet: first run on this host
        except Exception:  # noqa: BLE001
            logger.exception("shm restore failed; trying storage")
            return None
        if read is None:
            return None
        tensors, extra = read
        info = extra.get("tensors_info", {})
        if not info:
            return None
        # A warm restore is only valid for the same world size — a changed
        # world's local shards won't match this process's old layout;
        # storage has every process's shards for true resharding.
        if extra.get("num_processes") != self.num_processes or extra.get(
            "process_id"
        ) != self.process_id:
            logger.info(
                "shm state belongs to another world layout "
                "(proc %s/%s vs %s/%s); falling back to storage",
                extra.get("process_id"), extra.get("num_processes"),
                self.process_id, self.num_processes,
            )
            return None
        source = tree_utils.ShardSource()
        source.add(tensors, info)
        logger.info(
            "flash ckpt: warm restore from shm (step %s)", extra.get("step")
        )
        return source, extra

    @staticmethod
    def _retarget(target: Any, target_mesh) -> Any:
        """Re-home a target tree onto ``target_mesh``: sharding-bearing
        leaves become ShapeDtypeStruct placeholders with the SAME
        PartitionSpec on the new mesh (NamedSharding leaves keep their
        factorization; any other sharding replicates); host leaves pass
        through untouched."""
        from jax.sharding import NamedSharding, PartitionSpec

        def per_leaf(leaf):
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:
                return leaf
            spec = (
                sharding.spec
                if isinstance(sharding, NamedSharding)
                else PartitionSpec()
            )
            return jax.ShapeDtypeStruct(
                tuple(leaf.shape),
                leaf.dtype,
                sharding=NamedSharding(target_mesh, spec),
            )

        return jax.tree_util.tree_map(per_leaf, target)

    @staticmethod
    def _target_boxes(target: Any) -> Optional[Dict[str, list]]:
        """{path: [addressable boxes]} of a target tree — the question
        the reshard planner answers shard selection for.  ``None`` when
        the tree cannot be described (selection then reads everything)."""
        try:
            from jax.tree_util import keystr, tree_flatten_with_path

            from dlrover_tpu.checkpoint.tree_utils import (
                _leaf_placements,
                _norm_index,
            )

            out: Dict[str, list] = {}
            for path, leaf in tree_flatten_with_path(target)[0]:
                name = keystr(path)
                placed = _leaf_placements(leaf)
                if placed is not None:
                    _s, gshape, placements = placed
                    boxes = {
                        _norm_index(idx, gshape) for _d, idx in placements
                    }
                else:
                    shape = tuple(
                        getattr(leaf, "shape", np.shape(leaf))
                    )
                    boxes = {tuple((0, d) for d in shape)}
                out[name] = sorted(boxes)
            return out
        except Exception as e:  # noqa: BLE001 - selection is an
            # optimization; an undescribable target just reads all shards
            logger.debug("target-box derivation failed: %s", e)
            return None

    def _manifest(self, step: int, pid: int):
        """Cached header+meta fetch: shard selection and the data read
        share ONE verified meta pass per shard per load (PR 6 accepted
        the double read; this PR retires it).  Raises
        :class:`ShardCorruptionError`; ``None`` when absent."""
        man = self._man_cache.get((step, pid))
        if man is None:
            man = shard_file.read_shard_manifest(
                self.storage, self.ckpt_dir, step, pid
            )
            if man is not None:
                self._man_cache[(step, pid)] = man
        return man

    @staticmethod
    def _box_overlap(a, b) -> bool:
        if len(a) != len(b):
            return False
        return all(
            max(s1, s2) < min(e1, e2) for (s1, e1), (s2, e2) in zip(a, b)
        )

    def _needed_keys(self, man):
        """The minimal piece set this rank must read from one shard: keys
        whose box overlaps any target box.  ``None`` = read everything
        (no target, or an undescribable manifest)."""
        boxes = self._restore_boxes
        if boxes is None:
            return None
        try:
            info = man.extra.get("tensors_info") or {}
            need = set()
            for key, m in info.items():
                tb = boxes.get(m["path"])
                if not tb:
                    continue
                box = tuple(tuple(int(v) for v in p) for p in m["index"])
                if any(self._box_overlap(box, b) for b in tb):
                    need.add(key)
            return need
        except Exception as e:  # noqa: BLE001 - filtering is bandwidth;
            # an odd manifest just reads in full
            logger.debug("needed-key derivation failed: %s", e)
            return None

    def _select_pids(self, step: int, pids: list) -> list:
        """Plan-driven shard selection: of a step's shards, which source
        ranks' pieces does THIS process's target actually overlap?  A
        dp=16 world restoring replicated params should read one rank's
        shard, not sixteen — unless the step was SLICE-persisted, where
        the disjoint slices of every needed box are all needed (and only
        ranks holding overlapping pieces are).  Any failure (unreadable
        meta, uncoverable target, planner error) falls back to reading
        everything — selection is bandwidth, never correctness.  The
        manifests fetched here are cached and reused by the data read."""
        boxes = self._restore_boxes
        if boxes is None or len(pids) <= 1:
            return pids
        try:
            manifests = {}
            for pid in pids:
                man = self._manifest(step, pid)
                if man is None:
                    continue
                if not (man.extra.get("tensors_info") or {}):
                    return pids
                manifests[pid] = man
            if not manifests:
                return pids
            if any(m.extra.get("sliced") for m in manifests.values()):
                chosen = []
                for p in pids:
                    if p not in manifests:
                        continue
                    need = self._needed_keys(manifests[p])
                    if need is None:
                        # Derivation failed for this shard: "read
                        # everything" — excluding it would make every
                        # load pay the uncoverable-assembly full-read
                        # retry instead.
                        return pids
                    if need:
                        chosen.append(p)
            else:
                from dlrover_tpu.reshard.plan import ranks_needed

                need = ranks_needed(
                    {
                        pid: m.extra["tensors_info"]
                        for pid, m in manifests.items()
                    },
                    boxes,
                    dst_rank=self.process_id,
                )
                chosen = [p for p in pids if p in set(need)]
            if not chosen:
                return pids
            if len(chosen) < len(pids):
                logger.info(
                    "flash ckpt: reshard plan needs %d/%d shards of "
                    "step %d", len(chosen), len(pids), step,
                )
            return chosen
        except Exception as e:  # noqa: BLE001 - see docstring: selection
            # must never turn a restorable step into a failed one
            logger.debug(
                "shard selection for step %d fell back to full read: %s",
                step, e,
            )
            return pids

    def _read_step(self, step: int, selective: bool = True):
        """Read one step's shards into a ShardSource: plan-selected ranks
        only, needed pieces only, shards read CONCURRENTLY (each rank's
        restore pulls its minimal slice set from multiple slice files at
        once).  Returns ``(source, extra, was_selective)`` or ``None``
        when nothing was readable.

        A shard that fails verification is skipped like an absent one
        (the step may still cover the target from other ranks' shards).
        """
        source = tree_utils.ShardSource()
        extra_out = None
        corrupt = False
        read_failed = False
        pids = shard_file.list_shard_ids(self.storage, self.ckpt_dir, step)
        chosen = self._select_pids(step, pids) if selective else list(pids)
        was_selective = selective and (
            len(chosen) < len(pids) or self._restore_boxes is not None
        )

        def _read_one(pid: int, restrict: bool):
            try:
                man = self._manifest(step, pid)
                if man is None:
                    return pid, "absent", None
                keys = self._needed_keys(man) if restrict else None
                got = shard_file.read_shard_pieces(
                    self.storage, self.ckpt_dir, step, pid,
                    manifest=man, keys=keys,
                )
                if got is None:
                    # Absent counts as a failed SELECTED read too: a
                    # shard GC'd between list and read must trigger the
                    # unselected-replica fallback below, not starve it.
                    return pid, "absent", None
                return pid, "ok", got
            except shard_file.ShardCorruptionError as e:
                return pid, "corrupt", e
            except Exception as e:  # noqa: BLE001 - I/O hiccup: treat
                # the shard as absent (no quarantine — nothing proves
                # the bytes themselves are damaged).
                return pid, "error", e

        def _merge(results) -> None:
            nonlocal extra_out, corrupt, read_failed
            for pid, status, payload in results:
                if status == "ok":
                    tensors, slices, extra = payload
                    source.add(
                        tensors, extra.get("tensors_info", {}), slices
                    )
                    if pid == self.process_id or extra_out is None:
                        extra_out = extra
                elif status == "corrupt":
                    corrupt = True
                    read_failed = True
                    self._note_corruption(step, pid, payload)
                elif status == "error":
                    read_failed = True
                    logger.warning(
                        "shard (step %d, proc %d) unreadable (%s: %s); "
                        "skipping", step, pid,
                        type(payload).__name__, payload,
                    )
                else:
                    read_failed = True

        _merge(self._read_many(chosen, selective, _read_one))
        if read_failed and len(chosen) < len(pids):
            # A plan-selected shard was damaged/absent; the skipped
            # ranks may still cover the target (replicated layouts).
            # Selection saves bandwidth — it must never cost a
            # restorable step.
            rest = [p for p in pids if p not in set(chosen)]
            _merge(self._read_many(rest, False, _read_one))
        self._step_had_corruption[step] = corrupt
        if extra_out is None:
            if corrupt:
                self._quarantine(step)
            return None
        return source, extra_out, was_selective

    def _read_many(self, pids: list, restrict: bool, read_one):
        """Concurrent shard reads (bounded by ``ckpt_shard_io_workers``),
        results in ``pids`` order so extra_out stays deterministic."""
        if not pids:
            return []
        workers = min(
            len(pids), max(1, int(self._ctx.ckpt_shard_io_workers))
        )
        if workers <= 1 or len(pids) <= 1:
            return [read_one(pid, restrict) for pid in pids]
        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="ckpt-read"
        ) as pool:
            return list(pool.map(lambda p: read_one(p, restrict), pids))

    def _storage_candidates(self):
        """Yield (source, extra, selective) per restorable storage step:
        the committed (tracker) step first, then remaining step dirs
        newest-first.  The caller validates coverage by attempting
        assembly — an uncommitted step is usable when its present shards
        cover the target (fully replicated layouts need any one rank's
        shard; slice-persisted layouts need every overlapping slice).

        A step whose every shard is unreadable *and* showed corruption
        is quarantined on the spot."""
        committed = shard_file.latest_step(self.storage, self.ckpt_dir)
        steps = shard_file.list_steps(self.storage, self.ckpt_dir)
        candidates = []
        # Only a LIVE committed step is a candidate: on backends without
        # rename the quarantine is a marker file (list_steps filters it),
        # and the tracker must not smuggle the damaged step back in on
        # every restart.
        if committed is not None and committed in steps:
            candidates.append(committed)
        candidates.extend(
            s for s in sorted(steps, reverse=True) if s != committed
        )
        for step in candidates:
            got = self._read_step(step)
            if got is None:
                continue
            source, extra_out, was_selective = got
            logger.info(
                "flash ckpt: restore from storage step %d%s",
                step, "" if step == committed else " (uncommitted)",
            )
            yield source, extra_out, was_selective

    # -- integrity bookkeeping ----------------------------------------------
    def _note_corruption(
        self, step: int, pid: int, err: Exception
    ) -> None:
        integrity_counters.inc("ckpt_corruption_detected")
        logger.warning(
            "corrupt checkpoint shard (step %d, proc %d): %s",
            step, pid, err,
        )
        self._report_integrity(
            {
                "event": "corruption_detected",
                "step": step,
                "process_id": pid,
                "reason": str(err),
            }
        )

    def _quarantine(self, step: int) -> None:
        where = shard_file.quarantine_step(
            self.storage, self.ckpt_dir, step
        )
        if where is None:
            return
        integrity_counters.inc("ckpt_step_quarantined")
        self._report_integrity(
            {"event": "step_quarantined", "step": step, "path": where}
        )

    def _report_integrity(self, event: dict) -> None:
        """Best-effort diagnosis report: the master log is where silent
        bit-rot becomes an operator signal; the restore proceeds either
        way."""
        if self.client is None:
            return
        try:
            self.client.report_diagnosis_data(
                DiagnosisDataType.CKPT_INTEGRITY, json.dumps(event)
            )
        except Exception as e:  # noqa: BLE001
            logger.debug("ckpt integrity report failed: %s", e)

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self._arena.close()
