"""User-facing Flash Checkpointer.

Parity with the reference's per-framework checkpointers
(``flash_checkpoint/ddp.py:25 DdpCheckpointer`` etc.) — in the TPU build one
class covers every parallelism since state is always a sharded pytree
(GSPMD erases the DDP/FSDP/Megatron distinction the reference needs five
engines for).

Usage::

    ckpt = FlashCheckpointer("/ckpt/run1")
    ckpt.save(state, meta={"step": step})                # shm only (fast path)
    ckpt.save(state, meta={"step": step}, storage=True)  # + async persist
    restored = ckpt.load(target=state)                   # warm shm else disk
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.common.storage import CheckpointStorage


class FlashCheckpointer:
    def __init__(
        self,
        ckpt_dir: str,
        *,
        job_name: str = "",
        storage: Optional[CheckpointStorage] = None,
        master_client=None,
        # None = default rotation (3); 0 = keep all; N > 0 = keep newest N
        max_to_keep: Optional[int] = None,
    ):
        self.engine = CheckpointEngine(
            ckpt_dir,
            job_name=job_name,
            storage=storage,
            master_client=master_client,
            max_to_keep=max_to_keep,
        )

    def save(
        self,
        state: Any,
        meta: Optional[dict] = None,
        storage: bool = False,
    ) -> None:
        step = int((meta or {}).get("step", 0))
        if storage:
            self.engine.save_to_storage(step, state, meta)
        else:
            self.engine.save_to_memory(step, state, meta)

    def load(self, target: Any = None) -> Optional[Tuple[Any, dict]]:
        return self.engine.load(target)

    def wait(self, timeout: float = 600.0) -> bool:
        return self.engine.wait(timeout)

    def close(self) -> None:
        self.engine.close()
