"""Flash Checkpoint: async shared-memory pytree checkpointing.

TPU-native re-design of the reference's Flash Checkpoint (SURVEY.md §3.2,
``trainer/torch/flash_checkpoint/`` + ``elastic_agent/torch/ckpt_saver.py``):
workers stage the addressable shards of a sharded JAX pytree into a POSIX shm
arena (microseconds-to-milliseconds of step blocking), an async daemon
persists shm -> storage with a done-file commit protocol, and restore prefers
the still-warm shm arena (seconds) over storage (minutes) — including
**reshard-on-restore** when the world changed (Tenplex-style; the reference
sidesteps this with fixed-world restarts).
"""

from dlrover_tpu.checkpoint.checkpointer import FlashCheckpointer  # noqa: F401
from dlrover_tpu.checkpoint.engine import CheckpointEngine  # noqa: F401
