"""Flash Checkpoint: async shared-memory pytree checkpointing.

TPU-native re-design of the reference's Flash Checkpoint (SURVEY.md §3.2,
``trainer/torch/flash_checkpoint/`` + ``elastic_agent/torch/ckpt_saver.py``):
workers stage the addressable shards of a sharded JAX pytree into a POSIX shm
arena (microseconds-to-milliseconds of step blocking), an async daemon
persists shm -> storage with a done-file commit protocol, and restore prefers
the still-warm shm arena (seconds) over storage (minutes) — including
**reshard-on-restore** when the world changed (Tenplex-style; the reference
sidesteps this with fixed-world restarts).

Re-exports are lazy (PEP 562): ``python -m dlrover_tpu.checkpoint.fsck``
runs on operator/CI hosts without pulling jax in through the engine import.
"""

_LAZY = {
    "FlashCheckpointer": "dlrover_tpu.checkpoint.checkpointer",
    "CheckpointEngine": "dlrover_tpu.checkpoint.engine",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name])
        return getattr(mod, name)
    raise AttributeError(
        f"module 'dlrover_tpu.checkpoint' has no attribute {name!r}"
    )
