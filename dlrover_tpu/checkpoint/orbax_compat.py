"""Orbax interop: bridge flash checkpoints to/from the JAX ecosystem.

The reference integrates per-framework checkpoint zoos (Megatron, DS,
HF ``transformers`` save_pretrained); the JAX ecosystem's lingua franca
is Orbax.  This module lets users (a) hand a flash-checkpoint state to
any Orbax-consuming tool (evaluation harnesses, serving stacks,
``ocp.StandardCheckpointer`` pipelines) and (b) seed a flash-checkpoint
run from an Orbax checkpoint produced elsewhere — closing the
reference's "resume from a foreign checkpoint" capability
(``dlrover/python/common/storage.py`` pluggable backends +
``flash_checkpoint`` per-framework adapters) the TPU-native way.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from dlrover_tpu.common.log import logger


def save_as_orbax(state: Any, path: str) -> None:
    """Write a pytree as a standard Orbax checkpoint at ``path``."""
    import orbax.checkpoint as ocp

    ck = ocp.StandardCheckpointer()
    ck.save(path, state, force=True)
    ck.wait_until_finished()
    logger.info("orbax: wrote checkpoint at %s", path)


def load_from_orbax(path: str, target: Any) -> Any:
    """Restore a pytree (shaped/typed like ``target``) from an Orbax
    checkpoint."""
    import orbax.checkpoint as ocp

    ck = ocp.StandardCheckpointer()
    return ck.restore(path, target=target)


def flash_to_orbax(
    flash_ckpt, orbax_path: str, target: Any
) -> Optional[Tuple[int, str]]:
    """Convert the latest flash checkpoint (shm or storage) into an Orbax
    directory.  ``flash_ckpt`` is a
    :class:`~dlrover_tpu.checkpoint.checkpointer.FlashCheckpointer`;
    ``target`` the state pytree structure.  Returns (step, path) or None
    when there is nothing to convert.

    Note: operates on this process's view of the state — convert from a
    single-process run or a replicated state, or run once per shard with
    distinct paths for partitioned states."""
    restored = flash_ckpt.load(target=target)
    if restored is None:
        return None
    state, meta = restored
    step = int(meta.get("step", 0))
    path = f"{orbax_path.rstrip('/')}/step_{step:010d}"
    save_as_orbax(state, path)
    return step, path


def orbax_to_flash(
    orbax_path: str, flash_ckpt, target: Any, *, step: int = 0
) -> int:
    """Seed a flash-checkpoint run from an Orbax checkpoint: restore into
    ``target``'s structure and persist through the flash engine so the
    next elastic (re)start warm-loads it.  Returns the step recorded."""
    state = load_from_orbax(orbax_path, target)
    flash_ckpt.save(state, meta={"step": step}, storage=True)
    flash_ckpt.wait()
    logger.info(
        "orbax: seeded flash checkpoint (step %d) from %s",
        step, orbax_path,
    )
    return step
