"""Offline checkpoint fsck — the operator-facing integrity surface.

Walks a flash-checkpoint directory, verifies every shard against its CRCs
in bounded chunks (stream + incremental CRC — peak memory is one chunk
plus the meta blob, so shards larger than host RAM verify fine; format
v2; v1 legacy shards get structural checks only), and cross-checks
the commit protocol per step: tracker -> step dir, done votes <-> shard
files, and shard coverage of the committed step.  Quarantined dirs
(``step_N.corrupt`` / ``.quarantined`` marker) are re-verified so the
report names the exact damaged shard.

Usage::

    python -m dlrover_tpu.checkpoint.fsck /ckpt/run1 [--json]

Exit codes: ``0`` clean, ``1`` damage found, ``2`` bad invocation.  Deliberately
importable without jax (see the lazy package ``__init__``), so it runs on any
host that can see the storage.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import List, Optional

from dlrover_tpu.checkpoint import shard_file
from dlrover_tpu.common.storage import CheckpointStorage, PosixDiskStorage

SEV_DAMAGE = "DAMAGE"
SEV_WARN = "WARN"
SEV_INFO = "INFO"


@dataclasses.dataclass
class Finding:
    severity: str  # DAMAGE | WARN | INFO
    step: int  # -1 for directory-level findings
    path: str
    reason: str


@dataclasses.dataclass
class FsckReport:
    ckpt_dir: str
    committed_step: Optional[int] = None
    steps_checked: int = 0
    shards_checked: int = 0
    quarantined_steps: List[int] = dataclasses.field(default_factory=list)
    findings: List[Finding] = dataclasses.field(default_factory=list)

    def add(self, severity: str, step: int, path: str, reason: str) -> None:
        self.findings.append(Finding(severity, step, path, reason))

    @property
    def damaged(self) -> bool:
        return any(f.severity == SEV_DAMAGE for f in self.findings)

    def to_dict(self) -> dict:
        return {
            "ckpt_dir": self.ckpt_dir,
            "committed_step": self.committed_step,
            "steps_checked": self.steps_checked,
            "shards_checked": self.shards_checked,
            "quarantined_steps": self.quarantined_steps,
            "damaged": self.damaged,
            "findings": [dataclasses.asdict(f) for f in self.findings],
        }


def _scan_step_dir(storage: CheckpointStorage, dirpath: str):
    """(shard pid -> filename, done pids) from a step dir's listing —
    path-based, so it also works on quarantine-renamed dirs."""
    shards, done = {}, set()
    for name in storage.listdir(dirpath):
        if name.startswith("shard_") and name.endswith(".ckpt"):
            try:
                shards[int(name[len("shard_") : -len(".ckpt")])] = name
            except ValueError:
                pass
        elif name.startswith(".done_"):
            try:
                done.add(int(name[len(".done_"):]))
            except ValueError:
                pass
    return shards, done


def _check_ref_chain(
    report: FsckReport,
    storage: CheckpointStorage,
    ckpt_dir: str,
    step: int,
    pid: int,
    path: str,
    committed: bool,
    man=None,
) -> None:
    """Verify an incremental shard's reference chain (ISSUE 7): every
    ``ref`` entry must resolve to a live holder shard whose entry has the
    same slice bounds and the promised CRC.  A broken chain on the
    COMMITTED step is damage (the promised restore point cannot be
    rebuilt); on an uncommitted step it only warns (the ladder skips it).
    ``man`` reuses an already-fetched manifest (one meta pass per shard
    per step, the engine's _man_cache discipline)."""
    sev = SEV_DAMAGE if committed else SEV_WARN
    if man is None:
        try:
            man = shard_file.read_shard_manifest(
                storage, ckpt_dir, step, pid
            )
        except shard_file.ShardCorruptionError:
            return  # already reported by the shard verification pass
    if man is None:
        return
    cache: dict = {}
    refs = 0
    for key, tm in man.tensors.items():
        if not isinstance(tm.get("ref"), dict):
            continue
        refs += 1
        ref_step = tm["ref"].get("step")
        if isinstance(ref_step, int) and shard_file.is_step_quarantined(
            storage, ckpt_dir, ref_step
        ):
            report.add(
                sev, step, path,
                f"tensor {key!r} references QUARANTINED step {ref_step}",
            )
            continue
        try:
            shard_file._read_ref_blob(
                storage, ckpt_dir, pid, key, tm, cache
            )
        except shard_file.ShardCorruptionError as e:
            report.add(
                sev, step, path, f"broken ref chain: {e.reason}"
            )
    if refs:
        report.add(
            SEV_INFO, step, path,
            f"incremental shard: {refs} tensor(s) reference prior steps "
            f"{sorted(man.extra.get('ref_steps') or [])}",
        )


def _check_step_dir(
    report: FsckReport,
    storage: CheckpointStorage,
    ckpt_dir: str,
    dirpath: str,
    step: int,
    committed: bool,
) -> None:
    shards, done = _scan_step_dir(storage, dirpath)
    world: Optional[int] = None
    verified = set()
    sliced = False
    has_refs = False
    ref_shards: list = []  # (pid, path) needing a chain check
    for pid in sorted(shards):
        path = os.path.join(dirpath, shards[pid])
        # Stream + incremental CRC: peak memory is one chunk (+ meta), so
        # fsck verifies shards larger than host RAM headroom.  POSIX
        # backends hand back the real file; others fall back to a
        # materialized buffer inside open_read.
        # An unreadable shard of the COMMITTED step is damage (the
        # committed checkpoint is not restorable as promised), not a
        # warning — and it must not silently defuse the coverage check
        # below by keeping `world` unknown.
        f = storage.open_read(path)
        if f is None:
            report.add(
                SEV_DAMAGE if committed else SEV_WARN, step, path,
                "shard listed but unreadable",
            )
            continue
        report.shards_checked += 1
        try:
            with f:
                extra, version = shard_file.verify_shard_file(f, path=path)
        except shard_file.ShardCorruptionError as e:
            report.add(SEV_DAMAGE, step, path, f"corrupt shard: {e.reason}")
            continue
        except OSError as e:
            report.add(
                SEV_DAMAGE if committed else SEV_WARN, step, path,
                f"shard unreadable mid-verify: {e}",
            )
            continue
        verified.add(pid)
        if version == 1:
            report.add(
                SEV_INFO, step, path, "legacy v1 shard (no CRCs to verify)"
            )
        sliced = sliced or bool(extra.get("sliced"))
        if extra.get("ref_steps"):
            has_refs = True
            # Refs resolve against the LIVE ckpt_dir layout, so only
            # in-place step dirs can be chain-checked (a quarantine-
            # renamed dir's refs are reported via its own findings);
            # deferred below so the manifests are fetched ONCE and
            # shared with the coverage proof.
            if dirpath == shard_file.step_dir(ckpt_dir, step):
                ref_shards.append((pid, path))
        w = extra.get("num_processes")
        if isinstance(w, int) and w > 0:
            world = max(world or 0, w)
        if pid not in done:
            report.add(
                SEV_DAMAGE if committed else SEV_WARN, step, path,
                "shard present without its done vote"
                + ("" if committed else " (persist may be in flight)"),
            )
    for pid in sorted(done - set(shards)):
        report.add(
            SEV_DAMAGE, step, os.path.join(dirpath, f".done_{pid:05d}"),
            "done vote present but its shard file is missing",
        )
    # Done votes also bound the world: with every shard unreadable the
    # verified extras can't name num_processes, and the coverage check
    # must still fire for a committed step.
    if done:
        world = max(world or 0, max(done) + 1)
    if committed and world:
        missing = sorted(set(range(world)) - verified)
        if missing:
            report.add(
                SEV_DAMAGE, step, dirpath,
                f"committed step covers {len(verified)}/{world} shards "
                f"(missing or corrupt: {missing})",
            )
    live = dirpath == shard_file.step_dir(ckpt_dir, step)
    coverage = committed and (sliced or has_refs) and live
    if not (ref_shards or coverage):
        return
    # One header+meta fetch per shard, shared by the ref-chain walk and
    # the coverage proof (the verify pass above streams data CRCs and
    # cannot hand back decoded metas).
    manifests: dict = {}
    for pid in sorted(verified):
        try:
            man = shard_file.read_shard_manifest(
                storage, ckpt_dir, step, pid
            )
        except shard_file.ShardCorruptionError:
            continue  # already reported by the verify pass
        if man is not None:
            manifests[pid] = man
    for pid, path in ref_shards:
        _check_ref_chain(
            report, storage, ckpt_dir, step, pid, path, committed,
            man=manifests.get(pid),
        )
    if coverage:
        # The commit gate's tiling proof, re-run offline: a committed
        # sliced/incremental step whose present slices no longer cover
        # every tensor is not the restore point the tracker promises.
        from dlrover_tpu.checkpoint import slicer

        if manifests:
            ok, reason = slicer.step_covers(
                storage, ckpt_dir, step, manifests=manifests
            )
        else:
            ok, reason = False, "no readable shard meta"
        if not ok:
            report.add(
                SEV_DAMAGE, step, dirpath,
                f"slice coverage unprovable: {reason}",
            )


def fsck(
    ckpt_dir: str, storage: Optional[CheckpointStorage] = None
) -> FsckReport:
    storage = storage or PosixDiskStorage()
    report = FsckReport(ckpt_dir=ckpt_dir)

    tracker_raw = storage.read(shard_file.tracker_path(ckpt_dir), mode="r")
    committed: Optional[int] = None
    if tracker_raw is None:
        report.add(
            SEV_INFO, -1, shard_file.tracker_path(ckpt_dir),
            "no tracker (nothing committed yet)",
        )
    else:
        try:
            committed = int(str(tracker_raw).strip())
        except ValueError:
            report.add(
                SEV_DAMAGE, -1, shard_file.tracker_path(ckpt_dir),
                f"tracker content is garbage: {str(tracker_raw)[:80]!r}",
            )
    report.committed_step = committed

    live_steps = sorted(shard_file.list_steps(storage, ckpt_dir))
    quarantined = shard_file.list_quarantined(storage, ckpt_dir)
    report.quarantined_steps = [s for s, _ in quarantined]

    if committed is not None and committed not in live_steps:
        reason = "tracker names step with no step dir (GC'd or lost)"
        if committed in report.quarantined_steps:
            reason = "tracker names a QUARANTINED step"
        report.add(
            SEV_DAMAGE, committed,
            shard_file.step_dir(ckpt_dir, committed), reason,
        )

    for step in live_steps:
        report.steps_checked += 1
        _check_step_dir(
            report, storage, ckpt_dir,
            shard_file.step_dir(ckpt_dir, step), step,
            committed=(step == committed),
        )

    # Quarantined dirs count as damage (the quarantine itself is the
    # evidence) and are re-verified so the report names the bad shard.
    for step, dirpath in quarantined:
        report.add(
            SEV_DAMAGE, step, dirpath,
            "step is quarantined (failed verification during restore)",
        )
        _check_step_dir(
            report, storage, ckpt_dir, dirpath, step, committed=False
        )

    return report


def _print_human(report: FsckReport) -> None:
    print(
        f"fsck {report.ckpt_dir}: {report.steps_checked} live step(s), "
        f"{report.shards_checked} shard(s) checked, committed step "
        f"{report.committed_step if report.committed_step is not None else '-'}"
        + (
            f", quarantined: {report.quarantined_steps}"
            if report.quarantined_steps
            else ""
        )
    )
    for f in report.findings:
        where = f"step {f.step}" if f.step >= 0 else "dir"
        print(f"  {f.severity} {where}: {f.path}: {f.reason}")
    damage = sum(1 for f in report.findings if f.severity == SEV_DAMAGE)
    print(f"fsck: {'DAMAGED (%d problem(s))' % damage if damage else 'clean'}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dlrover_tpu.checkpoint.fsck",
        description=(
            "Verify a flash-checkpoint directory: shard CRCs, commit "
            "protocol, coverage.  Exits 1 when damage is found."
        ),
    )
    ap.add_argument("ckpt_dir", help="checkpoint directory to verify")
    ap.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    args = ap.parse_args(argv)
    storage = PosixDiskStorage()
    if not storage.exists(args.ckpt_dir):
        print(
            f"fsck: {args.ckpt_dir}: no such checkpoint directory",
            file=sys.stderr,
        )
        return 2
    report = fsck(args.ckpt_dir, storage)
    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        _print_human(report)
    return 1 if report.damaged else 0


if __name__ == "__main__":
    sys.exit(main())
