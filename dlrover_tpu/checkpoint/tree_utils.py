"""Pytree <-> flat shard-dict conversion for checkpointing.

The staging format is a flat ``{"<path>|<k>": np.ndarray}`` dict plus
per-tensor placement info (global shape + index slices), so that

- each *process* stores exactly its addressable shards (no gather),
- restore can re-assemble **any** target sharding from the pieces available
  (same-world: exact index match; changed-world: overlap copy — the
  resharding restore SURVEY.md §7 calls out as a hard part).

Restore is target-driven (orbax-style): the caller supplies a pytree of
jax.Arrays / ShapeDtypeStructs whose structure names the paths.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten


def _norm_index(index, shape) -> Tuple[Tuple[int, int], ...]:
    """Normalize a shard index (tuple of slices) to ((start, stop), ...)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _box_owners(leaf, gshape):
    """{normalized box: sorted process ids holding that box} from a leaf's
    GLOBAL device->index map — every process computes the same answer
    locally, which is what lets the sliced persist assign disjoint slices
    of replicated state without any cross-rank negotiation.  ``None``
    when the sharding cannot answer (callers then never slice the leaf).
    """
    try:
        sharding = leaf.sharding
        imap = sharding.devices_indices_map(gshape)
        out: Dict[Tuple[Tuple[int, int], ...], set] = {}
        for dev, idx in imap.items():
            out.setdefault(_norm_index(idx, gshape), set()).add(
                int(dev.process_index)
            )
        return {box: sorted(ranks) for box, ranks in out.items()}
    except Exception:  # noqa: BLE001 - unknown sharding kinds: unsliced
        return None


def flatten_to_shards(
    state: Any,
) -> Tuple[Dict[str, np.ndarray], Dict[str, dict]]:
    """Flatten a pytree of arrays into this process's shard dict.

    Returns (tensors, info): ``tensors["path|k"]`` is the k-th unique local
    shard of leaf ``path``; ``info["path|k"]`` records global_shape + index,
    plus the slicing inputs of ISSUE 7 — ``owners`` (every process id
    holding this same box, from the global indices map) for device arrays
    and ``host: True`` for host leaves (identical on every rank by the
    same assumption the restore path already makes).
    """
    leaves = tree_flatten_with_path(state)[0]
    tensors: Dict[str, np.ndarray] = {}
    info: Dict[str, dict] = {}
    for path, leaf in leaves:
        name = keystr(path)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
            gshape = tuple(leaf.shape)
            seen = {}
            for shard in leaf.addressable_shards:
                idx = _norm_index(shard.index, gshape)
                if idx in seen:
                    continue
                seen[idx] = np.asarray(shard.data)
            owners_by_box = _box_owners(leaf, gshape)
            for k, (idx, arr) in enumerate(sorted(seen.items())):
                key = f"{name}|{k}"
                tensors[key] = arr
                info[key] = {
                    "path": name,
                    "global_shape": list(gshape),
                    "index": [list(p) for p in idx],
                }
                if owners_by_box is not None:
                    info[key]["owners"] = owners_by_box.get(idx)
        else:
            arr = np.asarray(leaf)
            key = f"{name}|0"
            tensors[key] = arr
            info[key] = {
                "path": name,
                "global_shape": list(arr.shape),
                "index": [[0, d] for d in arr.shape],
                "host": True,
            }
    return tensors, info


class ShardSource:
    """All pieces known for the leaves of one checkpoint (possibly from
    several processes' shard files).

    Pieces may arrive *sliced* (ISSUE 7): a flat uint8 byte range of one
    box's C-order buffer, as the cross-replica sliced persist wrote them.
    Slices accumulate per (path, box) and materialize into a normal piece
    the moment they tile the full buffer; a box whose slices never
    complete simply contributes nothing (``assemble`` then reports the
    region uncovered and the restore ladder falls back)."""

    def __init__(self):
        # path -> list of (index, np.ndarray)
        self.pieces: Dict[str, List[Tuple[Tuple[Tuple[int, int], ...], np.ndarray]]] = {}
        # (path, index) -> {"full", "dtype", "shape", "parts": {(lo,hi): bytes}}
        self._partial: Dict[Tuple[str, tuple], dict] = {}

    def add(
        self,
        tensors: Dict[str, np.ndarray],
        info: Dict[str, dict],
        slices: Optional[Dict[str, dict]] = None,
    ) -> None:
        """``slices[key]``, when present, is the shard file's tensor meta
        for a sliced entry (``slice``/``full_nbytes``/``dtype``/``shape``)
        and ``tensors[key]`` is the flat uint8 slice payload."""
        for key, arr in tensors.items():
            meta = info.get(key)
            if meta is None:
                continue
            idx = tuple(tuple(p) for p in meta["index"])
            sl = (slices or {}).get(key)
            if sl is None:
                self.pieces.setdefault(meta["path"], []).append((idx, arr))
                continue
            lo, hi = (int(v) for v in sl["slice"])
            ent = self._partial.setdefault(
                (meta["path"], idx),
                {
                    "full": int(sl.get("full_nbytes", 0)),
                    "dtype": sl["dtype"],
                    "shape": tuple(int(d) for d in sl["shape"]),
                    "parts": {},
                },
            )
            ent["parts"][(lo, hi)] = np.asarray(arr, np.uint8).reshape(-1)
            self._materialize_if_complete(meta["path"], idx, ent)

    def _materialize_if_complete(self, path: str, idx, ent: dict) -> None:
        if ent.get("done"):
            return
        pos = 0
        parts = sorted(ent["parts"].items())
        for (lo, hi), _ in parts:
            if lo > pos:
                return  # gap: some rank's slice still missing
            pos = max(pos, hi)
        if pos < ent["full"]:
            return
        arr = np.empty(ent["shape"], dtype=np.dtype(ent["dtype"]))
        flat = arr.reshape(-1).view(np.uint8)
        if flat.size != ent["full"]:
            return  # meta lies about the buffer size: leave uncovered
        for (lo, hi), chunk in parts:
            flat[lo:hi] = chunk[: hi - lo]
        self.pieces.setdefault(path, []).append((idx, arr))
        ent["done"] = True

    def paths(self) -> List[str]:
        return list(self.pieces.keys())

    def assemble(
        self, path: str, index: Tuple[Tuple[int, int], ...], dtype=None
    ) -> Optional[np.ndarray]:
        """Build the sub-array of leaf ``path`` covering ``index`` from the
        available pieces.  Exact-match fast path; otherwise overlap-copy
        (resharding).  Returns None if any region is uncovered."""
        pieces = self.pieces.get(path)
        if not pieces:
            return None
        for idx, arr in pieces:
            if idx == index:
                return arr
        shape = tuple(e - s for s, e in index)
        out = np.empty(shape, dtype=dtype or pieces[0][1].dtype)
        covered = np.zeros(shape, dtype=bool) if out.size else None
        for idx, arr in pieces:
            # Overlap of [idx] and [index] in global coords.
            dst_sl, src_sl = [], []
            ok = True
            for (ps, pe), (rs, re) in zip(idx, index):
                lo, hi = max(ps, rs), min(pe, re)
                if lo >= hi:
                    ok = False
                    break
                dst_sl.append(slice(lo - rs, hi - rs))
                src_sl.append(slice(lo - ps, hi - ps))
            if not ok:
                continue
            out[tuple(dst_sl)] = arr[tuple(src_sl)]
            if covered is not None:
                covered[tuple(dst_sl)] = True
        if covered is not None and not covered.all():
            return None
        return out


def _owned(piece: np.ndarray) -> np.ndarray:
    """Ensure a restored piece owns its bytes.

    ``assemble()``'s exact-match fast path returns the source array
    itself, which on the zero-copy shm restore is a VIEW into the live
    arena — it must not reach the restored tree (directly, or via
    ``jax.device_put``, which on the CPU backend may alias an aligned
    numpy buffer instead of copying): the next ``save_to_memory`` would
    rewrite the bytes underfoot.  ``base is not None`` is exactly "this
    array borrows someone else's buffer"; storage-restored pieces
    (``unpack_shard`` copies) and overlap-assembled pieces (fresh
    ``np.empty``) pass through untouched."""
    piece = np.asarray(piece)
    return np.array(piece) if piece.base is not None else piece


def _leaf_placements(leaf):
    """For a sharding-bearing leaf (a live ``jax.Array`` OR a
    ``ShapeDtypeStruct`` carrying a sharding — the restore-to-any-mesh
    placeholder), return ``(sharding, gshape, [(device, index), ...])``
    for its addressable shards without materializing anything; ``None``
    for plain host leaves.  The indices map is the same source of truth
    the reshard planner's boxes are pinned against."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None or not hasattr(
        sharding, "addressable_devices_indices_map"
    ):
        return None
    gshape = tuple(leaf.shape)
    imap = sharding.addressable_devices_indices_map(gshape)
    return sharding, gshape, list(imap.items())


def restore_to_target(
    target: Any, source: ShardSource
) -> Any:
    """Fill ``target`` (pytree of jax.Array / ShapeDtypeStruct / np arrays)
    from ``source``.  Sharding-bearing targets (live arrays, or
    ShapeDtypeStructs with an explicit sharding — e.g. placeholders for a
    mesh the saving world never had) are rebuilt shard-by-shard on their
    devices; others become full np arrays."""
    flat, treedef = jax.tree_util.tree_flatten(target)
    paths_leaves = tree_flatten_with_path(target)[0]
    out_leaves = []
    for (path, leaf) in paths_leaves:
        name = keystr(path)
        placed = _leaf_placements(leaf)
        if placed is not None:
            sharding, gshape, placements = placed
            arrays = []
            for device, index in placements:
                idx = _norm_index(index, gshape)
                piece = source.assemble(name, idx, dtype=leaf.dtype)
                if piece is None:
                    raise KeyError(
                        f"checkpoint missing data for {name} index {idx}"
                    )
                arrays.append(jax.device_put(_owned(piece), device))
            restored = jax.make_array_from_single_device_arrays(
                gshape, sharding, arrays
            )
            out_leaves.append(restored)
        else:
            shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
            full_idx = tuple((0, d) for d in shape)
            piece = source.assemble(
                name, full_idx, dtype=getattr(leaf, "dtype", None)
            )
            if piece is None:
                raise KeyError(f"checkpoint missing data for {name}")
            out_leaves.append(_owned(piece))
    return tree_unflatten(treedef, out_leaves)
