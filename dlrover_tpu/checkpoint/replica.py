"""In-memory cross-node checkpoint replicas.

Parity with reference ``trainer/torch/flash_checkpoint/replica.py``
(``CkptReplicaManger :28``, ``ShardCkptReplicaManager :73``,
``FullCkptReplicaManager :247``): each node backs up its staged shm
checkpoint onto a peer so a *replaced* node can warm-restore without
touching (possibly slow/stale) persistent storage — the
emergency-checkpoint pattern over DCN (SURVEY.md §5 "Checkpoint/resume").

Topology: ring backup.  Node ``r`` pushes its processes' shards to node
``(r+1) % world`` over the control-plane RPC; a relaunched node ``r``
fetches them back from ``(r+1) % world``.  Peer addresses rendezvous
through the master KV store under ``replica/addr/{node_rank}``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from dlrover_tpu import chaos
from dlrover_tpu.agent.metrics import integrity_counters
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.rpc import RpcClient, RpcServer, local_ip
from dlrover_tpu.checkpoint import shard_file

_KV_PREFIX = "replica/addr/"


def _layout_mismatch(
    extra: dict, expect_process_id: int, expect_step: int
) -> Optional[str]:
    """Step/world-layout metadata check on a replica payload's ``extra``.
    Returns a rejection reason or ``None``."""
    if int(extra.get("step", -1)) != int(expect_step):
        return (
            f"step mismatch (payload says {extra.get('step')}, "
            f"envelope says {expect_step})"
        )
    pid = extra.get("process_id")
    if pid is not None and int(pid) != int(expect_process_id):
        return (
            f"process mismatch (payload proc {pid}, "
            f"envelope proc {expect_process_id})"
        )
    if not extra.get("tensors_info"):
        return "tensors_info missing (payload could never seed a restore)"
    if int(extra.get("num_processes", 0) or 0) <= 0:
        return "num_processes missing"
    return None


def check_replica_payload(
    payload: bytes, process_id: int, step: int
) -> Optional[str]:
    """CRC + layout verification of a replica payload (both directions of
    the ring exchange).  Returns a rejection reason or ``None``."""
    try:
        extra = shard_file.verify_shard(payload)
    except shard_file.ShardCorruptionError as e:
        return f"corrupt payload: {e}"
    return _layout_mismatch(extra, process_id, step)


def _chaos_torn_push(payload: bytes, step: int, process_id: int) -> bytes:
    """``replica.torn_push`` chaos site: only a prefix of the payload
    survives the transfer — the receiver's verification must reject it."""
    if chaos.inject(
        "replica.torn_push", step=step, rank=process_id
    ) is None:
        return payload
    return payload[: max(1, len(payload) // 2)]


class ReplicaStore:
    """Per-node replica holder: process_id -> (step, packed shard bytes)."""

    def __init__(self, max_bytes: int = 64 << 30):
        self._lock = threading.Lock()
        self._data: Dict[int, Tuple[int, bytes]] = {}
        self._max_bytes = max_bytes

    def put(self, process_id: int, step: int, payload: bytes) -> bool:
        with self._lock:
            cur = self._data.get(process_id)
            if cur is not None and cur[0] >= step:
                return False
            projected = sum(
                len(b) for pid, (_, b) in self._data.items()
                if pid != process_id
            ) + len(payload)
            if projected > self._max_bytes:
                logger.warning(
                    "replica store full (%d bytes); dropping step %d",
                    projected, step,
                )
                return False
            self._data[process_id] = (step, payload)
            return True

    def get(
        self, process_id: int, min_step: int = -1
    ) -> Optional[Tuple[int, bytes]]:
        with self._lock:
            cur = self._data.get(process_id)
            if cur is None or cur[0] < min_step:
                return None
            return cur

    def stats(self) -> dict:
        with self._lock:
            return {
                pid: {"step": s, "bytes": len(b)}
                for pid, (s, b) in self._data.items()
            }


class ReplicaServicer:
    """RPC handler hosted by the agent (push/fetch)."""

    def __init__(self, store: ReplicaStore):
        self._store = store

    def __call__(self, msg: m.Message) -> Optional[m.Message]:
        if isinstance(msg, m.ReplicaPush):
            # Verify before accepting: a torn push stored here would
            # poison a replaced node's warm restore later, when the
            # original copy is long gone.
            reason = check_replica_payload(
                msg.payload, msg.process_id, msg.step
            )
            if reason is not None:
                integrity_counters.inc("ckpt_replica_rejected")
                logger.warning(
                    "replica push (proc %d step %d) rejected: %s",
                    msg.process_id, msg.step, reason,
                )
                return m.BaseResponse(success=False, reason=reason)
            ok = self._store.put(msg.process_id, msg.step, msg.payload)
            return m.BaseResponse(success=ok)
        if isinstance(msg, m.ReplicaFetch):
            got = self._store.get(msg.process_id, msg.min_step)
            if got is None:
                return m.ReplicaData(found=False)
            return m.ReplicaData(found=True, step=got[0], payload=got[1])
        return m.BaseResponse(
            success=False, reason=f"unknown message {type(msg).__name__}"
        )


class CkptReplicaManager:
    """Agent-side manager: serve replicas, push own shards, seed restores.

    ``master_client`` provides the KV rendezvous; ``node_rank``/``world``
    come from the current rendezvous round (call :meth:`update_world` after
    each round — ring neighbours change when membership does).
    """

    def __init__(
        self,
        master_client,
        node_rank: Optional[int] = None,
        world_size: int = 1,
        push_interval_s: float = 30.0,
    ):
        self.client = master_client
        # Registration waits for a real rank: registering a default rank
        # here would clobber another node's address in the KV store until
        # the next update_world round.
        self.node_rank = -1 if node_rank is None else node_rank
        self.world_size = world_size
        self.push_interval = push_interval_s
        self._last_push: Dict[int, float] = {}
        self.store = ReplicaStore()
        self._server = RpcServer(0, ReplicaServicer(self.store))
        self._server.start()
        self.addr = f"{local_ip()}:{self._server.port}"
        self._peers: Dict[int, RpcClient] = {}
        if node_rank is not None:
            self._register()

    # -- membership --------------------------------------------------------
    def _register(self) -> None:
        if self.node_rank < 0:
            return
        try:
            self.client.kv_store_set(
                f"{_KV_PREFIX}{self.node_rank}", self.addr.encode()
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("replica addr registration failed: %s", e)

    def update_world(self, node_rank: int, world_size: int) -> None:
        self.node_rank = node_rank
        self.world_size = world_size
        self._register()

    def _peer(self, rank: int) -> Optional[RpcClient]:
        try:
            raw = self.client.kv_store_get(f"{_KV_PREFIX}{rank}")
        except Exception:  # noqa: BLE001
            return None
        if not raw:
            return None
        addr = raw.decode()
        cli = self._peers.get(rank)
        if cli is None or cli.addr != addr:
            cli = RpcClient(addr, timeout=30.0)
            self._peers[rank] = cli
        return cli

    @property
    def backup_rank(self) -> int:
        return (self.node_rank + 1) % self.world_size

    # -- push (after each staged save; reference backup :57) ---------------
    def backup_shard(
        self,
        process_id: int,
        step: int,
        tensors: Dict[str, np.ndarray],
        extra: dict,
        force: bool = False,
    ) -> bool:
        if self.world_size <= 1:
            return False
        now = time.monotonic()
        if not force and now - self._last_push.get(process_id, 0.0) < (
            self.push_interval
        ):
            return False
        peer = self._peer(self.backup_rank)
        if peer is None:
            return False
        payload = shard_file.pack_shard(tensors, extra)
        payload = _chaos_torn_push(payload, step, process_id)
        try:
            resp = peer.call(
                m.ReplicaPush(
                    owner_node=self.node_rank,
                    process_id=process_id,
                    step=step,
                    payload=payload,
                )
            )
            ok = bool(getattr(resp, "success", False))
        except Exception as e:  # noqa: BLE001
            logger.warning("replica push to rank %d failed: %s",
                           self.backup_rank, e)
            return False
        if not ok and getattr(resp, "reason", ""):
            logger.warning(
                "replica push (proc %d step %d) refused by node %d: %s",
                process_id, step, self.backup_rank, resp.reason,
            )
        if ok:
            self._last_push[process_id] = now
            logger.info(
                "replica: backed up proc %d step %d (%.1f MB) to node %d",
                process_id, step, len(payload) / (1 << 20), self.backup_rank,
            )
        return ok

    # -- restore seed (replaced node; reference gather on restart) ---------
    def fetch_replica(
        self, process_id: int, min_step: int = -1
    ) -> Optional[Tuple[int, Dict[str, np.ndarray], dict]]:
        if self.world_size <= 1:
            return None
        peer = self._peer(self.backup_rank)
        if peer is None:
            return None
        try:
            resp = peer.call(
                m.ReplicaFetch(process_id=process_id, min_step=min_step)
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("replica fetch failed: %s", e)
            return None
        if not isinstance(resp, m.ReplicaData) or not resp.found:
            return None
        # Verify on fetch too: the store's copy was verified on push, but
        # the fetch rides the same wire — a torn transfer here would seed
        # the local arena with garbage the warm restore then trusts.
        try:
            tensors, extra = shard_file.unpack_shard(resp.payload)
        except shard_file.ShardCorruptionError as e:
            integrity_counters.inc("ckpt_replica_rejected")
            logger.warning(
                "replica fetch for proc %d rejected (corrupt payload): %s",
                process_id, e,
            )
            return None
        reason = _layout_mismatch(extra, process_id, resp.step)
        if reason is not None:
            integrity_counters.inc("ckpt_replica_rejected")
            logger.warning(
                "replica fetch for proc %d rejected: %s", process_id, reason
            )
            return None
        logger.info(
            "replica: recovered proc %d step %d from node %d",
            process_id, resp.step, self.backup_rank,
        )
        return resp.step, tensors, extra

    def stop(self) -> None:
        self._server.stop()
        for cli in self._peers.values():
            cli.close()
