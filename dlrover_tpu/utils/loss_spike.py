"""Loss-spike detection and forensics.

Parity with reference ``atorch/atorch/utils/loss_spike_utils.py``
(``TokenLossSpike``: detect spikes against a sliding window, persist the
offending step/sample info for later replay).  JAX-friendly: feed it host
floats (``float(loss)``) — never trace it into a jitted function.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Deque, List, Optional

from dlrover_tpu.common.log import logger


class LossSpikeDetector:
    """Flags steps whose loss jumps above the recent trend.

    A spike is ``loss > mean + zscore_threshold * std`` AND
    ``loss > ratio_threshold * mean`` over the window (both conditions, so
    flat-but-noisy early training doesn't false-positive).  NaN/Inf always
    count as spikes."""

    def __init__(
        self,
        window: int = 100,
        zscore_threshold: float = 4.0,
        ratio_threshold: float = 1.5,
        min_samples: int = 20,
        spike_log_dir: str = "",
    ):
        self._window: Deque[float] = deque(maxlen=window)
        self._z = zscore_threshold
        self._ratio = ratio_threshold
        self._min = min_samples
        self._dir = spike_log_dir
        self.spikes: List[dict] = []

    def update(
        self,
        step: int,
        loss: float,
        sample_info: Optional[dict] = None,
    ) -> bool:
        """Record one step's loss; returns True if it is a spike."""
        is_bad = math.isnan(loss) or math.isinf(loss)
        is_spike = is_bad
        if not is_bad and len(self._window) >= self._min:
            n = len(self._window)
            mean = sum(self._window) / n
            var = sum((x - mean) ** 2 for x in self._window) / n
            std = math.sqrt(var)
            if (
                loss > mean + self._z * max(std, 1e-12)
                and loss > self._ratio * mean
            ):
                is_spike = True
        if is_spike:
            rec = {
                "step": step,
                "loss": loss,
                "time": time.time(),
                "sample_info": sample_info or {},
            }
            self.spikes.append(rec)
            logger.warning(
                "loss spike at step %d: loss=%s", step, loss
            )
            self._persist(rec)
        else:
            self._window.append(loss)
        return is_spike

    def _persist(self, rec: dict) -> None:
        if not self._dir:
            return
        try:
            os.makedirs(self._dir, exist_ok=True)
            path = os.path.join(self._dir, "loss_spikes.jsonl")
            with open(path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:  # pragma: no cover
            logger.exception("could not persist loss spike record")
