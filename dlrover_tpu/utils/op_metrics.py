"""Per-op runtime metrics feeding diagnosis: the xpu-timer analogue.

Parity target: the reference scrapes per-op Prometheus metrics from the
xpu-timer sidecar into its diagnosis chain
(``dlrover/python/diagnosis/datacollector/xpu_timer_metric_collector
.py:22`` — kernel-level hang/slow signals beyond heartbeats).  The
TPU-native shape: no CUDA hooks exist, so every ``capture_every`` steps
the collector wraps ONE training step in a ``jax.profiler`` capture,
parses the XLA trace with :mod:`dlrover_tpu.utils.trace_analysis`, and
classifies device time into collectives / matmuls / other.  The result
feeds three consumers:

- a :class:`~dlrover_tpu.agent.metrics.MetricsRegistry` (the agent's
  ``/metrics`` endpoint) — per-step p50/p90/p99 and per-class fractions,
- the worker's periodic diagnosis report (``diagnosis_data()`` JSON for
  ``MasterClient.report_diagnosis_data``) — the master's hang/straggler
  operators see WHERE time goes, not just that steps stopped,
- a metrics JSON file next to the logs (``metrics_path``) the agent's
  log collector can scrape without any RPC.

Collective share is the straggler tell: on a healthy step collectives
overlap compute; a sick peer shows up as this fraction exploding while
step wall time grows.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import tempfile
import time
from typing import Dict, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.utils.prof import StepProfiler

# XLA HLO name prefixes per class (TPU device tracks); the CPU test
# backend emits primitive names (dot_general, ...), covered too.
COLLECTIVE_PREFIXES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "send", "recv",
    "psum", "ppermute",
)
MATMUL_PREFIXES = ("dot", "dot_general", "convolution", "fusion.matmul")


def classify_op(name: str) -> str:
    n = name.lower()
    if n.startswith("end:"):
        n = n[4:].strip()
    for p in COLLECTIVE_PREFIXES:
        if n.startswith(p):
            return "collective"
    for p in MATMUL_PREFIXES:
        if n.startswith(p):
            return "matmul"
    return "other"


class OpMetricsCollector:
    """Rolling step stats + periodic per-op capture.

    Wrap the training loop::

        col = OpMetricsCollector(capture_every=200)
        for step in ...:
            col.step_begin(step)
            run_one_step()          # must block until the step finishes
            col.step_end(step)
        ... col.metrics() / col.diagnosis_data()
    """

    def __init__(
        self,
        *,
        capture_every: int = 0,  # 0 = step stats only, no traces
        registry=None,
        metrics_path: str = "",
        window: int = 200,
        top_k: int = 5,
        publish_every: int = 20,
    ):
        self.prof = StepProfiler(window)
        self.capture_every = int(capture_every)
        self.registry = registry
        self.metrics_path = metrics_path
        self.top_k = top_k
        self.publish_every = max(1, int(publish_every))
        self._trace_dir: Optional[str] = None
        self._capturing = False
        self._op_fracs: Dict[str, float] = {}
        self._top_ops: list = []
        self._last_capture_step = -1
        self._last_capture_ts = 0.0

    # -- loop hooks ---------------------------------------------------------
    def step_begin(self, step: int) -> None:
        if (
            self.capture_every > 0
            and step > 0  # step 0 is compile; its trace is misleading
            and step % self.capture_every == 0
            and not self._capturing
        ):
            import jax

            self._trace_dir = tempfile.mkdtemp(prefix="dlrtpu_optrace_")
            try:
                jax.profiler.start_trace(self._trace_dir)
                self._capturing = True
                self._last_capture_step = step
            except Exception as e:  # noqa: BLE001 - profiling is advisory
                logger.warning("op-metrics capture failed to start: %s", e)
                shutil.rmtree(self._trace_dir, ignore_errors=True)
                self._trace_dir = None

    def step_end(self, step: int) -> None:
        self.prof.step()
        captured = self._capturing
        if captured:
            self._finish_capture()
        # Publishing does registry sweeps + a file rename: cadence it
        # (consumers scrape every tens of steps anyway), plus right
        # after every capture so fresh op fractions land immediately.
        if captured or step % self.publish_every == 0:
            self._publish()

    def _finish_capture(self) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            logger.warning("op-metrics stop_trace failed: %s", e)
            self._capturing = False
            if self._trace_dir:  # don't leak the partial trace dir
                shutil.rmtree(self._trace_dir, ignore_errors=True)
                self._trace_dir = None
            return
        self._capturing = False
        try:
            files = glob.glob(
                os.path.join(self._trace_dir or "", "**",
                             "*.trace.json.gz"),
                recursive=True,
            )
            if files and self._analyze(files):
                self._last_capture_ts = time.time()
        except Exception as e:  # noqa: BLE001
            logger.warning("op-metrics trace analysis failed: %s", e)
        finally:
            if self._trace_dir:
                shutil.rmtree(self._trace_dir, ignore_errors=True)
                self._trace_dir = None

    def _analyze(self, paths) -> bool:
        """Aggregate op durations over ALL trace files of the capture —
        multi-device/multi-track captures emit one .trace.json.gz per
        track; analyzing only the first skews the fractions the
        straggler operator consumes.  Returns False (keeping the
        previously published fractions intact) when no file yielded any
        events, so an all-corrupt capture doesn't wipe good data."""
        from dlrover_tpu.utils.trace_analysis import TraceAnalysis

        if isinstance(paths, str):
            paths = [paths]
        by_class: Dict[str, float] = {}
        per_op: Dict[str, float] = {}
        for path in paths:
            try:
                ta = TraceAnalysis.from_file(path)
            except Exception as e:  # noqa: BLE001 - skip a bad track
                logger.warning("op-metrics: unreadable trace %s: %s",
                               path, e)
                continue
            for ev in ta.events:
                # Framework/bookkeeping events pollute fractions: keep
                # only op-shaped events (no '::' and not $-internal).
                if "::" in ev.name or ev.name.startswith("$"):
                    continue
                cls = classify_op(ev.name)
                by_class[cls] = by_class.get(cls, 0.0) + ev.dur_us
                key = ev.name.split(".")[0]
                per_op[key] = per_op.get(key, 0.0) + ev.dur_us
        total = sum(by_class.values())
        if total <= 0:
            return False
        self._op_fracs = {k: v / total for k, v in by_class.items()}
        self._top_ops = sorted(
            per_op.items(), key=lambda kv: -kv[1]
        )[: self.top_k]
        return True

    # -- outputs ------------------------------------------------------------
    def metrics(self) -> Dict[str, float]:
        out = {
            f"step_{k}": v for k, v in self.prof.summary().items()
        }
        for cls in ("collective", "matmul", "other"):
            out[f"optime_{cls}_frac"] = self._op_fracs.get(cls, 0.0)
        out["last_capture_step"] = float(self._last_capture_step)
        return out

    def diagnosis_data(self) -> str:
        """JSON blob for MasterClient.report_diagnosis_data("op_metrics",
        ...) — consumed by the master's hang/straggler operators."""
        return json.dumps(
            {
                "metrics": self.metrics(),
                "top_ops": [
                    {"name": n, "total_us": round(us, 1)}
                    for n, us in self._top_ops
                ],
                "ts": time.time(),
            }
        )

    def _publish(self) -> None:
        m = self.metrics()
        if self.registry is not None:
            for k, v in m.items():
                try:
                    self.registry.set(f"worker_{k}", float(v))
                # graftcheck: disable=CC104 -- metrics publish is
                # advisory; a registry closing mid-shutdown races this
                # publisher by design
                except Exception:  # noqa: BLE001
                    pass
        if self.metrics_path:
            tmp = f"{self.metrics_path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    f.write(self.diagnosis_data())
                os.replace(tmp, self.metrics_path)
            except OSError:
                pass


class OpMetricsCallback:
    """Trainer callback wiring an :class:`OpMetricsCollector` into the
    loop and the master's diagnosis chain.

    Because the Trainer's hook surface fires at step END, a capture is
    armed one step ahead: ``step_begin(step+1)`` from ``on_step_end`` —
    so the profiled window covers exactly one full subsequent step.
    Every ``report_every`` steps the collector's JSON lands on the
    master as ``DiagnosisDataType.OP_METRICS`` (feeding
    ``CheckStragglerOperator``)."""

    def __init__(
        self,
        *,
        capture_every: int = 0,
        report_every: int = 50,
        master_client=None,
        registry=None,
        metrics_path: str = "",
    ):
        self.collector = OpMetricsCollector(
            capture_every=capture_every,
            registry=registry,
            metrics_path=metrics_path,
        )
        self.report_every = int(report_every)
        self.client = master_client

    # TrainerCallback surface (duck-typed; see trainer.TrainerCallback).
    def on_train_begin(self, args, state, control) -> None: ...

    def on_step_end(self, args, state, control, metrics) -> None:
        self.collector.step_end(state.step)
        if (
            self.client is not None
            and self.report_every > 0
            and state.step % self.report_every == 0
        ):
            try:
                self.client.report_diagnosis_data(
                    "op_metrics", self.collector.diagnosis_data()
                )
            except Exception as e:  # noqa: BLE001 - advisory path
                logger.debug("op-metrics report failed: %s", e)
        self.collector.step_begin(state.step + 1)

    def on_log(self, args, state, control, logs) -> None: ...

    def on_evaluate(self, args, state, control, metrics) -> None: ...

    def on_save(self, args, state, control) -> None: ...

    def on_epoch_end(self, args, state, control) -> None: ...

    def on_train_end(self, args, state, control) -> None:
        if self.collector._capturing:  # close a dangling capture
            self.collector._finish_capture()
