"""Load-bearing utilities (reference ``atorch/atorch/utils/`` subset that
the TPU build keeps: profiler/tracer ``prof.py``/``tracer.py``, loss-spike
detector ``loss_spike_utils.py``, metrics endpoint — the IB-counter monitor
maps to host-interconnect stats surfaced via the same endpoint)."""

from dlrover_tpu.utils.prof import StepProfiler, Tracer, profile_trace
from dlrover_tpu.utils.loss_spike import LossSpikeDetector

__all__ = [
    "StepProfiler",
    "Tracer",
    "profile_trace",
    "LossSpikeDetector",
]
