"""Profiling: step timing, chrome-trace events, jax profiler capture.

Parity with reference ``atorch/atorch/utils/prof.py`` (step/op profiler),
``utils/tracer.py`` (event tracer) and the xpu-timer scrape path —
TPU-native on top of ``jax.profiler`` (XLA traces viewable in
Perfetto/TensorBoard) instead of CUDA kernel hooks.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional

from dlrover_tpu.common.log import logger


def percentile(sorted_xs, p: float) -> float:
    """Nearest-rank percentile over a pre-sorted sequence."""
    n = len(sorted_xs)
    return sorted_xs[min(n - 1, int(p * n))]


class StepProfiler:
    """Per-step wall-time stats with percentile summaries.

    Call :meth:`step` once per training step; the first call after
    construction (or after :meth:`reset`) is counted separately as warmup
    (XLA compile)."""

    def __init__(self, window: int = 200):
        self._times: Deque[float] = deque(maxlen=window)
        self._last: Optional[float] = None
        self.warmup_s: Optional[float] = None
        self._created = time.perf_counter()
        self.total_steps = 0

    def step(self) -> Optional[float]:
        now = time.perf_counter()
        dt: Optional[float] = None
        if self._last is None:
            self.warmup_s = now - self._created
        else:
            dt = now - self._last
            self._times.append(dt)
        self._last = now
        self.total_steps += 1
        return dt

    def reset(self) -> None:
        self._times.clear()
        self._last = None
        self._created = time.perf_counter()

    def summary(self) -> Dict[str, float]:
        if not self._times:
            return {"steps": float(self.total_steps)}
        xs = sorted(self._times)
        n = len(xs)

        def pct(p: float) -> float:
            return percentile(xs, p)

        return {
            "steps": float(self.total_steps),
            "mean_s": sum(xs) / n,
            "p50_s": pct(0.5),
            "p90_s": pct(0.9),
            "p99_s": pct(0.99),
            "max_s": xs[-1],
            "warmup_s": self.warmup_s or 0.0,
            "steps_per_s": n / sum(xs) if sum(xs) > 0 else 0.0,
        }


class Tracer:
    """Chrome-trace (catapult) event recorder (reference ``tracer.py`` /
    ``parse_trace_json.py`` counterpart).  Thread-safe; dump with
    :meth:`save` and load the file in Perfetto."""

    def __init__(self, max_events: int = 100000):
        self._events: Deque[dict] = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    def _us(self) -> int:
        return int((time.perf_counter() - self._t0) * 1e6)

    @contextlib.contextmanager
    def span(self, name: str, category: str = "train", **args):
        start = self._us()
        try:
            yield
        finally:
            end = self._us()
            with self._lock:
                self._events.append(
                    {
                        "name": name,
                        "cat": category,
                        "ph": "X",
                        "ts": start,
                        "dur": end - start,
                        "pid": os.getpid(),
                        "tid": threading.get_ident() % 1_000_000,
                        "args": args,
                    }
                )

    def instant(self, name: str, **args) -> None:
        with self._lock:
            self._events.append(
                {
                    "name": name,
                    "ph": "i",
                    "ts": self._us(),
                    "pid": os.getpid(),
                    "tid": threading.get_ident() % 1_000_000,
                    "s": "p",
                    "args": args,
                }
            )

    def save(self, path: str) -> None:
        with self._lock:
            events = list(self._events)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        logger.info("tracer: wrote %d events to %s", len(events), path)


@contextlib.contextmanager
def profile_trace(log_dir: str, host_tracer_level: int = 2):
    """Capture an XLA/JAX profiler trace around a code block
    (view in TensorBoard / xprof; replaces xpu-timer kernel traces)."""
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("jax profiler trace written to %s", log_dir)
