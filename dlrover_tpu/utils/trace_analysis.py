"""Chrome-trace analysis: turn a trace into an actionable breakdown.

Parity with the reference's trace tooling
(``atorch/utils/trace/`` timeline parsing, the xpu-timer's per-kernel
aggregation, and ``analyse``-stage reporting): given a chrome-trace JSON
— from :class:`~dlrover_tpu.utils.prof.Tracer`, ``jax.profiler``'s
trace-viewer export, or any Perfetto-compatible producer — compute
per-op/per-category time rollups, top-k hotspots, concurrency-corrected
busy time, and step statistics, and render a text report.  Pure host
code: no jax import, usable offline on collected traces.
"""

from __future__ import annotations

import dataclasses
import gzip
import json
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class TraceEvent:
    name: str
    category: str
    start_us: float
    dur_us: float
    tid: int = 0
    pid: int = 0
    args: dict = dataclasses.field(default_factory=dict)

    @property
    def end_us(self) -> float:
        return self.start_us + self.dur_us


def load_trace(path: str) -> List[TraceEvent]:
    """Read a chrome trace (.json or .json.gz; bare list or
    {"traceEvents": [...]}), keeping complete ('X') duration events."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    raw = data["traceEvents"] if isinstance(data, dict) else data
    def as_int(v) -> int:
        """Some producers (viztracer, py-spy) emit string tids like
        "MainThread"; hash those instead of failing the whole load."""
        try:
            return int(v or 0)
        except (TypeError, ValueError):
            return hash(str(v)) & 0x7FFFFFFF

    out = []
    for ev in raw:
        if ev.get("ph") != "X":
            continue
        out.append(
            TraceEvent(
                name=str(ev.get("name", "")),
                category=str(ev.get("cat", "")),
                start_us=float(ev.get("ts", 0.0)),
                dur_us=float(ev.get("dur", 0.0)),
                tid=as_int(ev.get("tid")),
                pid=as_int(ev.get("pid")),
                args=ev.get("args", {}) or {},
            )
        )
    out.sort(key=lambda e: e.start_us)
    return out


@dataclasses.dataclass
class OpStat:
    name: str
    count: int
    total_us: float
    mean_us: float
    max_us: float
    pct_of_busy: float


def _merge_busy(intervals: List[Tuple[float, float]]) -> float:
    """Union length of [start, end) intervals — wall-clock busy time
    with overlapping (concurrent) events counted once."""
    if not intervals:
        return 0.0
    intervals.sort()
    busy = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            busy += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return busy + (cur_e - cur_s)


class TraceAnalysis:
    """Aggregations over one loaded trace."""

    def __init__(self, events: Sequence[TraceEvent]):
        self.events = list(events)

    @classmethod
    def from_file(cls, path: str) -> "TraceAnalysis":
        return cls(load_trace(path))

    # -- rollups -------------------------------------------------------------
    def span_us(self) -> float:
        if not self.events:
            return 0.0
        return max(e.end_us for e in self.events) - min(
            e.start_us for e in self.events
        )

    def busy_us(self) -> float:
        return _merge_busy([(e.start_us, e.end_us) for e in self.events])

    def by_category(self) -> Dict[str, float]:
        """category -> summed duration (overlap NOT deduplicated: this is
        'work attributed', matching per-op rollups)."""
        out: Dict[str, float] = defaultdict(float)
        for e in self.events:
            out[e.category or "(none)"] += e.dur_us
        return dict(out)

    def top_ops(self, k: int = 20) -> List[OpStat]:
        total: Dict[str, List[float]] = defaultdict(list)
        for e in self.events:
            total[e.name].append(e.dur_us)
        busy = self.busy_us() or 1.0
        stats = [
            OpStat(
                name=name,
                count=len(durs),
                total_us=sum(durs),
                mean_us=sum(durs) / len(durs),
                max_us=max(durs),
                pct_of_busy=100.0 * sum(durs) / busy,
            )
            for name, durs in total.items()
        ]
        stats.sort(key=lambda s: -s.total_us)
        return stats[:k]

    def steps(
        self, step_event: str = "train_step"
    ) -> List[Tuple[float, float]]:
        """(start, dur) of every event named ``step_event`` — the step
        markers the Tracer/trainer emit."""
        return [
            (e.start_us, e.dur_us)
            for e in self.events
            if e.name == step_event
        ]

    def step_stats(
        self, step_event: str = "train_step"
    ) -> Optional[Dict[str, float]]:
        durs = sorted(d for _, d in self.steps(step_event))
        if not durs:
            return None
        from dlrover_tpu.utils.prof import percentile

        def pct(p: float) -> float:
            return percentile(durs, p)

        return {
            "count": float(len(durs)),
            "mean_us": sum(durs) / len(durs),
            "p50_us": pct(0.50),
            "p90_us": pct(0.90),
            "p99_us": pct(0.99),
            "max_us": durs[-1],
        }

    def gaps(
        self, threshold_us: float = 1000.0
    ) -> List[Tuple[float, float]]:
        """Idle windows longer than ``threshold_us`` between busy spans —
        the input-pipeline/host-stall hunting ground."""
        iv = sorted((e.start_us, e.end_us) for e in self.events)
        out = []
        if not iv:
            return out
        cur_end = iv[0][1]
        for s, e in iv[1:]:
            if s - cur_end > threshold_us:
                out.append((cur_end, s - cur_end))
            cur_end = max(cur_end, e)
        return out

    # -- report --------------------------------------------------------------
    def report(self, k: int = 12, step_event: str = "train_step") -> str:
        lines = []
        span = self.span_us()
        busy = self.busy_us()
        lines.append(
            f"trace: {len(self.events)} events, span {span/1e3:.2f} ms, "
            f"busy {busy/1e3:.2f} ms "
            f"({100.0 * busy / span if span else 0.0:.1f}%)"
        )
        ss = self.step_stats(step_event)
        if ss:
            lines.append(
                f"steps ({step_event}): n={int(ss['count'])} "
                f"mean={ss['mean_us']/1e3:.2f}ms "
                f"p50={ss['p50_us']/1e3:.2f}ms "
                f"p90={ss['p90_us']/1e3:.2f}ms "
                f"p99={ss['p99_us']/1e3:.2f}ms"
            )
        cats = sorted(self.by_category().items(), key=lambda kv: -kv[1])
        lines.append("by category:")
        for cat, us in cats[:8]:
            lines.append(f"  {cat:<24} {us/1e3:10.2f} ms")
        lines.append(f"top {k} ops by total time:")
        for s in self.top_ops(k):
            lines.append(
                f"  {s.name[:48]:<48} n={s.count:<6} "
                f"total={s.total_us/1e3:9.2f}ms "
                f"mean={s.mean_us:8.1f}us  {s.pct_of_busy:5.1f}%"
            )
        gaps = self.gaps()
        if gaps:
            worst = max(gaps, key=lambda g: g[1])
            lines.append(
                f"idle gaps >1ms: {len(gaps)} "
                f"(worst {worst[1]/1e3:.2f} ms at t={worst[0]/1e3:.2f} ms)"
            )
        return "\n".join(lines)


def main(argv=None) -> int:  # pragma: no cover - thin CLI shell
    import argparse

    p = argparse.ArgumentParser("dlrover-tpu-trace")
    p.add_argument("trace", help="chrome trace .json/.json.gz")
    p.add_argument("--top", type=int, default=12)
    p.add_argument("--step_event", default="train_step")
    args = p.parse_args(argv)
    print(
        TraceAnalysis.from_file(args.trace).report(
            args.top, args.step_event
        )
    )
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
