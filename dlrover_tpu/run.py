"""``tpurun`` — the job launcher CLI (``python -m dlrover_tpu.run``).

Parity with reference ``dlrover-run`` (``trainer/torch/elastic_run.py``:
``parse_args :125``, ``_launch_dlrover_local_master :245``,
``_check_dlrover_master_available :277``, ``run :413``): a torchrun-style
front-end that (on node 0 of standalone jobs) spawns a local master
subprocess, waits for it, merges master-pushed run config, then hands off to
the elastic agent.

Examples::

    # single host, 2 worker processes, local master auto-spawned
    tpurun --standalone --nproc_per_node=2 train.py --lr 3e-4

    # multi-host: every host points at the job master
    tpurun --master_addr=10.0.0.2:5001 --nnodes=2:4 --node_rank=$RANK train.py
"""

from __future__ import annotations

import argparse
import atexit
import os
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import List, Optional, Tuple

from dlrover_tpu import chaos
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.training import ElasticLaunchConfig, launch_agent
from dlrover_tpu.common.log import logger, set_role
from dlrover_tpu.common.rpc import addr_connectable


def parse_nnodes(spec: str) -> Tuple[int, int]:
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return int(lo), int(hi)
    n = int(spec)
    return n, n


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        "tpurun", description="elastic TPU training launcher"
    )
    p.add_argument("--standalone", action="store_true",
                   help="single-host mode: auto-spawn a local master")
    p.add_argument("--standby", action="store_true",
                   help="master HA (ISSUE 13): give the standalone local "
                        "master a durable state journal plus a WARM "
                        "STANDBY that adopts the state on a crash "
                        "(instead of the cold blank-state relaunch)")
    p.add_argument("--master_state_dir", default="",
                   help="control-plane journal dir for --standby "
                        "(default: a run-scoped dir under the system "
                        "temp dir)")
    p.add_argument("--cell", type=int, default=0,
                   help="multi-cell control plane (ISSUE 15): spawn a "
                        "shared cell registry plus N cell masters "
                        "(consistent-hash node ownership); this node "
                        "talks to its node id's OWNING cell.  Composes "
                        "with --standby: every cell master then gets "
                        "its own journal + warm standby")
    p.add_argument("--nnodes", default="1",
                   help="'N' or 'MIN:MAX' elastic node range")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("DLROVER_TPU_NODE_RANK", 0)))
    p.add_argument("--node_id", type=int, default=-1,
                   help="stable node id (defaults to node_rank)")
    p.add_argument("--master_addr", default=os.environ.get(
        "DLROVER_TPU_MASTER_ADDR", ""))
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--monitor_interval", type=float, default=2.0)
    p.add_argument("--rdzv_timeout", type=float, default=600.0)
    p.add_argument("--network_check", action="store_true",
                   help="run the pre-flight matmul+psum node check")
    p.add_argument("--comm_perf_test", action="store_true")
    p.add_argument("--node_unit", type=int, default=1)
    p.add_argument("--log_dir", default="")
    p.add_argument("--job_name", default=os.environ.get(
        "DLROVER_TPU_JOB_NAME", "local-job"))
    p.add_argument("--node_role", default=os.environ.get(
        "DLROVER_TPU_NODE_ROLE", "worker"),
        help="fleet role of this node (ISSUE 10): 'worker' joins the "
             "training rendezvous; service roles ('gateway', "
             "'embedding') register for supervision only and run "
             "their entrypoint outside the XLA mesh")
    p.add_argument("--no_python", action="store_true",
                   help="entrypoint is a program, not a python script")
    p.add_argument("--job_file", default="",
                   help="declarative ElasticJob YAML (script, args, "
                        "replicas, ckpt config); explicit CLI flags win")
    p.add_argument("entrypoint", nargs="?", default="",
                   help="training script (optional with --job_file)")
    p.add_argument("args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if args.job_file:
        _apply_job_file(p, args)
    elif not args.entrypoint:
        p.error("entrypoint is required (or pass --job_file)")
    return args


def _apply_job_file(parser: argparse.ArgumentParser,
                    args: argparse.Namespace) -> None:
    """Fill launcher settings from an ElasticJob YAML (reference
    ``elastic_job.yaml`` consumed by the operator; here the launcher
    reads it directly).  A flag the user set explicitly (i.e. differs
    from the parser default) is never overridden."""
    from dlrover_tpu.scheduler.jobfile import load_elastic_job, nnodes_arg

    jf = load_elastic_job(args.job_file)

    def default_only(name: str, value) -> None:
        if getattr(args, name) == parser.get_default(name):
            setattr(args, name, value)

    if not args.entrypoint and jf.script:
        args.entrypoint = jf.script
    if not args.entrypoint:
        parser.error(
            f"--job_file {args.job_file}: no spec.template.script and no "
            "entrypoint argument"
        )
    default_only("job_name", jf.name)
    default_only("nnodes", nnodes_arg(jf))
    default_only("nproc_per_node", jf.nproc_per_node)
    default_only("node_unit", jf.node_unit)
    default_only("max_restarts", jf.max_restarts)
    if jf.network_check:
        args.network_check = True
    ckpt_extra = []
    if jf.ckpt_dir:
        ckpt_extra.append(f"--ckpt_dir={jf.ckpt_dir}")
    if jf.ckpt_interval:
        ckpt_extra.append(f"--ckpt_interval={jf.ckpt_interval}")
    if not args.args:
        extra = list(jf.script_args) + ckpt_extra
        args.args = ["--", *extra] if extra else []
    else:
        # User-provided script args replace the YAML's, but the
        # checkpoint config is durability state, not a script arg —
        # keep it unless the user explicitly overrides the same flag
        # (exact flag-name match; a substring test would false-positive
        # on e.g. --ckpt_dirs).
        user_flags = {
            a.split("=", 1)[0] for a in args.args if a.startswith("--")
        }
        args.args = list(args.args) + [
            e for e in ckpt_extra
            if e.split("=", 1)[0] not in user_flags
        ]


def _master_cmd(args, port: int, port_file: str = "",
                state_dir: str = "") -> List[str]:
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    cmd = [
        sys.executable, "-m", "dlrover_tpu.master.main",
        "--port", str(port),
        "--job_name", args.job_name,
        "--platform", "local",
        "--min_nodes", str(min_nodes),
        "--max_nodes", str(max_nodes),
        "--node_unit", str(args.node_unit),
    ]
    if port_file:
        cmd += ["--port_file", port_file]
    if state_dir:
        cmd += ["--state_dir", state_dir]
    # Multi-cell launches stash the per-cell identity on a COPY of the
    # args namespace (the count flag itself is ``--cell``), so every
    # relaunch path — cold supervisor, HA promote — reproduces it.
    if getattr(args, "cell_id", ""):
        cmd += ["--cell_id", args.cell_id,
                "--cell_registry", getattr(args, "cell_registry", "")]
    return cmd


def _launch_local_master(args, state_dir: str = "") \
        -> Tuple[subprocess.Popen, str, int]:
    """Spawn ``python -m dlrover_tpu.master.main`` and wait for its port
    (reference ``_launch_dlrover_local_master :245``)."""
    port_file = tempfile.mktemp(prefix="dlrtpu_master_port_")
    proc = subprocess.Popen(_master_cmd(args, 0, port_file, state_dir))
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.exists(port_file):
            with open(port_file) as f:
                content = f.read().strip()
            if content:
                os.unlink(port_file)
                return proc, f"127.0.0.1:{content}", int(content)
        if proc.poll() is not None:
            raise RuntimeError(
                f"local master exited early with code {proc.returncode}"
            )
        time.sleep(0.2)
    raise TimeoutError("local master did not report its port in 60s")


#: Chaos crash sites aimed at the PRIMARY master; a standby inheriting
#: the env verbatim would arm them too and die alongside it.
_MASTER_CRASH_SITES = ("master.kill", "master.restart",
                       "master.journal_torn")


def _launch_standby_master(args, state_dir: str, primary_addr: str) \
        -> Tuple[subprocess.Popen, str]:
    """Spawn a warm standby (``master.main --standby``) and wait for the
    port it BOUND (it serves only after takeover)."""
    port_file = tempfile.mktemp(prefix="dlrtpu_standby_port_")
    cmd = [
        sys.executable, "-m", "dlrover_tpu.master.main",
        "--standby", "--state_dir", state_dir,
        "--primary_addr", primary_addr,
        "--port", "0", "--port_file", port_file,
        "--job_name", args.job_name,
    ]
    if getattr(args, "cell_id", ""):
        cmd += ["--cell_id", args.cell_id,
                "--cell_registry", getattr(args, "cell_registry", "")]
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    cmd += ["--min_nodes", str(min_nodes), "--max_nodes", str(max_nodes),
            "--node_unit", str(args.node_unit)]
    env = chaos.scrub_env(dict(os.environ), _MASTER_CRASH_SITES)
    proc = subprocess.Popen(cmd, env=env)
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.exists(port_file):
            with open(port_file) as f:
                content = f.read().strip()
            if content:
                os.unlink(port_file)
                return proc, f"127.0.0.1:{content}"
        if proc.poll() is not None:
            raise RuntimeError(
                f"standby master exited early with code {proc.returncode}"
            )
        time.sleep(0.2)
    raise TimeoutError("standby master did not report its port in 60s")


def _supervise_local_master(
    args,
    holder: List[subprocess.Popen],
    port: int,
    stop_evt: threading.Event,
    max_restarts: int = 3,
) -> threading.Thread:
    """Keep the standalone job's local master alive: if it exits nonzero
    while the job is still running, relaunch it on the SAME port (agents
    ride the gap via RPC retry + rendezvous re-join).  A clean exit (rc=0,
    job finished) ends supervision.  This is what turns a chaos
    ``master.restart`` — or a real master crash — into a blip instead of
    a dead job."""

    def loop() -> None:
        restarts = 0
        while not stop_evt.wait(1.0):
            proc = holder[0]
            rc = proc.poll()
            if rc is None:
                continue
            if rc == 0 or rc < 0:
                # rc 0: job finished.  rc < 0: killed by a signal — the
                # master never signals itself, so this is the launcher's
                # own teardown (atexit terminate on an abnormal exit
                # path); respawning would orphan a master on the port.
                return
            if restarts >= max_restarts:
                logger.error(
                    "local master exited rc=%d and restart budget (%d) is "
                    "spent; agents will time out", rc, max_restarts,
                )
                return
            restarts += 1
            logger.warning(
                "local master exited rc=%d; relaunching on port %d "
                "(restart %d/%d)", rc, port, restarts, max_restarts,
            )
            env = dict(os.environ)
            plan = chaos.active_plan()
            restart_codes = {
                s.exit_code for s in plan.specs
                if s.site == "master.restart"
            } if plan is not None else set()
            if rc in restart_codes:
                # The one-shot crash fault fired (matched by the plan's
                # own exit code, so exit= overrides are recognized); a
                # replacement inheriting the plan verbatim would re-arm
                # it and die identically.
                chaos.scrub_env(env, ("master.restart",))
            holder[0] = subprocess.Popen(_master_cmd(args, port), env=env)

    thread = threading.Thread(
        target=loop, name="master-supervisor", daemon=True
    )
    thread.start()
    return thread


def _supervise_ha_masters(
    args,
    state_dir: str,
    primary_holder: List[subprocess.Popen],
    standby_holder: List[subprocess.Popen],
    stop_evt: threading.Event,
    max_restarts: int = 3,
) -> threading.Thread:
    """The --standby supervision mode (ISSUE 13), next to the cold
    ``_supervise_local_master`` path: on a primary crash the standby
    ADOPTS the journaled state (hot), so the supervisor's job is not to
    relaunch the dead primary but to (a) wait for the takeover, (b)
    promote the standby process into the primary slot, and (c) spawn a
    FRESH standby behind the new leader so the next crash is also hot.
    Agents follow the leader via the state-dir ``addr`` file chain, so
    repeated failovers need no env changes.  A standby that dies while
    the primary is healthy is simply respawned."""
    from dlrover_tpu.master.state import read_addr

    def loop() -> None:
        restarts = 0
        while not stop_evt.wait(1.0):
            primary, standby = primary_holder[0], standby_holder[0]
            prc = primary.poll()
            if prc is None:
                src = standby.poll()
                if src is not None and src != 0 and not stop_evt.is_set():
                    if restarts >= max_restarts:
                        logger.error(
                            "standby exited rc=%d and restart budget (%d) "
                            "is spent; next master crash will be cold",
                            src, max_restarts,
                        )
                        return
                    restarts += 1
                    logger.warning(
                        "standby exited rc=%d; respawning (restart %d/%d)",
                        src, restarts, max_restarts,
                    )
                    try:
                        standby_holder[0], _ = _launch_standby_master(
                            args, state_dir, read_addr(state_dir)
                        )
                    except (RuntimeError, TimeoutError) as e:
                        logger.error(
                            "could not respawn a standby: %s; next "
                            "master crash will be cold", e,
                        )
                        return
                continue
            if prc == 0 or (prc < 0 and stop_evt.is_set()):
                # Job finished, or launcher teardown signalled the
                # master.  Unlike the cold supervisor, a signal death
                # alone is NOT teardown here: an external SIGKILL/OOM
                # kill of the primary is exactly the failure HA covers,
                # so only rc<0 paired with our own stop event returns.
                return
            # Primary crashed: the standby should take over.  Wait for
            # the new leader to publish its address (bounded).
            old_addr = read_addr(state_dir)
            deadline = time.time() + 60
            new_addr = ""
            while time.time() < deadline and not stop_evt.is_set():
                cur = read_addr(state_dir)
                if cur and cur != old_addr:
                    new_addr = cur
                    break
                if standby_holder[0].poll() is not None:
                    break  # standby died too — cold path below
                time.sleep(0.2)
            if not new_addr:
                logger.error(
                    "primary exited rc=%d and no takeover observed; "
                    "agents will time out", prc,
                )
                return
            logger.warning(
                "primary exited rc=%d; standby took over at %s",
                prc, new_addr,
            )
            # Promote, then back the new leader with a fresh standby.
            primary_holder[0] = standby_holder[0]
            if restarts >= max_restarts:
                logger.error(
                    "standby restart budget (%d) spent; the next master "
                    "crash will be cold", max_restarts,
                )
                return
            restarts += 1
            try:
                standby_holder[0], _ = _launch_standby_master(
                    args, state_dir, new_addr
                )
            except (RuntimeError, TimeoutError) as e:
                logger.error("could not respawn a standby: %s", e)
                return

    thread = threading.Thread(
        target=loop, name="master-ha-supervisor", daemon=True
    )
    thread.start()
    return thread


def _launch_cell_registry(args) -> Tuple[subprocess.Popen, str]:
    """Spawn the shared cell-registry KV and wait for its port."""
    port_file = tempfile.mktemp(prefix="dlrtpu_cellreg_port_")
    proc = subprocess.Popen([
        sys.executable, "-m", "dlrover_tpu.cells.main",
        "--registry", "--port", "0", "--port_file", port_file,
    ])
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.exists(port_file):
            with open(port_file) as f:
                content = f.read().strip()
            if content:
                os.unlink(port_file)
                return proc, f"127.0.0.1:{content}"
        if proc.poll() is not None:
            raise RuntimeError(
                f"cell registry exited early rc={proc.returncode}"
            )
        time.sleep(0.2)
    raise TimeoutError("cell registry did not report its port in 60s")


def _launch_cells(args, master_stop: threading.Event) -> str:
    """``--cell N`` (ISSUE 15): registry + N cell masters, each under
    the SAME supervision ladder a single master gets (cold relaunch,
    or journal + warm standby with ``--standby``).  Returns the addr of
    THIS node's owning cell master."""
    import argparse as _argparse

    from dlrover_tpu.cells.cell import cell_for_node

    reg_proc, reg_addr = _launch_cell_registry(args)
    atexit.register(
        lambda: reg_proc.poll() is None and reg_proc.terminate()
    )
    # Exported for sidecar tooling: `python -m dlrover_tpu.cells.main
    # --federation` (and operator debugging) defaults its registry
    # address from this.
    os.environ["DLROVER_TPU_CELL_REGISTRY"] = reg_addr
    cell_ids = [f"cell{i}" for i in range(args.cell)]
    base_state = args.master_state_dir or os.path.join(
        tempfile.gettempdir(),
        f"dlrtpu_cells_{args.job_name}_"
        f"{os.environ['DLROVER_TPU_RUN_ID']}",
    )
    addrs: dict = {}
    for cid in cell_ids:
        cell_args = _argparse.Namespace(**vars(args))
        cell_args.cell_id = cid
        cell_args.cell_registry = reg_addr
        state_dir = ""
        if args.standby:
            state_dir = os.path.join(base_state, cid)
            os.makedirs(state_dir, exist_ok=True)
        holder: List[subprocess.Popen] = []
        proc, addr, port = _launch_local_master(cell_args, state_dir)
        holder.append(proc)
        addrs[cid] = (addr, state_dir)
        atexit.register(
            lambda h=holder: h[0].poll() is None and h[0].terminate()
        )
        if args.standby:
            sb_holder: List[subprocess.Popen] = []
            sb_proc, _sb_addr = _launch_standby_master(
                cell_args, state_dir, addr
            )
            sb_holder.append(sb_proc)
            atexit.register(
                lambda h=sb_holder: h[0].poll() is None
                and h[0].terminate()
            )
            _supervise_ha_masters(
                cell_args, state_dir, holder, sb_holder, master_stop,
                args.max_restarts,
            )
        else:
            _supervise_local_master(
                cell_args, holder, port, master_stop, args.max_restarts
            )
    node_id = args.node_id if args.node_id >= 0 else args.node_rank
    own = cell_for_node(node_id, cell_ids)
    own_addr, own_state = addrs[own]
    if own_state:
        # The agent's failover chain follows the OWNING cell's journal.
        os.environ["DLROVER_TPU_MASTER_STATE_DIR"] = own_state
    logger.info(
        "multi-cell control plane up: registry %s, cells %s; node %d "
        "-> %s at %s", reg_addr,
        {c: a for c, (a, _s) in addrs.items()}, node_id, own, own_addr,
    )
    return own_addr


def _gc_shm_arenas(
    job_name: str, run_id: str = "", min_age_s: float = 3600.0
) -> None:
    """Unlink /dev/shm arenas of ``job_name``: one run id exactly (exit
    cleanup), or — with no run id — only arenas idle for ``min_age_s``
    (startup GC).  The age guard matters: several nodes of one job can
    share a host, and a relaunching node must never wipe a live sibling's
    staged checkpoint (live arenas are rewritten every few steps, so their
    mtime is always fresh)."""
    import glob
    import time as _time

    safe = job_name.replace("/", "_")
    scope = f"{safe}-{run_id}" if run_id else f"{safe}-*"
    now = _time.time()
    for path in glob.glob(f"/dev/shm/dlrtpu_{scope}_*"):
        try:
            # graftcheck: disable=OB301 -- compared against the file's
            # wall-clock mtime; wall time is the point here
            if not run_id and now - os.stat(path).st_mtime < min_age_s:
                continue
            os.unlink(path)
        except OSError:
            pass


def run(args: argparse.Namespace) -> int:
    set_role(f"agent-{args.node_rank}")
    os.environ["DLROVER_TPU_NODE_ROLE"] = args.node_role
    # One id per launcher invocation: namespaces host-local IPC (shm
    # arenas/queues/locks) so stale state from a previous launch of the
    # same job name can't leak into this one.
    os.environ.setdefault("DLROVER_TPU_RUN_ID", uuid.uuid4().hex[:8])
    # Run-scoped arenas would otherwise accumulate in RAM-backed /dev/shm,
    # one multi-GB set per launch: GC leftovers of earlier launches of this
    # job now, and unlink our own at exit.  Durable state lives in storage
    # (breakpoint saves persist before workers are torn down).
    _gc_shm_arenas(args.job_name)
    atexit.register(_gc_shm_arenas, args.job_name,
                    os.environ["DLROVER_TPU_RUN_ID"])
    if chaos.active_plan() is not None:
        logger.warning(
            "launcher: chaos fault plan is ACTIVE: %s",
            chaos.active_plan().describe(),
        )
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    master_holder: List[subprocess.Popen] = []
    standby_holder: List[subprocess.Popen] = []
    master_stop = threading.Event()
    master_addr = args.master_addr
    ha_state_dir = ""
    if args.standalone and not master_addr and args.cell > 0:
        master_addr = _launch_cells(args, master_stop)
        ha_state_dir = os.environ.get("DLROVER_TPU_MASTER_STATE_DIR", "")
    elif args.standalone and not master_addr:
        if args.standby:
            ha_state_dir = args.master_state_dir or os.path.join(
                tempfile.gettempdir(),
                f"dlrtpu_ha_{args.job_name}_"
                f"{os.environ['DLROVER_TPU_RUN_ID']}",
            )
            os.makedirs(ha_state_dir, exist_ok=True)
        proc, master_addr, master_port = _launch_local_master(
            args, ha_state_dir
        )
        master_holder.append(proc)
        if args.standby:
            sb_proc, standby_addr = _launch_standby_master(
                args, ha_state_dir, master_addr
            )
            standby_holder.append(sb_proc)
            # Agents (and their workers, which inherit the env) learn
            # both the failover chain (state-dir addr file) and the
            # static standby address.
            os.environ["DLROVER_TPU_MASTER_STATE_DIR"] = ha_state_dir
            os.environ["DLROVER_TPU_MASTER_STANDBY_ADDR"] = standby_addr
            _supervise_ha_masters(
                args, ha_state_dir, master_holder, standby_holder,
                master_stop, args.max_restarts,
            )
            atexit.register(
                lambda: standby_holder[0].poll() is None
                and standby_holder[0].terminate()
            )
        else:
            _supervise_local_master(
                args, master_holder, master_port, master_stop
            )
        atexit.register(
            lambda: master_holder[0].poll() is None
            and master_holder[0].terminate()
        )
    if not master_addr:
        raise SystemExit(
            "either --standalone or --master_addr is required"
        )
    if not addr_connectable(master_addr, timeout=30):
        raise SystemExit(f"master at {master_addr} is not reachable")

    node_id = args.node_id if args.node_id >= 0 else args.node_rank
    config = ElasticLaunchConfig(
        min_nodes=min_nodes,
        max_nodes=max_nodes,
        nproc_per_node=args.nproc_per_node,
        node_id=node_id,
        node_rank=args.node_rank,
        max_restarts=args.max_restarts,
        monitor_interval=args.monitor_interval,
        rdzv_timeout=args.rdzv_timeout,
        network_check=args.network_check,
        comm_perf_test=args.comm_perf_test,
        log_dir=args.log_dir,
        job_name=args.job_name,
        node_role=args.node_role,
    )
    config.auto_configure()

    # Merge master-pushed run config (reference _elastic_config_from_master).
    # The state-dir hook makes the launcher's own client follow a
    # failover (the final job-exit report must reach the NEW leader).
    client = MasterClient(master_addr, node_id, state_dir=ha_state_dir)
    def _coerce(cur, val):
        # bool("false") is True: string-valued run configs (the usual
        # wire form) need explicit truthiness parsing for bool fields.
        if isinstance(cur, bool) and isinstance(val, str):
            return val.strip().lower() in ("1", "true", "yes", "on")
        return type(cur)(val)

    try:
        pushed = client.get_elastic_run_config()
        for key, val in pushed.items():
            if hasattr(config, key):
                setattr(config, key, _coerce(getattr(config, key), val))
    except Exception as e:  # noqa: BLE001
        logger.warning("could not fetch master run config: %s", e)

    # Gate on the CONFIG (CLI merged with master-pushed run config just
    # above) so a master enabling/disabling the checks actually takes
    # effect — node_health_check reads config.comm_perf_test too.
    if config.network_check or config.comm_perf_test:
        from dlrover_tpu.agent.node_check import node_health_check

        ok = node_health_check(config, master_addr, client)
        if not ok:
            logger.error("node health check failed; exiting for relaunch")
            return 3

    entry = (
        [args.entrypoint] if args.no_python
        else [sys.executable, "-u", args.entrypoint]
    )
    script_args = args.args
    if script_args and script_args[0] == "--":
        script_args = script_args[1:]
    try:
        rc = launch_agent(config, entry + script_args, master_addr)
    finally:
        # Stop master supervision on EVERY exit path: if the agent raised,
        # the atexit terminate must not race a supervisor respawn.
        master_stop.set()
    if master_holder:
        try:
            client.report_job_exit(rc == 0, "launcher done")
        except Exception as e:  # noqa: BLE001
            # Best-effort courtesy RPC, but a dead master here usually
            # explains a confusing exit — leave a trace.
            logger.debug("job-exit report to master failed: %s", e)
        try:
            master_holder[0].wait(timeout=30)
        except subprocess.TimeoutExpired:
            logger.warning("local master did not exit in 30s; terminating")
            master_holder[0].terminate()
    client.close()
    return rc


def main() -> None:
    sys.exit(run(parse_args()))


if __name__ == "__main__":
    main()
