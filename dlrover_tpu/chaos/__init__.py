"""chaosd: deterministic fault injection across the control plane.

See :mod:`dlrover_tpu.chaos.plan` for the ``DLROVER_TPU_FAULTS`` grammar
and the injection-point catalog.  The hot entry point is :func:`inject`,
a single ``None``-check when no plan is configured.
"""

from dlrover_tpu.chaos.plan import (  # noqa: F401
    ENV_VAR,
    EXIT_CKPT_AFTER_COMMIT,
    EXIT_CKPT_BEFORE_COMMIT,
    EXIT_CELL_BLACKOUT,
    EXIT_CELL_MASTER_KILL,
    EXIT_JOURNAL_TORN,
    EXIT_MASTER_KILL,
    EXIT_MASTER_RESTART,
    EXIT_WORKER_KILL,
    SITES,
    FaultPlan,
    FaultSpec,
    active_plan,
    configure,
    inject,
    on_crash,
    reset,
    scrub_env,
    without_sites,
)
