"""chaosd — deterministic, seeded fault injection for the control plane.

ElasWave (PAPERS.md) argues elastic-native systems must treat failure
handling as a continuously-tested subsystem; this module is how we do that
on CPU-only CI.  A :class:`FaultPlan` is parsed from the
``DLROVER_TPU_FAULTS`` env var (or set explicitly via :func:`configure`)
and consulted by named *injection points* threaded through the layers that
matter (RPC client/server, rendezvous, checkpoint commit, shm reads,
worker steps).  With no plan configured every injection point is a
single ``None``-check no-op.

Grammar (``;``-separated specs, each ``site:key=val,key=val``)::

    DLROVER_TPU_FAULTS="rpc.unavailable:p=0.2,seed=7;master.restart:at=10s;\
ckpt.crash_before_commit:step=5;worker.kill:rank=1,step=6"

Spec keys:

==========  =============================================================
``p``       probability per evaluation (default 1.0)
``seed``    decision seed (plan-wide; the last spec that sets it wins)
``at``      only fire once this many seconds have elapsed (``10s``/``500ms``)
``step``    only fire when the site reports this step
``step_ge`` only fire once the site reports a step >= this (monotone
            progress counters — e.g. the gateway tier's heartbeat
            reports its completed-request count, so ``step_ge=2``
            means "once two requests finished", deterministic even
            when the counter skips values between evaluations)
``rank``    only fire for this rank / process id / node rank
``method``  only fire for this RPC message type (e.g. ``JoinRendezvous``)
``times``   max firings (default 1 for crash sites, unlimited otherwise)
``every``   fire on every Nth matching evaluation (deterministic flap)
``delay``   sleep duration for latency sites (``2s``/``50ms``)
``exit``    exit code override for crash sites
==========  =============================================================

Determinism: the decision for the *n*-th evaluation of a site is a pure
function of ``(seed, site, n)`` — no shared RNG stream — so two runs of
the same scenario inject the identical fault sequence for the same
evaluation sequence, and concurrent sites never perturb each other.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import zlib
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger

ENV_VAR = "DLROVER_TPU_FAULTS"

# Exit codes picked outside the usual 0/1/2 band so a chaos crash is
# recognizable in launcher/e2e logs.
EXIT_CKPT_BEFORE_COMMIT = 66
EXIT_CKPT_AFTER_COMMIT = 67
EXIT_WORKER_KILL = 77
EXIT_MASTER_RESTART = 42
EXIT_REPLICA_KILL = 78
EXIT_RESHARD_CRASH = 79
EXIT_SLICE_CRASH = 80
EXIT_GATEWAY_KILL = 81
EXIT_DRAFT_KILL = 82
EXIT_MASTER_KILL = 83
EXIT_JOURNAL_TORN = 84
EXIT_CELL_MASTER_KILL = 85
EXIT_CELL_BLACKOUT = 86

#: site name -> (kind, defaults).  Kinds: ``error`` (caller raises),
#: ``latency`` (inject() sleeps), ``crash`` (inject() calls os._exit),
#: ``flag`` (caller applies the effect, e.g. "pretend the read was torn").
#:
#: ``doc`` is each site's one-line operator contract: graftcheck's
#: ``--chaos-table`` reporter generates the README's injection-point
#: catalog from exactly these strings (a tier-1 test pins the README
#: table to the generated one), so the documentation lives beside the
#: declaration and cannot drift from it.
SITES: Dict[str, dict] = {
    "rpc.unavailable": {
        "kind": "error",
        "doc": "synthetic UNAVAILABLE at `RpcClient.call` before the "
               "send (the request never left)",
    },
    "rpc.latency": {
        "kind": "latency", "delay": 0.2,
        "doc": "sleep `delay` at `RpcClient.call` before the send",
    },
    "rpc.drop": {
        "kind": "error",
        "doc": "request aborted UNAVAILABLE at `RpcServer`; the "
               "handler never runs",
    },
    # Gray network (ISSUE 18): the RPC SUCCEEDS — the failure modes
    # are time and multiplicity, not loss.  Worse than a clean outage:
    # nothing trips the retry/failover machinery, so dedupe and
    # timeout budgets are what must hold.
    "net.gray": {
        "kind": "flag", "delay": 0.2,
        "doc": "gray network at `RpcClient.call`: the reply arrives "
               "but `delay` LATE, and the request is re-sent once "
               "(wire duplicate) — the receiver's idempotency/dedupe "
               "must absorb it; nothing is dropped",
    },
    "rdzv.late_join": {
        "kind": "latency", "delay": 2.0,
        "doc": "sleep `delay` in the master rendezvous join (late "
               "joiner)",
    },
    "rdzv.lost_node": {
        "kind": "flag",
        "doc": "rendezvous join silently discarded; the agent's "
               "re-join loop must recover",
    },
    "ckpt.crash_before_commit": {
        "kind": "crash", "exit": EXIT_CKPT_BEFORE_COMMIT, "times": 1,
        "doc": "`os._exit(66)` in shard-file commit BEFORE the "
               "tracker write — previous step stays committed",
    },
    "ckpt.crash_after_commit": {
        "kind": "crash", "exit": EXIT_CKPT_AFTER_COMMIT, "times": 1,
        "doc": "`os._exit(67)` in shard-file commit AFTER the tracker "
               "write — the new step is durable",
    },
    "ckpt.slow_storage": {
        "kind": "latency", "delay": 1.0,
        "doc": "sleep `delay` per shard persist (saver + engine) — "
               "the bounded-stall knobs are what must absorb it",
    },
    "shm.torn_read": {
        "kind": "flag", "times": 1,
        "doc": "one shm-arena read reports torn state; validation "
               "must refuse it",
    },
    # Data-corruption sites: the caller damages the payload it was about
    # to write/send (silent bit-rot, torn transfers) — the commit
    # protocol proceeds normally, so restore-side verification is what
    # must catch it.
    "storage.corrupt_shard": {
        "kind": "flag", "times": 1,
        "doc": "one written shard gets a flipped byte (silent "
               "bit-rot); CRC verification must catch it at restore",
    },
    "storage.truncate_shard": {
        "kind": "flag", "times": 1,
        "doc": "one written shard loses its second half (torn write); "
               "the restore ladder falls back a step",
    },
    "replica.torn_push": {
        "kind": "flag", "times": 1,
        "doc": "only a payload prefix 'arrives' at the replica ring; "
               "the receiver must reject it",
    },
    "worker.kill": {
        "kind": "crash", "exit": EXIT_WORKER_KILL, "times": 1,
        "doc": "`os._exit(77)` at the worker step hook at "
               "`rank`/`step`",
    },
    # Serving-fleet sites (ISSUE 5): kill a replica mid-stream, lose a
    # granted request before the replica ever sees it (the gateway's
    # poll-reconcile must re-dispatch), or slow one replica's rounds
    # (the p95-TTFT signal the autoscaler steers on).
    "serving.replica_kill": {
        "kind": "crash", "exit": EXIT_REPLICA_KILL, "times": 1,
        "doc": "`os._exit(78)` in the replica's tick mid-stream; "
               "journal replay + gateway dedupe keep exactly-once",
    },
    "serving.drop_request": {
        "kind": "flag", "times": 1,
        "doc": "a granted request evaporates before the replica sees "
               "it; poll-reconcile must re-dispatch",
    },
    "serving.slow_replica": {
        "kind": "latency", "delay": 0.5,
        "doc": "sleep `delay` in one replica's tick — the p95-TTFT "
               "signal the autoscaler steers on",
    },
    # KV-handoff site (ISSUE 8): the prefill->decode KV segment is lost
    # or torn in flight.
    "serving.kv_drop": {
        "kind": "flag", "times": 1,
        "doc": "KV handoff fault (`method=export`/`import`/`pull`): "
               "segment lost before kv-ready / torn at the decode "
               "import / P2P pull dropped (CRC must reject; "
               "re-prefill — a failed pull falls back to relay — "
               "bounded by max_attempts)",
    },
    # Paged-KV site (ISSUE 19): a block's free is dropped on the
    # abort/finish path — refcount zero, but the block never returns
    # to the free list.  The arena's per-iteration scavenge rebuilds
    # the free list from the refcounts; the tier-1 invariant is
    # conservation: free_blocks + used_blocks == pool size after any
    # chaos run.
    "serving.block_leak": {
        "kind": "flag", "times": 1,
        "doc": "drop a KV block's free on the abort path (`block=id`); "
               "the arena scavenge must repair it — conservation law "
               "`free + used == pool` holds after any run",
    },
    # Offline-tier site (ISSUE 20): kill one offline worker's CHUNK
    # machinery at the chunk loop's admission point — partial decode
    # output evaporates, the chunk requeues, and the journaled work
    # queue's dedupe makes the replay exactly-once (`method=<worker>`
    # scopes the victim; whole-worker death reuses replica_kill).
    "offline.chunk_kill": {
        "kind": "flag", "times": 1,
        "doc": "offline worker dies mid-chunk (`method=<worker_id>`): "
               "partials discarded, chunk requeued intact; the "
               "journal-before-ack queue replays it exactly-once",
    },
    # Gateway-tier site (ISSUE 9): hard-kill one gateway of a sharded
    # tier mid-stream.
    "serving.gateway_kill": {
        "kind": "crash", "exit": EXIT_GATEWAY_KILL, "times": 1,
        "doc": "`os._exit(81)` in the tier heartbeat "
               "(`method=<gateway_id>`, `step_ge=N` completions) — "
               "survivors adopt the hash range; client resubmit + "
               "journal/dedupe keep exactly-once",
    },
    # Draft-replica site (ISSUE 11): kill the speculation proposal
    # server mid-round.  Correctness is owned by the TARGET's
    # acceptance, so the only legal observable effect is degradation.
    "serving.draft_kill": {
        "kind": "crash", "exit": EXIT_DRAFT_KILL, "times": 1,
        "doc": "`os._exit(82)` in the draft proposal loop "
               "(`method=<worker_id>`, `step_ge=N` rolls) — spec "
               "targets degrade to plain decode (`spec_fallbacks`), "
               "every in-flight request exactly-once, no token "
               "changes",
    },
    "master.restart": {
        "kind": "crash", "exit": EXIT_MASTER_RESTART, "times": 1,
        "doc": "`os._exit(42)` at elapsed `at` — the SUPERVISED cold "
               "path (launcher relaunches on the same port)",
    },
    # Master HA sites (ISSUE 13).  ``master.kill`` is the UNCLEAN exit —
    # distinct from the supervised ``master.restart`` cold path;
    # ``master.journal_torn`` crashes INSIDE a ControlStateJournal
    # append between the two halves of a frame.
    "master.kill": {
        "kind": "crash", "exit": EXIT_MASTER_KILL, "times": 1,
        "doc": "`os._exit(83)` at elapsed `at` — the UNCLEAN death "
               "the warm standby must absorb (no supervisor "
               "relaunch)",
    },
    "master.journal_torn": {
        "kind": "crash", "exit": EXIT_JOURNAL_TORN, "times": 1,
        "doc": "crash `os._exit(84)` BETWEEN the two halves of a WAL "
               "frame — the literal crash-mid-append; reopen "
               "truncates the torn tail, losing exactly the unacked "
               "record",
    },
    # Multi-cell sites (ISSUE 15).  ``cell.master_kill`` is one cell's
    # master dying UNCLEANLY — the cell's warm standby absorbs it while
    # every OTHER cell must not black out; ``cell.split`` forges the
    # two-owners-for-one-range state the federation's view cross-check
    # must detect.
    "cell.master_kill": {
        "kind": "crash", "exit": EXIT_CELL_MASTER_KILL, "times": 1,
        "doc": "`os._exit(85)` in one cell master's registry heartbeat "
               "(`method=<cell_id>`, `step_ge=N` beats) — its standby "
               "adopts the journaled state; peer cells never black out",
    },
    "cell.split": {
        "kind": "flag", "times": 1,
        "doc": "one cell heartbeat publishes a SELF-ONLY ring view "
               "(`method=<cell_id>`) — the federation sees two owners "
               "for one node range (`cell_split_detected`); views "
               "self-heal on the next beat",
    },
    # Correlated whole-cell failure (ISSUE 17): the unit of failure is
    # an entire cell — master, warm standby, and every gateway/replica
    # in it die as ONE event.  Admitted in-flight requests must still
    # complete exactly once via sibling-cell spillover.
    "cell.blackout": {
        "kind": "crash", "exit": EXIT_CELL_BLACKOUT, "times": 1,
        "doc": "`os._exit(86)` kills one WHOLE cell as a single event "
               "(`method=<cell_id>`): the cell master and every "
               "gateway of that cell fire this site from their "
               "heartbeats, so the cell is gone within one beat — no "
               "standby takeover; in-flight requests complete exactly "
               "once by spilling to a sibling cell",
    },
    # Scale-out checkpoint site (ISSUE 7): a rank dies after streaming
    # its slice bytes but BEFORE the atomic publish + done-vote.
    "storage.slice_crash": {
        "kind": "crash", "exit": EXIT_SLICE_CRASH, "times": 1,
        "doc": "`os._exit(80)` after slice bytes hit the unpublished "
               "tmp file — widow slice; the coverage proof must "
               "block commit",
    },
    # Live-reshard sites (ISSUE 6): all three must degrade to the
    # checkpoint-restart ladder with fsck-clean storage.
    "reshard.drop_segment": {
        "kind": "flag", "times": 1,
        "doc": "a plan segment vanishes in flight; the mover must "
               "fail the move (never hang or accept torn bytes) and "
               "fall to the restart ladder",
    },
    "reshard.stall_peer": {
        "kind": "latency", "delay": 0.5,
        "doc": "sleep `delay` in a peer's segment server — a stalled "
               "NIC slowing every pull",
    },
    "reshard.crash_mid_move": {
        "kind": "crash", "exit": EXIT_RESHARD_CRASH, "times": 1,
        "doc": "`os._exit(79)` between segment applies — the "
               "survivors detect the lost rank; restart ladder with "
               "fsck-clean storage",
    },
}


def _parse_duration(text: str) -> float:
    text = text.strip()
    if text.endswith("ms"):
        return float(text[:-2]) / 1000.0
    if text.endswith("s"):
        return float(text[:-1])
    return float(text)


@dataclasses.dataclass
class FaultSpec:
    """One parsed fault: a site plus its matching filters."""

    site: str
    kind: str = "flag"
    p: float = 1.0
    at: Optional[float] = None
    step: Optional[int] = None
    step_ge: Optional[int] = None
    rank: Optional[int] = None
    method: str = ""
    times: int = -1  # -1 = unlimited
    every: int = 0  # 0 = off; N = every Nth matching evaluation
    delay: float = 0.0
    exit_code: int = 1
    plan_seed: Optional[int] = None  # a spec's seed= sets the plan seed
    # Runtime counters (per process), guarded by the plan lock.
    evals: int = 0
    fired: int = 0

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        """Parse one ``site:key=val,...`` spec.  Raises ``ValueError`` on an
        unknown site or key — a typo'd chaos plan must fail loudly, not
        silently inject nothing."""
        site, _, rest = text.strip().partition(":")
        site = site.strip()
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; known: {sorted(SITES)}"
            )
        defaults = SITES[site]
        spec = cls(
            site=site,
            kind=defaults["kind"],
            times=defaults.get("times", -1),
            delay=defaults.get("delay", 0.0),
            exit_code=defaults.get("exit", 1),
        )
        for part in filter(None, (p.strip() for p in rest.split(","))):
            key, sep, val = part.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(f"fault param {part!r} is not key=value")
            val = val.strip()
            if key == "p":
                spec.p = float(val)
            elif key == "seed":
                spec.plan_seed = int(val)
            elif key == "at":
                spec.at = _parse_duration(val)
            elif key == "step":
                spec.step = int(val)
            elif key == "step_ge":
                spec.step_ge = int(val)
            elif key == "rank":
                spec.rank = int(val)
            elif key == "method":
                spec.method = val
            elif key == "times":
                spec.times = int(val)
            elif key == "every":
                spec.every = int(val)
            elif key == "delay":
                spec.delay = _parse_duration(val)
            elif key == "exit":
                spec.exit_code = int(val)
            else:
                raise ValueError(
                    f"unknown fault param {key!r} in spec {text!r}"
                )
        return spec


def _decide(seed: int, site: str, n: int, p: float) -> bool:
    """Deterministic Bernoulli draw for the n-th evaluation of ``site``:
    a pure function of (seed, site, n), so runs replay identically and
    sites never share an RNG stream."""
    if p >= 1.0:
        return True
    if p <= 0.0:
        return False
    h = zlib.crc32(f"{seed}:{site}:{n}".encode())
    return (h & 0xFFFFFF) / float(1 << 24) < p


class FaultPlan:
    """A parsed set of :class:`FaultSpec` s plus the decision engine."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = specs
        self.seed = seed
        self._lock = threading.Lock()
        self._t0 = time.monotonic()

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = [
            FaultSpec.parse(part)
            for part in filter(None, (p.strip() for p in text.split(";")))
        ]
        if not specs:
            raise ValueError(f"empty fault plan {text!r}")
        seed = 0
        for spec in specs:
            if spec.plan_seed is not None:
                seed = spec.plan_seed
        return cls(specs, seed=seed)

    def has_site(self, site: str) -> bool:
        return any(s.site == site for s in self.specs)

    def site_armed(self, site: str) -> bool:
        """True while ``site`` can STILL fire (firing budget not
        exhausted).  Hot paths that pay extra work only to give a crash
        site its window (the control journal's split-write) gate on
        this instead of :meth:`has_site`, so a consumed one-shot stops
        costing anything."""
        with self._lock:
            return any(
                s.site == site and (s.times < 0 or s.fired < s.times)
                for s in self.specs
            )

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def fire(self, site: str, **ctx) -> Optional[FaultSpec]:
        """Decide whether a fault fires at ``site`` for this evaluation.
        Pure decision — effects are applied by :func:`inject`."""
        hit = None
        with self._lock:
            for spec in self.specs:
                if spec.site != site:
                    continue
                if spec.rank is not None and ctx.get("rank") != spec.rank:
                    continue
                if spec.step is not None and ctx.get("step") != spec.step:
                    continue
                if spec.step_ge is not None and (
                    ctx.get("step") is None
                    or ctx.get("step") < spec.step_ge
                ):
                    continue
                if spec.method and ctx.get("method") != spec.method:
                    continue
                if spec.at is not None and self.elapsed() < spec.at:
                    continue
                if 0 <= spec.times <= spec.fired:
                    continue
                spec.evals += 1
                if spec.every > 0 and spec.evals % spec.every != 0:
                    continue
                if not _decide(self.seed, site, spec.evals, spec.p):
                    continue
                spec.fired += 1
                hit = spec
                break
        return hit

    def stats(self) -> Dict[str, int]:
        """site -> total firings (for tests and exit logging)."""
        with self._lock:
            out: Dict[str, int] = {}
            for spec in self.specs:
                out[spec.site] = out.get(spec.site, 0) + spec.fired
            return out

    def describe(self) -> str:
        return "; ".join(
            f"{s.site}(p={s.p}, times={s.times})" for s in self.specs
        )


_PLAN: Optional[FaultPlan] = None

#: Pre-crash callbacks (ISSUE 12): a chaos crash simulates SIGKILL for
#: every subsystem under test (no atexit, no finally) — but the flight
#: recorder is precisely the black box that must survive the crash, so
#: registered hooks run (guarded) in the last instants before
#: ``os._exit``.  Hooks must be fast and must never raise the process
#: back to life: exceptions are swallowed (logged), and the exit
#: proceeds regardless.
_CRASH_HOOKS: List = []


def on_crash(hook) -> None:
    """Register ``hook(site, ctx)`` to run before a chaos crash exits."""
    if hook not in _CRASH_HOOKS:
        _CRASH_HOOKS.append(hook)


def _load_from_env() -> Optional[FaultPlan]:
    text = os.environ.get(ENV_VAR, "").strip()
    if not text:
        return None
    try:
        plan = FaultPlan.parse(text)
    except ValueError:
        # A malformed chaos knob must not take down a production job; the
        # chaos tests themselves assert on injection counts, so a typo'd
        # plan is still caught where it matters.
        logger.exception("chaos: invalid %s=%r ignored", ENV_VAR, text)
        return None
    logger.warning(
        "chaos: fault plan active (seed=%d): %s", plan.seed, plan.describe()
    )
    return plan


def configure(plan: "FaultPlan | str | None") -> Optional[FaultPlan]:
    """Install a fault plan explicitly (tests / embedders).  Pass ``None``
    to clear.  Raises ``ValueError`` on a malformed plan string."""
    global _PLAN
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    _PLAN = plan
    return _PLAN


def reset() -> None:
    configure(None)


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def inject(site: str, **ctx) -> Optional[FaultSpec]:
    """The injection point.  Returns ``None`` (and does nothing) unless a
    configured fault fires here.  Latency faults sleep in place; crash
    faults never return (``os._exit``); error/flag faults return the spec
    and the caller applies the effect."""
    plan = _PLAN
    if plan is None:
        return None
    spec = plan.fire(site, **ctx)
    if spec is None:
        return None
    _journal_firing(site, spec, ctx)
    if spec.kind == "latency":
        logger.warning(
            "chaos: %s fired (ctx=%s): sleeping %.3fs", site, ctx, spec.delay
        )
        time.sleep(spec.delay)
    elif spec.kind == "crash":
        logger.warning(
            "chaos: %s fired (ctx=%s): os._exit(%d)", site, ctx,
            spec.exit_code,
        )
        for hook in list(_CRASH_HOOKS):
            try:
                hook(site, dict(ctx))
            except Exception:  # noqa: BLE001 - the exit must proceed
                logger.warning("chaos: crash hook failed", exc_info=True)
        # Hard exit on purpose: a chaos crash simulates SIGKILL/OOM — no
        # atexit hooks, no finally blocks, no flushing beyond this line
        # (the flight-recorder spill above is the one sanctioned
        # exception: the black box that must survive the crash).
        os._exit(spec.exit_code)
    else:
        logger.warning("chaos: %s fired (ctx=%s)", site, ctx)
    return spec


def _journal_firing(site: str, spec: FaultSpec, ctx: dict) -> None:
    """Every chaos firing is a control-plane journal event (ISSUE 12):
    a postmortem must show the injection beside its consequences.  Lazy
    import (obs pulls nothing heavy, but chaos must import first)."""
    try:
        from dlrover_tpu.obs import journal

        journal(
            "chaos.inject", site=site, fault_kind=spec.kind,
            fired=spec.fired,
            ctx={k: v for k, v in ctx.items()
                 if isinstance(v, (str, int, float, bool))},
        )
    except Exception:  # noqa: BLE001 - chaos must fire regardless
        logger.debug("chaos: journal emit failed", exc_info=True)


def without_sites(plan_text: str, sites) -> str:
    """Drop every spec whose site is in ``sites`` from a raw plan string.

    Fault-firing state is per process, so a one-shot crash fault would
    re-arm in every relaunched process that inherits the env and kill the
    replacement too.  Relaunchers therefore scrub the crash site that
    just fired before spawning the successor: the launcher's local-master
    supervisor strips ``master.restart`` after an exit-42, and the agent
    strips ``worker.kill`` from worker envs after observing exit-77.
    Non-crash faults (flaps, latency) intentionally survive relaunch."""
    sites = set(sites)
    kept = [
        part for part in (p.strip() for p in plan_text.split(";"))
        if part and part.partition(":")[0].strip() not in sites
    ]
    if not kept:
        return ""
    # The plan-wide seed may have ridden on a stripped spec ("the last
    # spec that sets it wins"); deterministic replay of the surviving
    # faults must not silently fall back to seed 0.  Re-pin it on the
    # last survivor (last-wins makes that the effective seed).
    try:
        want = FaultPlan.parse(plan_text).seed
        if FaultPlan.parse(";".join(kept)).seed != want:
            sep = "," if ":" in kept[-1] else ":"
            kept[-1] += f"{sep}seed={want}"
    except ValueError:
        pass  # unparseable input: return the filtered text as-is
    return ";".join(kept)


def scrub_env(env: dict, sites) -> dict:
    """Strip ``sites`` from ``env``'s fault plan in place (removing the
    variable entirely when nothing survives) and return ``env``.  The one
    implementation both relaunchers use — the launcher's master
    supervisor and the agent's worker respawn."""
    text = env.get(ENV_VAR)
    if text:
        stripped = without_sites(text, sites)
        if stripped:
            env[ENV_VAR] = stripped
        else:
            env.pop(ENV_VAR)
    return env


_PLAN = _load_from_env()
