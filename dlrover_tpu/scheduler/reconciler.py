"""L1 control plane: the ElasticJob reconciler (k8s-operator equivalent).

Parity with the reference Go operator
(``go/operator/pkg/controllers/elasticjob_controller.go:1`` Reconcile loop,
``controllers/master/master.go:1`` master-pod bootstrap,
``scaleplan_controller.go`` ScalePlan application,
``api/v1alpha1/elasticjob_types.go:39`` the ElasticJob/ReplicaSpec schema).

TPU-first shape: instead of CRDs + controller-runtime, a small
**level-triggered reconcile loop** over the :class:`PlatformClient` node
table.  The desired state is a :class:`JobSpec`; the observed state is
``platform.list_nodes()``; each :meth:`JobReconciler.reconcile_once` computes
and applies the diff through the SAME platform client the master's scaler
uses, so a test that kills an InMemory node and a GKE pod deletion exercise
one code path.

Master-first bootstrap: the job master node is created before any worker
(reference ``master.go`` creates the master pod when the job is created) and
workers are only launched once the master reports RUNNING.  The master's
auto-scaler feeds back through :class:`~dlrover_tpu.master.scaler.
ElasticJobScaler` plan files (the ScalePlan-CR analogue), which the
reconciler consumes on each pass.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.scheduler.platform import PlatformClient, PlatformNode

_LIVE = (NodeStatus.INITIAL, NodeStatus.PENDING, NodeStatus.RUNNING)
_DEAD = (NodeStatus.FAILED, NodeStatus.DELETED)


@dataclasses.dataclass
class ReplicaSpec:
    """Desired replicas of one node type (reference
    ``elasticjob_types.go:39`` ReplicaSpec: replicas + restart policy)."""

    count: int
    resource: NodeResource = dataclasses.field(default_factory=NodeResource)
    max_relaunch: int = 3


@dataclasses.dataclass
class JobSpec:
    """Desired job state — the ElasticJob-CR analogue."""

    job_name: str
    replicas: Dict[str, ReplicaSpec]
    with_master: bool = True
    master_resource: NodeResource = dataclasses.field(
        default_factory=NodeResource
    )
    master_max_relaunch: int = 2


class JobPhase:
    PENDING = "pending"          # master not yet running
    RUNNING = "running"
    COMPLETED = "completed"
    FAILED = "failed"


class JobReconciler:
    """Owns desired replica state and drives the platform toward it.

    One instance per job.  Thread-safe; run :meth:`reconcile_once` from a
    test, or :meth:`start` for the watch-triggered background loop.
    """

    def __init__(
        self,
        spec: JobSpec,
        platform: PlatformClient,
        *,
        plan_dir: Optional[str] = None,
        resync_interval: float = 2.0,
    ):
        self.spec = spec
        self.platform = platform
        self.plan_dir = plan_dir
        self.phase = JobPhase.PENDING
        self._lock = threading.Lock()
        self._next_id = 0
        # (node_type, rank) -> relaunches consumed.
        self._relaunches: Dict[Tuple[str, int], int] = {}
        # Node names whose failure we've already answered with a relaunch.
        self._handled_failures: set = set()
        self._consumed_plans: set = set()
        self._resync = resync_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- desired-state mutation (ScalePlan entry) ---------------------------
    def set_replicas(self, node_type: str, count: int) -> None:
        with self._lock:
            if node_type in self.spec.replicas:
                self.spec.replicas[node_type].count = max(0, count)
            else:
                self.spec.replicas[node_type] = ReplicaSpec(count=max(0, count))

    def _consume_plan_files(self) -> None:
        """Apply ScalePlan JSON specs emitted by
        :class:`~dlrover_tpu.master.scaler.ElasticJobScaler` (the
        ScalePlan-CR analogue, reference ``scaleplan_controller.go``)."""
        if not self.plan_dir:
            return
        pattern = os.path.join(
            self.plan_dir, f"{self.spec.job_name}-scaleplan-*.json"
        )
        for path in sorted(glob.glob(pattern)):
            if path in self._consumed_plans:
                continue
            try:
                with open(path) as f:
                    plan = json.load(f)
            except (OSError, ValueError):
                continue
            for ntype, group in plan.get("node_group_resources", {}).items():
                self.set_replicas(ntype, int(group.get("count", 0)))
                logger.info(
                    "reconciler: plan %s -> %s=%d",
                    os.path.basename(path), ntype, group.get("count"),
                )
            self._consumed_plans.add(path)

    # -- observation helpers ------------------------------------------------
    def _observe(self) -> Dict[str, List[PlatformNode]]:
        by_type: Dict[str, List[PlatformNode]] = {}
        for pn in self.platform.list_nodes():
            by_type.setdefault(pn.node_type, []).append(pn)
            self._next_id = max(self._next_id, pn.node_id + 1)
        return by_type

    def _alloc_id(self) -> int:
        nid = self._next_id
        self._next_id += 1
        return nid

    def _launch(
        self, node_type: str, rank: int, resource: NodeResource,
        max_relaunch: int,
    ) -> PlatformNode:
        node = Node(
            node_type,
            self._alloc_id(),
            rank_index=rank,
            config_resource=resource,
            max_relaunch_count=max_relaunch,
        )
        pn = self.platform.create_node(node, self.spec.job_name)
        logger.info(
            "reconciler: launched %s (type=%s rank=%d)",
            pn.name, node_type, rank,
        )
        return pn

    # -- the reconcile pass --------------------------------------------------
    def reconcile_once(self) -> Dict[str, int]:
        """One level-triggered pass: observe, diff, act.  Returns a summary
        ``{"launched": n, "removed": n}`` of the actions taken."""
        self._consume_plan_files()
        with self._lock:
            return self._reconcile_locked()

    def _reconcile_locked(self) -> Dict[str, int]:
        if self.phase in (JobPhase.COMPLETED, JobPhase.FAILED):
            return {"launched": 0, "removed": 0}
        by_type = self._observe()
        launched = removed = 0

        # 1. Master bootstrap (reference master.go: master pod first).
        if self.spec.with_master:
            masters = by_type.get(NodeType.MASTER, [])
            live = [m for m in masters if m.status in _LIVE]
            if not live:
                budget = self._relaunches.get((NodeType.MASTER, 0), 0)
                if any(m.status == NodeStatus.FAILED for m in masters):
                    if budget >= self.spec.master_max_relaunch:
                        self.phase = JobPhase.FAILED
                        logger.error(
                            "reconciler: master exhausted %d relaunches",
                            budget,
                        )
                        return {"launched": launched, "removed": removed}
                    self._relaunches[(NodeType.MASTER, 0)] = budget + 1
                self._launch(
                    NodeType.MASTER, 0, self.spec.master_resource,
                    self.spec.master_max_relaunch,
                )
                launched += 1
                self.phase = JobPhase.PENDING
                return {"launched": launched, "removed": removed}
            if all(m.status != NodeStatus.RUNNING for m in live):
                # Master scheduled but not up: workers wait.
                self.phase = JobPhase.PENDING
                return {"launched": launched, "removed": removed}
        self.phase = JobPhase.RUNNING

        # 2. Per-type replica reconciliation.  Completion requires at least
        # one type that actually wants replicas — a job scaled to 0 (pause)
        # must stay reconcilable, not flip to terminal COMPLETED.
        all_done = any(r.count > 0 for r in self.spec.replicas.values())
        for ntype, rspec in self.spec.replicas.items():
            nodes = by_type.get(ntype, [])
            live = [n for n in nodes if n.status in _LIVE]
            succeeded = [
                n for n in nodes if n.status == NodeStatus.SUCCEEDED
            ]
            if len(succeeded) < rspec.count:
                all_done = False
            live_ranks = {n.rank_index for n in live}
            done_ranks = {n.rank_index for n in succeeded}

            # 2a. Relaunch failed nodes (same rank, new id) within budget.
            for n in nodes:
                if n.status != NodeStatus.FAILED:
                    continue
                if n.name in self._handled_failures:
                    continue
                self._handled_failures.add(n.name)
                if (
                    n.rank_index in live_ranks
                    or n.rank_index in done_ranks
                    or n.rank_index >= rspec.count
                ):
                    continue  # rank already covered or scaled away
                key = (ntype, n.rank_index)
                used = self._relaunches.get(key, 0)
                if used >= rspec.max_relaunch:
                    self.phase = JobPhase.FAILED
                    logger.error(
                        "reconciler: %s rank %d exhausted %d relaunches",
                        ntype, n.rank_index, used,
                    )
                    return {"launched": launched, "removed": removed}
                self._relaunches[key] = used + 1
                self._launch(
                    ntype, n.rank_index, rspec.resource, rspec.max_relaunch
                )
                live_ranks.add(n.rank_index)
                launched += 1

            # 2b. Scale up: fill missing ranks [0, count).
            covered = live_ranks | done_ranks
            for rank in range(rspec.count):
                if rank in covered:
                    continue
                self._launch(ntype, rank, rspec.resource, rspec.max_relaunch)
                covered.add(rank)
                launched += 1

            # 2c. Scale down: remove live nodes with rank >= count
            # (highest first, keeping surviving ranks contiguous).
            extras = sorted(
                (n for n in live if n.rank_index >= rspec.count),
                key=lambda n: -n.rank_index,
            )
            for n in extras:
                if self.platform.delete_node(n.name):
                    logger.info("reconciler: removed %s", n.name)
                    removed += 1

        # 3. Completion: every replica rank succeeded.
        if all_done:
            self.phase = JobPhase.COMPLETED
            logger.info("reconciler: job %s completed", self.spec.job_name)
        return {"launched": launched, "removed": removed}

    # -- background loop ----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="job-reconciler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        # Level-triggered with watch acceleration: a platform event only
        # wakes the loop early; every pass re-lists the world.
        wake = threading.Event()

        def watcher():
            try:
                for _ in self.platform.watch(self._stop):
                    wake.set()
            except Exception:  # noqa: BLE001 - watch streams may drop
                logger.exception("reconciler watch stream ended")

        wt = threading.Thread(target=watcher, daemon=True)
        wt.start()
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001
                logger.exception("reconcile pass failed")
            if self.phase in (JobPhase.COMPLETED, JobPhase.FAILED):
                return
            wake.wait(timeout=self._resync)
            wake.clear()


def main(argv=None) -> int:  # pragma: no cover - thin CLI shell
    """Standalone operator process: ``python -m
    dlrover_tpu.scheduler.reconciler --job_name j --workers 4 --platform gke``
    (the deployment analogue of the reference's operator Deployment)."""
    import argparse

    from dlrover_tpu.scheduler.platform import new_platform_client

    p = argparse.ArgumentParser("dlrover-tpu-operator")
    p.add_argument("--job_file", default="",
                   help="declarative ElasticJob YAML (replaces "
                        "--job_name/--workers/resource flags)")
    p.add_argument("--job_name", default="")
    p.add_argument("--workers", type=int, default=0)
    p.add_argument("--platform", default="gke")
    p.add_argument("--namespace", default="default")
    p.add_argument("--image", default="")
    p.add_argument("--plan_dir", default="")
    p.add_argument("--max_relaunch", type=int, default=3)
    p.add_argument("--tpu_chips", type=int, default=4)
    args = p.parse_args(argv)

    kwargs = (
        {"namespace": args.namespace, "image": args.image}
        if args.platform == "gke"
        else {}
    )
    platform = new_platform_client(args.platform, **kwargs)
    if args.job_file:
        from dlrover_tpu.scheduler.jobfile import (
            load_elastic_job,
            to_job_spec,
        )

        spec = to_job_spec(load_elastic_job(args.job_file))
    else:
        if not args.job_name or args.workers <= 0:
            p.error("--job_name and --workers are required "
                    "(or pass --job_file)")
        spec = JobSpec(
            job_name=args.job_name,
            replicas={
                NodeType.WORKER: ReplicaSpec(
                    count=args.workers,
                    resource=NodeResource(tpu_chips=args.tpu_chips),
                    max_relaunch=args.max_relaunch,
                )
            },
        )
    rec = JobReconciler(
        spec, platform, plan_dir=args.plan_dir or None
    )
    rec.start()
    try:
        while rec.phase not in (JobPhase.COMPLETED, JobPhase.FAILED):
            rec._stop.wait(2.0)
            if rec._stop.is_set():
                break
    except KeyboardInterrupt:
        pass
    finally:
        rec.stop()
    return 0 if rec.phase == JobPhase.COMPLETED else 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
