"""Platform scheduler layer: where nodes actually run.

Parity with reference ``dlrover/python/scheduler/`` (``k8sClient
kubernetes.py:122``, ``K8sElasticJob :371``, ``JobArgs job.py:69``,
``RayClient ray.py:51``) re-cast for TPU fleets: the scheduling quantum is a
TPU-VM *host* inside a slice (all-or-nothing) or a whole slice in multislice
jobs, not a pod-per-GPU.
"""

from dlrover_tpu.scheduler.job import JobArgs, NodeGroupArgs
from dlrover_tpu.scheduler.platform import (
    InMemoryPlatform,
    PlatformClient,
    PlatformNode,
    PlatformNodeEvent,
    new_platform_client,
)

__all__ = [
    "JobArgs",
    "NodeGroupArgs",
    "InMemoryPlatform",
    "PlatformClient",
    "PlatformNode",
    "PlatformNodeEvent",
    "new_platform_client",
]
