"""Declarative ElasticJob file: the YAML job spec an operator checks in.

Parity with the reference's ElasticJob CRD
(``go/operator/api/v1alpha1/elasticjob_types.go:39`` ElasticJobSpec /
ReplicaSpec and the user-facing example
``examples/pytorch/nanogpt/elastic_job.yaml``).  TPU-first shape: one
YAML document consumed by BOTH entry points —

- ``python -m dlrover_tpu.scheduler.reconciler --job_file job.yaml``
  (desired replica state for the reconcile loop), and
- ``python -m dlrover_tpu.run --job_file job.yaml`` (launcher defaults:
  script, args, nproc, elastic node range).

Schema (all spec fields optional unless noted)::

    apiVersion: elastic.dlrover-tpu/v1alpha1
    kind: ElasticJob
    metadata:
      name: nanogpt            # required
    spec:
      distributionStrategy: AllreduceStrategy
      nodeUnit: 1
      maxRestarts: 3
      networkCheck: false
      replicaSpecs:
        worker:                # required: at least one replica type
          replicas: 2          # required
          minReplicas: 1       # elastic range (defaults to replicas)
          maxReplicas: 4
          maxRelaunch: 3
          resources:
            tpuChips: 4
            tpuType: v5e
            cpu: 4
            memoryMB: 8192
      template:
        script: examples/nanogpt_train.py
        args: ["--steps=40"]
        nprocPerNode: 2
      checkpoint:
        dir: /ckpt
        interval: 5
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.node import NodeResource

API_VERSION = "elastic.dlrover-tpu/v1alpha1"
KIND = "ElasticJob"


@dataclasses.dataclass
class ReplicaFileSpec:
    replicas: int
    min_replicas: int
    max_replicas: int
    max_relaunch: int = 3
    resource: NodeResource = dataclasses.field(default_factory=NodeResource)


@dataclasses.dataclass
class ElasticJobFile:
    """Parsed + validated ElasticJob YAML."""

    name: str
    replica_specs: Dict[str, ReplicaFileSpec]
    distribution_strategy: str = "AllreduceStrategy"
    node_unit: int = 1
    max_restarts: int = 3
    network_check: bool = False
    script: str = ""
    script_args: List[str] = dataclasses.field(default_factory=list)
    nproc_per_node: int = 1
    ckpt_dir: str = ""
    ckpt_interval: int = 0

    @property
    def worker(self) -> ReplicaFileSpec:
        if "worker" not in self.replica_specs:
            raise ValueError("ElasticJob has no 'worker' replicaSpec")
        return self.replica_specs["worker"]


def _req(d: Dict, key: str, ctx: str) -> Any:
    if key not in d:
        raise ValueError(f"ElasticJob file: missing '{key}' in {ctx}")
    return d[key]


def parse_elastic_job(doc: Dict[str, Any]) -> ElasticJobFile:
    if doc.get("kind", KIND) != KIND:
        raise ValueError(
            f"ElasticJob file: kind must be {KIND}, got {doc.get('kind')}"
        )
    meta = _req(doc, "metadata", "document")
    name = _req(meta, "name", "metadata")
    spec = _req(doc, "spec", "document")
    raw_replicas = _req(spec, "replicaSpecs", "spec")
    if not raw_replicas:
        raise ValueError("ElasticJob file: replicaSpecs is empty")

    replica_specs: Dict[str, ReplicaFileSpec] = {}
    for rtype, r in raw_replicas.items():
        r = r or {}  # `worker:` with no body parses to None
        n = int(_req(r, "replicas", f"replicaSpecs.{rtype}"))
        res = r.get("resources", {}) or {}
        replica_specs[rtype] = ReplicaFileSpec(
            replicas=n,
            min_replicas=int(r.get("minReplicas", n)),
            max_replicas=int(r.get("maxReplicas", n)),
            max_relaunch=int(r.get("maxRelaunch", 3)),
            resource=NodeResource(
                cpu=float(res.get("cpu", 0)),
                memory_mb=int(res.get("memoryMB", 0)),
                tpu_chips=int(res.get("tpuChips", 0)),
                tpu_type=str(res.get("tpuType", "")),
                tpu_topology=str(res.get("tpuTopology", "")),
            ),
        )

    tmpl = spec.get("template", {}) or {}
    ckpt = spec.get("checkpoint", {}) or {}
    return ElasticJobFile(
        name=str(name),
        replica_specs=replica_specs,
        distribution_strategy=str(
            spec.get("distributionStrategy", "AllreduceStrategy")
        ),
        node_unit=int(spec.get("nodeUnit", 1)),
        max_restarts=int(spec.get("maxRestarts", 3)),
        network_check=bool(spec.get("networkCheck", False)),
        script=str(tmpl.get("script", "")),
        script_args=[str(a) for a in (tmpl.get("args", []) or [])],
        nproc_per_node=int(tmpl.get("nprocPerNode", 1)),
        ckpt_dir=str(ckpt.get("dir", "")),
        ckpt_interval=int(ckpt.get("interval", 0)),
    )


def load_elastic_job(path: str) -> ElasticJobFile:
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"ElasticJob file {path}: not a YAML mapping")
    return parse_elastic_job(doc)


def to_job_spec(jf: ElasticJobFile):
    """ElasticJobFile -> the reconciler's :class:`JobSpec` (desired
    replica state; the CR half of the operator contract)."""
    from dlrover_tpu.scheduler.reconciler import JobSpec, ReplicaSpec

    return JobSpec(
        job_name=jf.name,
        replicas={
            rtype: ReplicaSpec(
                count=r.replicas,
                resource=r.resource,
                max_relaunch=r.max_relaunch,
            )
            for rtype, r in jf.replica_specs.items()
        },
    )


def nnodes_arg(jf: ElasticJobFile) -> str:
    w = jf.worker
    if w.min_replicas == w.max_replicas:
        return str(w.replicas)
    return f"{w.min_replicas}:{w.max_replicas}"
