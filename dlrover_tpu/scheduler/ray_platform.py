"""Ray platform: nodes as Ray actors.

Parity with reference ``scheduler/ray.py`` (``RayClient :51``) +
``master/scaler/ray_scaler.py`` (``ActorScaler :39``) + the submitter
(``client/platform/ray/ray_job_submitter.py``).  Each node is a detached
actor that runs the elastic agent with the env contract the launcher
would have provided.  Gated on the ``ray`` package unless a ``ray_mod``
is injected — tests drive the full CRUD/watch/failure-detection logic
against a fake Ray (the same pattern as GkePlatform's fake kube API).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence

from dlrover_tpu.common.constants import NodeEventType, NodeStatus
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.scheduler.platform import (
    PlatformClient,
    PlatformNode,
    PlatformNodeEvent,
    _node_name,
)


class RayPlatform(PlatformClient):
    """Each node is a detached Ray actor running the elastic agent."""

    def __init__(
        self,
        namespace: str = "dlrover_tpu",
        agent_env: Optional[Dict[str, str]] = None,
        agent_args: Optional[Sequence[str]] = None,
        poll_interval: float = 5.0,
        ray_mod=None,
    ):
        """``agent_args``: the launcher argv every node shares (e.g.
        ``["--nnodes=4", "--nproc_per_node=4", "--master_addr=H:P",
        "train.py", "--", "--steps=100"]``); per-node identity flags are
        appended by :meth:`create_node`."""
        if ray_mod is not None:
            self._ray = ray_mod
        else:  # pragma: no cover - needs the ray package
            try:
                import ray  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "RayPlatform requires the 'ray' package"
                ) from e
            self._ray = ray
            if not ray.is_initialized():
                ray.init(namespace=namespace, ignore_reinit_error=True)
        self._agent_env = dict(agent_env or {})
        self._agent_args = list(agent_args or [])
        self._poll_interval = poll_interval
        self._lock = threading.Lock()
        self._actors: Dict[str, object] = {}
        self._nodes: Dict[str, PlatformNode] = {}
        self._events: "queue.Queue[PlatformNodeEvent]" = queue.Queue()

    def _agent_actor_cls(self):
        ray = self._ray

        # max_concurrency=2: run() blocks the actor for the job's whole
        # lifetime; ping() must be served concurrently or health checks
        # would report every busy (healthy) node as dead.
        @ray.remote(max_concurrency=2)
        class AgentActor:
            def run(self, env, argv):  # pragma: no cover - inside ray
                import os

                os.environ.update(env)
                from dlrover_tpu import run as run_mod

                return run_mod.run(run_mod.parse_args(argv))

            def ping(self):
                return True

        return AgentActor

    def create_node(self, node: Node, job_name: str) -> PlatformNode:
        name = _node_name(job_name, node)
        # Detached actors outlive a crashed master; a same-named orphan
        # from the previous incarnation must be killed or the named
        # create below raises and the orphan trains invisibly forever.
        get_actor = getattr(self._ray, "get_actor", None)
        if get_actor is not None and name not in self._actors:
            try:
                orphan = get_actor(name)
            except Exception:  # noqa: BLE001 - no such actor
                orphan = None
            if orphan is not None:
                logger.warning(
                    "ray: killing orphaned actor %s from a previous "
                    "master incarnation", name,
                )
                self._ray.kill(orphan)
        # Build the agent argv FIRST (a bad conf must not leak a named
        # detached actor).  Identity flags go before the entrypoint; the
        # REAL parser finds the entrypoint boundary, so bare store_true
        # flags and space-separated values both split correctly.
        from dlrover_tpu import run as run_mod

        try:
            parsed = run_mod.parse_args(list(self._agent_args))
        except SystemExit as e:
            raise ValueError(
                f"agent_args is not a valid launcher argv: "
                f"{self._agent_args}"
            ) from e
        cut = self._agent_args.index(parsed.entrypoint)
        ident = [
            f"--job_name={job_name}",
            f"--node_rank={node.rank_index}",
            f"--node_id={node.id}",
        ]
        argv = [*self._agent_args[:cut], *ident, *self._agent_args[cut:]]
        # ray.kill returns before the GCS releases the actor name, so a
        # named create right after killing the orphan can still collide;
        # retry briefly.
        actor = None
        err = None
        for _ in range(20):
            try:
                actor = self._agent_actor_cls().options(
                    name=name, lifetime="detached"
                ).remote()
                break
            except Exception as e:  # noqa: BLE001 - name still taken
                err = e
                time.sleep(0.5)
        if actor is None:
            raise RuntimeError(
                f"could not create actor {name}: {err}"
            )
        actor.run.remote(dict(self._agent_env), argv)
        pn = PlatformNode(
            name=name,
            node_type=node.type,
            node_id=node.id,
            rank_index=node.rank_index,
            status=NodeStatus.RUNNING,
            resource=node.config_resource,
            create_time=time.time(),
        )
        with self._lock:
            self._actors[name] = actor
            self._nodes[name] = pn
        return dataclasses.replace(pn)

    def delete_node(self, name: str) -> bool:
        with self._lock:
            actor = self._actors.pop(name, None)
            pn = self._nodes.pop(name, None)
        if actor is None:
            return False
        self._ray.kill(actor)
        if pn is not None:
            pn.status = NodeStatus.DELETED
            # Deleted nodes vanish from polls; the job manager's DELETED
            # handling needs an explicit event (InMemoryPlatform parity).
            self._events.put(
                PlatformNodeEvent(
                    NodeEventType.DELETED, dataclasses.replace(pn)
                )
            )
        return True

    def list_nodes(self) -> List[PlatformNode]:
        with self._lock:
            snapshot = list(self._actors.items())
        # Fire every ping first, then resolve with ONE shared deadline —
        # serial 5s-per-dead-actor waits would stall the watch loop and
        # delay failure detection for every other node.
        refs = []
        for name, actor in snapshot:
            try:
                refs.append((name, actor.ping.remote()))
            except Exception:  # noqa: BLE001
                refs.append((name, None))
        wait = getattr(self._ray, "wait", None)
        ready = None
        if wait is not None and refs:
            live_refs = [r for _, r in refs if r is not None]
            try:
                done, _ = wait(
                    live_refs, num_returns=len(live_refs), timeout=5
                )
                ready = set(map(id, done))
            except Exception:  # noqa: BLE001
                ready = None
        out = []
        for name, ref in refs:
            if ready is not None:
                # ray.wait marks ERRORED refs ready too (a dead actor's
                # ping resolves to RayActorError immediately) — the get
                # below is what distinguishes alive from crashed, and it
                # is instant for a resolved ref.
                ok = ref is not None and id(ref) in ready
                if ok:
                    try:
                        self._ray.get(ref, timeout=1)
                    except Exception:  # noqa: BLE001
                        ok = False
            else:
                try:
                    ok = ref is not None and bool(
                        self._ray.get(ref, timeout=5)
                    )
                except Exception:  # noqa: BLE001
                    ok = False
            status = NodeStatus.RUNNING if ok else NodeStatus.FAILED
            with self._lock:
                pn = self._nodes.get(name)
                if pn is None:
                    continue  # deleted mid-listing: not a failure
                pn.status = status
                out.append(dataclasses.replace(pn))
        return out

    def watch(self, stop: threading.Event) -> Iterator[PlatformNodeEvent]:
        """Change stream: explicit delete events + status polling (Ray
        has no pod-watch analogue; the poll pings every actor, so the
        interval trades detection latency against O(actors) RPCs)."""
        seen: Dict[str, str] = {}
        while not stop.is_set():
            try:
                while True:
                    ev = self._events.get_nowait()
                    seen.pop(ev.node.name, None)
                    yield ev
            except queue.Empty:
                pass
            for pn in self.list_nodes():
                if seen.get(pn.name) != pn.status:
                    seen[pn.name] = pn.status
                    yield PlatformNodeEvent(
                        NodeEventType.MODIFIED, dataclasses.replace(pn)
                    )
            stop.wait(self._poll_interval)
