"""Ray platform: nodes as Ray actors (API-compatible stub).

Parity with reference ``scheduler/ray.py`` (``RayClient :51``) +
``master/scaler/ray_scaler.py`` (``ActorScaler :39``) + the submitter
(``client/platform/ray/ray_job_submitter.py``).  Gated on the ``ray``
package; without it the class raises at construction, keeping the factory
importable (SURVEY.md §2 #34).
"""

from __future__ import annotations

import threading
import time
from typing import Iterator, List

from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.common.node import Node
from dlrover_tpu.scheduler.platform import (
    PlatformClient,
    PlatformNode,
    PlatformNodeEvent,
    _node_name,
)


class RayPlatform(PlatformClient):  # pragma: no cover - needs ray
    """Each node is a detached Ray actor running the elastic agent."""

    def __init__(self, namespace: str = "dlrover_tpu"):
        try:
            import ray  # type: ignore
        except ImportError as e:
            raise RuntimeError("RayPlatform requires the 'ray' package") from e
        self._ray = ray
        if not ray.is_initialized():
            ray.init(namespace=namespace, ignore_reinit_error=True)
        self._actors = {}

    def create_node(self, node: Node, job_name: str) -> PlatformNode:
        ray = self._ray

        @ray.remote
        class AgentActor:
            def run(self, env):  # pragma: no cover
                import os
                import runpy

                os.environ.update(env)
                runpy.run_module("dlrover_tpu.agent", run_name="__main__")

            def ping(self):
                return True

        name = _node_name(job_name, node)
        actor = AgentActor.options(
            name=name, lifetime="detached"
        ).remote()
        self._actors[name] = actor
        return PlatformNode(
            name=name,
            node_type=node.type,
            node_id=node.id,
            rank_index=node.rank_index,
            status=NodeStatus.RUNNING,
            create_time=time.time(),
        )

    def delete_node(self, name: str) -> bool:
        actor = self._actors.pop(name, None)
        if actor is None:
            return False
        self._ray.kill(actor)
        return True

    def list_nodes(self) -> List[PlatformNode]:
        nodes = []
        for name, actor in list(self._actors.items()):
            try:
                self._ray.get(actor.ping.remote(), timeout=5)
                status = NodeStatus.RUNNING
            except Exception:
                status = NodeStatus.FAILED
            nodes.append(
                PlatformNode(
                    name=name, node_type="worker", node_id=0, rank_index=0,
                    status=status,
                )
            )
        return nodes

    def watch(self, stop: threading.Event) -> Iterator[PlatformNodeEvent]:
        from dlrover_tpu.common.constants import NodeEventType

        seen = {}
        while not stop.is_set():
            for pn in self.list_nodes():
                if seen.get(pn.name) != pn.status:
                    seen[pn.name] = pn.status
                    yield PlatformNodeEvent(NodeEventType.MODIFIED, pn)
            stop.wait(5.0)
